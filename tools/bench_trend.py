#!/usr/bin/env python
"""bench_trend — the per-row benchmark trajectory + regression gate.

Reads every committed ``BENCH_r*.json`` AND ``SERVING_r*.json`` at the repo
root (plus, with ``--fresh``, an uncommitted run's ``bench_results.json``),
normalizes the two artifact shapes the repo has accumulated — the raw
driver capture (``{cmd, parsed, tail, ...}``, r01–r05) and the direct bench
payload (``{metric, configs, ...}``, r06+) — and prints each config row's
rate + MFU trajectory across releases.

Rows carry one of two RATE metrics and the trend tracks either, never
mixing them: training and request-granularity serving rows report
``samples_per_sec_per_chip``; autoregressive decode rows (``tools/loadgen
--decode``, tpuddp/serving/decode/) report ``tokens_per_sec`` (rendered
with a ``t/s`` suffix). A row name that appears under both metrics — e.g.
``closed_loop`` in a request-serving and a decode artifact — is judged per
metric, so a decode row is never regressed against a request-rate best.

Regression rule: each CANDIDATE (the ``--fresh`` artifact when given, else
the newest committed artifact of each family — BENCH and SERVING) is
compared row by row against the BEST earlier value of the same row name
and rate metric **on the same device** (a CPU-rung run must never be
judged against a TPU row of the same name). Any candidate row whose rate
falls more than ``--threshold`` (default 10%) below its historical best
exits nonzero — wired into ``tools/run_full_gate.py`` so a perf regression
fails the gate like a schema drift does.

Usage:
    python tools/bench_trend.py                       # committed trajectory
    python tools/bench_trend.py --fresh bench_results.json
    python tools/bench_trend.py --threshold 0.05
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def normalize(path):
    """Extract ``(tag, device, configs)`` from either artifact shape, or
    None when the file holds no per-config rows (e.g. r01's summary-only
    capture — reported, not fatal)."""
    tag = os.path.splitext(os.path.basename(path))[0]
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_trend: skipping unreadable {path}: {e}",
              file=sys.stderr)
        return None
    if not isinstance(obj, dict):
        return None
    payload = None
    if isinstance(obj.get("configs"), dict):
        payload = obj
    elif isinstance(obj.get("parsed"), dict) and isinstance(
        obj["parsed"].get("configs"), dict
    ):
        payload = obj["parsed"]
    else:
        # driver capture whose parse failed: the payload is the LAST
        # stdout line of the tail (bench.py's parseable-summary contract)
        tail = obj.get("tail")
        if isinstance(tail, list):
            tail = "\n".join(tail)
        if isinstance(tail, str):
            for line in reversed(tail.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        parsed = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(parsed, dict) and isinstance(
                        parsed.get("configs"), dict
                    ):
                        payload = parsed
                    break
    if payload is None:
        return None
    configs = {
        name: row
        for name, row in payload["configs"].items()
        if isinstance(row, dict)
    }
    if not configs:
        return None
    return tag, payload.get("device") or "unknown", configs


_FAMILIES = ("BENCH_r*.json", "SERVING_r*.json", "MULTICHIP_r*.json")
# MULTICHIP rows: r01-r05 are raw driver captures with no per-config rows
# (normalize() reports + skips them); r06+ carry the 2-D-mesh proving rows
# (tools/bench_mesh.py — tokens/sec + per-chip param-byte cut) and are
# judged like every other family.


def load_artifacts(fresh=None, repo=_REPO):
    """Committed BENCH_r*.json + SERVING_r*.json (release order within each
    family) + the optional fresh run. Returns ``(artifacts, candidates)``:
    with ``--fresh`` the fresh artifact is the sole candidate, otherwise the
    newest committed artifact of EACH family is judged."""
    artifacts = []
    candidates = []
    for pattern in _FAMILIES:
        family = []
        for path in sorted(glob.glob(os.path.join(repo, pattern))):
            norm = normalize(path)
            if norm is None:
                print(f"bench_trend: {os.path.basename(path)} carries no "
                      "config rows (skipped)")
                continue
            family.append(norm)
        artifacts.extend(family)
        if family and not fresh:
            candidates.append(family[-1])
    if fresh:
        norm = normalize(fresh)
        if norm is None:
            print(f"bench_trend: --fresh {fresh} carries no config rows",
                  file=sys.stderr)
            return artifacts, []
        norm = (f"fresh:{norm[0]}", norm[1], norm[2])
        artifacts.append(norm)
        candidates = [norm]
    return artifacts, candidates


def _num(row, key):
    v = row.get(key)
    return float(v) if isinstance(v, (int, float)) else None


# The two rate metrics a config row may carry (schema._BENCH_ROW_RATES):
# samples/sec/chip for training + request-granularity serving rows,
# tokens/sec for autoregressive decode rows. Trend cells and regression
# comparisons are always per (row name, device, rate metric).
_RATE_KEYS = ("samples_per_sec_per_chip", "tokens_per_sec")


def _rate(row):
    """``(key, value)`` of the row's rate metric, or ``(None, None)``."""
    for key in _RATE_KEYS:
        v = _num(row, key)
        if v is not None:
            return key, v
    return None, None


def print_trajectory(artifacts) -> None:
    """Per-row rate (and MFU where known) across releases. Decode rows show
    their tokens/sec with a ``t/s`` suffix so the two rate families never
    read as one number."""
    rows = []
    seen = []
    for _tag, device, configs in artifacts:
        for name in configs:
            if (device, name) not in seen:
                seen.append((device, name))
    header = ["row", "device"] + [tag for tag, _, _ in artifacts]
    for device, name in seen:
        cells = [name[:44], device]
        for _tag, dev, configs in artifacts:
            row = configs.get(name) if dev == device else None
            if row is None:
                cells.append("-")
                continue
            key, rate = _rate(row)
            mfu = _num(row, "mfu")
            if rate is None:
                cell = "?"
            else:
                cell = f"{rate:,.0f}"
                if key == "tokens_per_sec":
                    cell += "t/s"
            if mfu is not None:
                cell += f"/{mfu:.3f}"
            cells.append(cell)
        rows.append(cells)
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows else len(header[i])
        for i in range(len(header))
    ]
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    print("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    print("(cells: samples/sec/chip — or tokens/sec marked 't/s' — "
          "'/MFU' where recorded)")


def check_regressions(artifacts, candidate, threshold: float):
    """Candidate rows vs their same-device, same-rate-metric historical
    best. Returns the list of regression description strings (empty =
    pass)."""
    cand_tag, cand_device, cand_configs = candidate
    history = [a for a in artifacts if a[0] != cand_tag]
    regressions = []
    for name, row in cand_configs.items():
        rate_key, rate = _rate(row)
        if rate is None:
            continue
        best = None
        best_tag = None
        for tag, device, configs in history:
            if device != cand_device:
                continue
            prev = configs.get(name)
            if prev is None:
                continue
            prev_rate = _num(prev, rate_key)
            if prev_rate is not None and (best is None or prev_rate > best):
                best, best_tag = prev_rate, tag
        if best is None or best <= 0:
            continue
        drop = 1.0 - rate / best
        if drop > threshold:
            unit = (
                "tokens/s" if rate_key == "tokens_per_sec"
                else "samples/s/chip"
            )
            regressions.append(
                f"{name!r} on {cand_device}: {rate:,.1f} {unit} in "
                f"{cand_tag} is {drop * 100:.1f}% below the best "
                f"{best:,.1f} ({best_tag}) — over the "
                f"{threshold * 100:.0f}% floor"
            )
    return regressions


# ------------------------------------------------------------ TUNE family --
# TUNE_r*.json (tools/autotune.py): per-rule predicted-vs-measured deltas,
# a different shape from the rate families — tracked per (rule, metric,
# device), never mixed into the rate trend.


def load_tune_artifacts(repo=_REPO):
    """Committed TUNE_r*.json artifacts in release order:
    ``(tag, device, results)`` with only well-formed result rows kept."""
    artifacts = []
    for path in sorted(glob.glob(os.path.join(repo, "TUNE_r*.json"))):
        tag = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_trend: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        if not isinstance(obj, dict) or obj.get("type") != "tune_report":
            continue
        rows = [
            r for r in obj.get("results") or []
            if isinstance(r, dict) and isinstance(r.get("rule"), str)
        ]
        if rows:
            artifacts.append((tag, obj.get("device") or "unknown", rows))
    return artifacts


def print_tune_trend(tune_artifacts) -> None:
    """Per-rule predicted -> measured trajectory across TUNE releases.
    A cell reads ``+50.0->+48.2`` (endorsed) or ``+14.0->-3.1 !`` (probe
    REFUSED endorsement)."""
    seen = []
    for _tag, device, rows in tune_artifacts:
        for r in rows:
            key = (device, r["rule"], r.get("metric"))
            if key not in seen:
                seen.append(key)
    header = (["rule", "metric", "device"]
              + [tag for tag, _, _ in tune_artifacts])
    out = []
    for device, rule, metric in seen:
        cells = [rule[:32], str(metric)[:24], device]
        for _tag, dev, rows in tune_artifacts:
            row = next(
                (r for r in rows
                 if dev == device and r["rule"] == rule
                 and r.get("metric") == metric),
                None,
            )
            if row is None:
                cells.append("-")
                continue
            pred = row.get("predicted_delta_pct")
            meas = row.get("measured_delta_pct")
            pred_s = f"{pred:+.1f}" if isinstance(pred, (int, float)) else "?"
            meas_s = f"{meas:+.1f}" if isinstance(meas, (int, float)) else "?"
            cells.append(
                f"{pred_s}->{meas_s}" + ("" if row.get("endorsed") else " !")
            )
        out.append(cells)
    widths = [
        max(len(header[i]), *(len(r[i]) for r in out)) if out
        else len(header[i])
        for i in range(len(header))
    ]
    print("\ntune trajectory (predicted->measured improvement %, "
          "'!' = endorsement refused):")
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    print("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for r in out:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))


def check_tune_regressions(tune_artifacts):
    """A rule the probe ENDORSED in an earlier release but now refuses —
    same (rule, metric, device), judged per metric so a rule probed on a
    new metric never regresses against an old one. Returns description
    strings (empty = pass)."""
    if len(tune_artifacts) < 2:
        return []
    cand_tag, cand_device, cand_rows = tune_artifacts[-1]
    history = tune_artifacts[:-1]
    regressions = []
    for row in cand_rows:
        if row.get("endorsed"):
            continue
        for tag, device, rows in history:
            if device != cand_device:
                continue
            prev = next(
                (r for r in rows
                 if r["rule"] == row["rule"]
                 and r.get("metric") == row.get("metric")
                 and r.get("endorsed")),
                None,
            )
            if prev is not None:
                meas = row.get("measured_delta_pct")
                meas_s = (
                    f"{meas:+.1f}%" if isinstance(meas, (int, float))
                    else "unmeasured"
                )
                regressions.append(
                    f"tune rule {row['rule']!r} on {row.get('metric')} "
                    f"({cand_device}): endorsed in {tag}, now {meas_s} in "
                    f"{cand_tag} — the probe refused endorsement"
                )
                break
    return regressions


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Per-row bench trajectory across committed BENCH_r*.json "
        "artifacts, failing on a >threshold regression of any best row.",
    )
    parser.add_argument("--fresh", default=None, metavar="PATH",
                        help="an uncommitted bench_results.json to judge as "
                        "the candidate (default: the newest committed "
                        "artifact)")
    parser.add_argument("--threshold", type=float, default=0.10,
                        help="allowed fractional drop vs the historical best "
                        "(default 0.10)")
    parser.add_argument("--repo", default=_REPO, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    artifacts, candidates = load_artifacts(args.fresh, repo=args.repo)
    tune_artifacts = load_tune_artifacts(repo=args.repo)
    if tune_artifacts:
        print_tune_trend(tune_artifacts)
        print()
    else:
        print("bench_trend: no TUNE_r*.json artifacts with result rows — "
              "no tune trajectory to report (not a failure)")
    if not artifacts:
        # a fresh clone (no committed BENCH_r*/SERVING_r* artifacts yet) has
        # no trajectory to regress against — an empty gate, not a failure
        print("bench_trend: no BENCH_r*/SERVING_r*.json artifacts with "
              "config rows found — nothing to compare, nothing to regress "
              "(exit 0)")
        return 0
    if not candidates:
        # --fresh pointed at an artifact with no config rows: report the
        # committed trajectory, but there is no candidate to judge
        print_trajectory(artifacts)
        print(f"bench_trend: --fresh {args.fresh} carries no config rows — "
              "no candidate to judge (exit 0)")
        return 0
    print_trajectory(artifacts)
    regressions = []
    for candidate in candidates:
        regressions += check_regressions(artifacts, candidate, args.threshold)
    if not args.fresh:
        # tune regressions only judge committed artifacts against each
        # other — a --fresh bench candidate says nothing about tuning
        regressions += check_tune_regressions(tune_artifacts)
    if regressions:
        for r in regressions:
            print(f"REGRESSION: {r}", file=sys.stderr)
        return 1
    print(f"bench_trend: no row of candidate(s) "
          f"{', '.join(c[0] for c in candidates)} regressed more than "
          f"{args.threshold * 100:.0f}% against its same-device best")
    return 0


if __name__ == "__main__":
    sys.exit(main())
