#!/usr/bin/env python
"""bench_trend — the per-row benchmark trajectory + regression gate.

Reads every committed ``BENCH_r*.json`` at the repo root (plus, with
``--fresh``, an uncommitted run's ``bench_results.json``), normalizes the
two artifact shapes the repo has accumulated — the raw driver capture
(``{cmd, parsed, tail, ...}``, r01–r05) and the direct bench payload
(``{metric, configs, ...}``, r06+) — and prints each config row's
samples/sec + MFU trajectory across releases.

Regression rule: the CANDIDATE (the ``--fresh`` artifact when given, else
the newest committed one) is compared row by row against the BEST earlier
value of the same row name **on the same device** (a CPU-rung run must
never be judged against a TPU row of the same name). Any candidate row
whose ``samples_per_sec_per_chip`` falls more than ``--threshold`` (default
10%) below its historical best exits nonzero — wired into
``tools/run_full_gate.py`` so a perf regression fails the gate like a
schema drift does.

Usage:
    python tools/bench_trend.py                       # committed trajectory
    python tools/bench_trend.py --fresh bench_results.json
    python tools/bench_trend.py --threshold 0.05
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def normalize(path):
    """Extract ``(tag, device, configs)`` from either artifact shape, or
    None when the file holds no per-config rows (e.g. r01's summary-only
    capture — reported, not fatal)."""
    tag = os.path.splitext(os.path.basename(path))[0]
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_trend: skipping unreadable {path}: {e}",
              file=sys.stderr)
        return None
    if not isinstance(obj, dict):
        return None
    payload = None
    if isinstance(obj.get("configs"), dict):
        payload = obj
    elif isinstance(obj.get("parsed"), dict) and isinstance(
        obj["parsed"].get("configs"), dict
    ):
        payload = obj["parsed"]
    else:
        # driver capture whose parse failed: the payload is the LAST
        # stdout line of the tail (bench.py's parseable-summary contract)
        tail = obj.get("tail")
        if isinstance(tail, list):
            tail = "\n".join(tail)
        if isinstance(tail, str):
            for line in reversed(tail.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        parsed = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(parsed, dict) and isinstance(
                        parsed.get("configs"), dict
                    ):
                        payload = parsed
                    break
    if payload is None:
        return None
    configs = {
        name: row
        for name, row in payload["configs"].items()
        if isinstance(row, dict)
    }
    if not configs:
        return None
    return tag, payload.get("device") or "unknown", configs


def load_artifacts(fresh=None, repo=_REPO):
    """Committed BENCH_r*.json (release order) + the optional fresh run."""
    artifacts = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        norm = normalize(path)
        if norm is None:
            print(f"bench_trend: {os.path.basename(path)} carries no config "
                  "rows (skipped)")
            continue
        artifacts.append(norm)
    if fresh:
        norm = normalize(fresh)
        if norm is None:
            print(f"bench_trend: --fresh {fresh} carries no config rows",
                  file=sys.stderr)
            return artifacts, None
        norm = (f"fresh:{norm[0]}", norm[1], norm[2])
        artifacts.append(norm)
    return artifacts, artifacts[-1] if artifacts else None


def _num(row, key):
    v = row.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def print_trajectory(artifacts) -> None:
    """Per-row samples/sec (and MFU where known) across releases."""
    rows = []
    seen = []
    for _tag, device, configs in artifacts:
        for name in configs:
            if (device, name) not in seen:
                seen.append((device, name))
    header = ["row", "device"] + [tag for tag, _, _ in artifacts]
    for device, name in seen:
        cells = [name[:44], device]
        for _tag, dev, configs in artifacts:
            row = configs.get(name) if dev == device else None
            if row is None:
                cells.append("-")
                continue
            sps = _num(row, "samples_per_sec_per_chip")
            mfu = _num(row, "mfu")
            cell = f"{sps:,.0f}" if sps is not None else "?"
            if mfu is not None:
                cell += f"/{mfu:.3f}"
            cells.append(cell)
        rows.append(cells)
    widths = [
        max(len(header[i]), *(len(r[i]) for r in rows)) if rows else len(header[i])
        for i in range(len(header))
    ]
    print("  ".join(h.ljust(w) for h, w in zip(header, widths)))
    print("-" * (sum(widths) + 2 * (len(widths) - 1)))
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    print("(cells: samples/sec/chip, '/MFU' where recorded)")


def check_regressions(artifacts, candidate, threshold: float):
    """Candidate rows vs their same-device historical best. Returns the
    list of regression description strings (empty = pass)."""
    cand_tag, cand_device, cand_configs = candidate
    history = [a for a in artifacts if a[0] != cand_tag]
    regressions = []
    for name, row in cand_configs.items():
        sps = _num(row, "samples_per_sec_per_chip")
        if sps is None:
            continue
        best = None
        best_tag = None
        for tag, device, configs in history:
            if device != cand_device:
                continue
            prev = configs.get(name)
            if prev is None:
                continue
            prev_sps = _num(prev, "samples_per_sec_per_chip")
            if prev_sps is not None and (best is None or prev_sps > best):
                best, best_tag = prev_sps, tag
        if best is None or best <= 0:
            continue
        drop = 1.0 - sps / best
        if drop > threshold:
            regressions.append(
                f"{name!r} on {cand_device}: {sps:,.1f} samples/s/chip in "
                f"{cand_tag} is {drop * 100:.1f}% below the best "
                f"{best:,.1f} ({best_tag}) — over the "
                f"{threshold * 100:.0f}% floor"
            )
    return regressions


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Per-row bench trajectory across committed BENCH_r*.json "
        "artifacts, failing on a >threshold regression of any best row.",
    )
    parser.add_argument("--fresh", default=None, metavar="PATH",
                        help="an uncommitted bench_results.json to judge as "
                        "the candidate (default: the newest committed "
                        "artifact)")
    parser.add_argument("--threshold", type=float, default=0.10,
                        help="allowed fractional drop vs the historical best "
                        "(default 0.10)")
    parser.add_argument("--repo", default=_REPO, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    artifacts, candidate = load_artifacts(args.fresh, repo=args.repo)
    if not artifacts:
        # a fresh clone (no committed BENCH_r*.json yet) has no trajectory
        # to regress against — that is an empty gate, not a failure
        print("bench_trend: no BENCH_r*.json artifacts with config rows "
              "found — nothing to compare, nothing to regress (exit 0)")
        return 0
    if candidate is None:
        # --fresh pointed at an artifact with no config rows: report the
        # committed trajectory, but there is no candidate to judge
        print_trajectory(artifacts)
        print(f"bench_trend: --fresh {args.fresh} carries no config rows — "
              "no candidate to judge (exit 0)")
        return 0
    print_trajectory(artifacts)
    regressions = check_regressions(artifacts, candidate, args.threshold)
    if regressions:
        for r in regressions:
            print(f"REGRESSION: {r}", file=sys.stderr)
        return 1
    print(f"bench_trend: no row of candidate {candidate[0]} regressed more "
          f"than {args.threshold * 100:.0f}% against its same-device best")
    return 0


if __name__ == "__main__":
    sys.exit(main())
