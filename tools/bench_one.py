"""Focused single-config bench: AlexNet b128 bf16-opt s2d scan-fused (K=16),
with optional jax.profiler trace. Mirrors bench.py's methodology."""
import argparse, os, sys, time
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

p = argparse.ArgumentParser()
p.add_argument("--trace", default=None)
p.add_argument("--batch", type=int, default=128)
p.add_argument("--scan", type=int, default=16)
p.add_argument("--steps", type=int, default=96)
p.add_argument("--model", default="alexnet_s2d")
p.add_argument("--size", type=int, default=224)
p.add_argument("--opt-dtype", default="bfloat16")
p.add_argument("--remat", action="store_true")
args = p.parse_args()

import jax, jax.numpy as jnp
from tpuddp import nn, optim
from tpuddp.models import load_model
from tpuddp.data.transforms import make_train_augment
from tpuddp.parallel import make_mesh
from tpuddp.parallel.ddp import DistributedDataParallel
from tpuddp.training.step import stack_batches

PEAK = 197e12

model = load_model(args.model, 10)
augment = make_train_augment(size=args.size if args.size else None, compute_dtype=jnp.bfloat16)
devices = jax.devices()
mesh = make_mesh(devices)
opt = optim.Adam(1e-3, state_dtype=args.opt_dtype or None)
ddp = DistributedDataParallel(model, opt, nn.CrossEntropyLoss(), mesh=mesh,
                              mode="shard_map", augment=augment, remat=args.remat)
in_shape = (32, 32, 3)
model_in = augment(jax.random.key(0), jnp.zeros((1,) + in_shape, np.uint8)).shape[1:]
state = ddp.init_state(jax.random.key(0), jnp.zeros((1,) + tuple(model_in)))

rng = np.random.RandomState(0)
gb = args.batch * len(devices)
x = rng.randint(0, 256, (gb,) + in_shape).astype(np.uint8)
y = rng.randint(0, 10, gb).astype(np.int32)
w = np.ones(gb, np.float32)
batch = ddp.shard((x, y, w))
stacked = ddp.shard_stacked(stack_batches([tuple(np.asarray(b) for b in batch)] * args.scan))

state_box = [state]
def run(steps):
    outer = max(1, steps // args.scan)
    m = None
    for _ in range(outer):
        state_box[0], m = ddp.train_step_many(state_box[0], stacked)
    loss = float(np.sum(np.asarray(m["loss_sum"])))
    assert np.isfinite(loss)
    return outer * args.scan

run(args.scan); run(args.scan)

# flops probe
def program_flops(jitted, *a):
    try:
        c = jitted.lower(*a).compile().cost_analysis()
        if isinstance(c, (list, tuple)): c = c[0]
        f = float(c.get("flops", 0.0))
        return f if f > 0 else None
    except Exception as e:
        print("cost fail", e, file=sys.stderr); return None

bx, by, bw = batch
f_single = program_flops(jax.jit(lambda s,a,b,c: ddp.train_step(s,(a,b,c))), state_box[0], bx, by, bw)

if args.trace:
    jax.profiler.start_trace(args.trace)
t0 = time.perf_counter()
steps = run(args.steps)
dt = time.perf_counter() - t0
if args.trace:
    jax.profiler.stop_trace()
ms = dt / steps * 1e3
mfu = f_single / (ms / 1e3) / PEAK if f_single else float("nan")
print(f"{args.model} b{args.batch} K={args.scan}: {steps*args.batch/dt:,.0f} samples/s  {ms:.3f} ms/step  MFU {100*mfu:.2f}%")
