#!/usr/bin/env python
"""tpuddp_inspect — validate and summarize tpuddp telemetry artifacts.

Works on both machine-readable artifacts the framework writes:

- ``history.jsonl`` (a run's typed record stream: ``run_meta`` / ``epoch``
  / ``step_stats`` / ``event`` / ``serving_stats`` / ``decode_stats``,
  tpuddp/observability/schema.py) — prints the run header (including the
  schema-v6 decode provenance block for autoregressive runs), a per-epoch
  table with step-time percentiles, serving/decode SLO window tables
  (tokens/sec, TTFT, ITL, KV occupancy for decode), the event timeline,
  and the gradient-comm byte savings a compressed hook achieved;
- ``bench_results.json`` (the bench harness's full per-config payload);
- ``flightrec_<reason>.json`` (the crash flight recorder's post-mortem
  sidecar, tpuddp/observability/flight.py) — validates the ring contents
  against the same per-record schema and pretty-prints the last windows,
  epochs, and event timeline the crashed run saw.

An elastically-resumed history (several ``run_meta`` headers back to back)
attributes every epoch row to the header that OWNS it: the per-epoch table
gains a ``run`` column and the grad-comm savings line uses only the latest
run segment, so pre- and post-resume worlds never mix in one figure.

Checkpoint subcommands (numpy, no jax — both run on analysis hosts):

- ``ckpt <file-or-dir>`` — summarize a format-v3 checkpoint: the recorded
  ``(data, model)`` topology, per-leaf placement tags, shard-tagged flat
  leaves, reshard provenance, and the sha256 manifest status. Pointed at a
  run dir it lists every ``ckpt_*.npz`` (+ stale ``.tmp`` debris count)
  and summarizes the newest.
- ``reshard <src> --to data=D,model=M [--out PATH]`` — the offline
  cross-topology reshaper (tpuddp/training/reshard.py): rewrite a
  checkpoint saved on one mesh shape for another, atomically, with a fresh
  manifest — what ``training.reshard_on_mismatch: true`` does at load
  time, runnable before the relaunch instead.

Advisor subcommand (pure python — the whole CLI runs without jax):

- ``tune <run_dir>`` — the offline evidence engine
  (tpuddp/observability/advisor.py): parse the run's history, traces, and
  writer sidecars into typed evidence and print knob recommendations with
  per-rule evidence citations + predicted deltas. ``--emit PATH`` writes
  the tuned ``$TPUDDP_TUNE_OVERLAY`` payload; ``--json`` is the
  machine-readable report. Read-only: inspecting a run never changes it.
  TUNE_r*.json probe artifacts (tools/autotune.py) validate and summarize
  through the bare-path mode like every other artifact.

Usage:
    python tools/tpuddp_inspect.py <path> [--validate] [--events]
    python tools/tpuddp_inspect.py ckpt <file-or-dir>
    python tools/tpuddp_inspect.py reshard <src> --to data=D,model=M
    python tools/tpuddp_inspect.py tune <run_dir> [--emit PATH] [--json]

``--validate`` checks the schema only (exit 0 valid / 1 invalid, errors on
stderr) — the mode ``tools/run_full_gate.py`` runs over the dryrun history
and the bench artifact, so schema drift fails a gate instead of corrupting
downstream consumers. No flags: validate AND print the summary.

The file kind is detected by content (a JSON-lines stream vs one JSON
object), not by name, so renamed artifacts still inspect.
"""

from __future__ import annotations

import argparse
import collections
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_schema():
    """Load tpuddp/observability/schema.py by file path — NOT through the
    tpuddp package, whose observability __init__ imports jax/numpy. The
    validators are pure python, so this CLI stays usable on analysis hosts
    where the accelerator runtime is absent."""
    path = os.path.join(_REPO, "tpuddp", "observability", "schema.py")
    spec = importlib.util.spec_from_file_location("_tpuddp_inspect_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_reshard():
    """Load tpuddp/training/reshard.py by file path — numpy + stdlib only,
    same rationale as _load_schema: the checkpoint subcommands must work
    where the accelerator runtime is absent."""
    path = os.path.join(_REPO, "tpuddp", "training", "reshard.py")
    spec = importlib.util.spec_from_file_location(
        "_tpuddp_inspect_reshard", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_advisor():
    """Load tpuddp/observability/advisor.py by file path (pure stdlib —
    the evidence engine reads artifacts, never the runtime), so the
    ``tune`` subcommand works on analysis hosts without jax."""
    path = os.path.join(_REPO, "tpuddp", "observability", "advisor.py")
    spec = importlib.util.spec_from_file_location(
        "_tpuddp_inspect_advisor", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_integrity():
    """tpuddp/resilience/integrity.py by file path (stdlib-only module)."""
    path = os.path.join(_REPO, "tpuddp", "resilience", "integrity.py")
    spec = importlib.util.spec_from_file_location(
        "_tpuddp_inspect_integrity", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _detect_kind(path: str) -> str:
    """'bench' (ONE JSON object with metric+configs — possibly
    pretty-printed across lines), 'flight' (one object stamped
    type=flight_recording — the crash post-mortem sidecar), 'trace' (one
    object with traceEvents + a tpuddp provenance block — the causal
    tracing plane's Chrome-trace artifact), or 'history' (a JSONL record
    stream, which fails whole-file json.load with 'Extra data' beyond one
    record)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except ValueError:
        return "history"
    if isinstance(obj, dict) and obj.get("type") == "flight_recording":
        return "flight"
    if isinstance(obj, dict) and "traceEvents" in obj:
        return "trace"
    if isinstance(obj, dict) and obj.get("type") == "tune_report":
        return "tune"
    if isinstance(obj, dict) and "configs" in obj and "metric" in obj:
        return "bench"
    return "history"


def _read_history(path: str):
    records = []
    with open(path) as f:
        for line in f:
            if line.strip():
                try:
                    records.append(json.loads(line))
                except ValueError:
                    records.append({"type": "<unparseable>"})
    return records


def _writer_sidecars(run_dir: str):
    """Every parseable ``*.writer.json`` under ``run_dir`` (the async
    snapshot writer's per-publish statistics sidecars), recursive so
    peer_ckpt/ spill copies count too."""
    import glob as _glob

    out = []
    pattern = os.path.join(_glob.escape(run_dir), "**", "*.writer.json")
    for p in sorted(_glob.glob(pattern, recursive=True)):
        try:
            with open(p) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(payload, dict):
            out.append(payload)
    return out


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _print_table(rows, headers):
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    line = "  ".join(h.rjust(w) for h, w in zip(headers, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(c.rjust(w) for c, w in zip(r, widths)))


def summarize_history(path: str) -> None:
    records = _read_history(path)
    metas = [r for r in records if r.get("type") == "run_meta"]
    # attribute every row to the run_meta header that OWNS it (the newest
    # header ABOVE it in the stream): an elastically-resumed history holds
    # several runs back to back, and a summary mixing their worlds — or
    # computing byte savings from the newest header over the oldest run's
    # epochs — reads as one run that never happened.
    run_idx = -1
    epochs, epoch_runs = [], []
    for r in records:
        if r.get("type") == "run_meta":
            run_idx += 1
        elif r.get("type") == "epoch":
            epochs.append(r)
            epoch_runs.append(max(run_idx, 0))
    # legacy (pre-schema) histories: epoch rows are the ones with losses
    if not epochs:
        epochs = [r for r in records if "train_loss" in r]
        epoch_runs = [0] * len(epochs)
    latest_epochs = [
        e for e, ri in zip(epochs, epoch_runs) if ri == max(run_idx, 0)
    ]
    events = [r for r in records if r.get("type") == "event" or (
        "type" not in r and "event" in r)]
    steps = [r for r in records if r.get("type") == "step_stats"]
    serving = [r for r in records if r.get("type") == "serving_stats"]
    decode = [r for r in records if r.get("type") == "decode_stats"]

    if metas:
        m = metas[-1]
        print(f"run_meta ({len(metas)} header(s); newest):")
        for k in (
            "api", "model", "dataset", "config_hash", "mesh_shape",
            # the v8 2-D mesh block: data/model axis widths + the TP
            # rule-table hash when the model axis is real
            "mesh",
            "world_size", "process_count", "device_kind", "jax_version",
            "tpuddp_version", "comm_hook", "comm_topology", "comm_density",
            "scan_steps", "grad_accumulation", "step_stats_every",
            # serving run_meta fields (api == "serving")
            "num_replicas", "max_batch_size", "max_queue_depth",
            "per_tenant_quota", "batch_timeout_ms", "buckets", "input_shape",
            "restored_epoch", "checkpoint_dir",
            # elastic + live-plane provenance (schema v5)
            "resumed_from_world", "observability",
        ):
            if m.get(k) is not None:
                print(f"  {k:>20}: {m[k]}")
        guard = m.get("guard")
        if isinstance(guard, dict) and guard.get("enabled"):
            print(f"  {'guard':>20}: {guard}")
        # decode provenance (required since schema v6; null = not an
        # autoregressive run): the KV-pool geometry + sampling contract
        dec = m.get("decode")
        if isinstance(dec, dict):
            geom = (
                f"{dec.get('kv_blocks')}x{dec.get('kv_block_size')} KV "
                f"blocks, {dec.get('max_slots')} slots, max_seq_len "
                f"{dec.get('max_seq_len')}"
            )
            print(f"  {'decode':>20}: model={dec.get('model')} "
                  f"vocab={dec.get('vocab_size')} {geom}")
            print(f"  {'':>20}  temperature={dec.get('temperature')} "
                  f"stop_token={dec.get('stop_token')} "
                  f"prefill_buckets={dec.get('prefill_buckets')}")
        # survivability provenance (required since schema v7; null = not a
        # serving writer): the deadline/probation/retry knob block
        sur = m.get("survivability")
        if isinstance(sur, dict):
            print(f"  {'survivability':>20}: "
                  f"request_ttl_s={sur.get('request_ttl_s')} "
                  f"max_recoveries={sur.get('max_recoveries')} "
                  f"recovery_attempts={sur.get('recovery_attempts')} "
                  f"retry_budget={sur.get('retry_budget')}")
        # comm provenance (required since schema v10; null = meshless /
        # serving header): the overlap sub-block says whether the backward
        # issued its collectives per segment and into how many segments
        comm = m.get("comm")
        if isinstance(comm, dict):
            ov = comm.get("overlap") or {}
            line = (f"  {'comm.overlap':>20}: enabled={ov.get('enabled')} "
                    f"segments={ov.get('segments')}")
            if ov.get("reason"):
                line += f" ({ov['reason']})"
            print(line)
    else:
        print("run_meta: MISSING (pre-schema history?)")

    if epochs:
        multi_run = len(metas) > 1
        if multi_run:
            print(f"\nepochs ({len(epochs)} across {len(metas)} runs; "
                  f"'run' column = owning header, newest is "
                  f"{len(metas) - 1}):")
        else:
            print(f"\nepochs ({len(epochs)}):")
        rows = []
        for e, ri in zip(epochs, epoch_runs):
            row = [
                str(e.get("epoch")),
                _fmt(e.get("train_loss")),
                _fmt(e.get("test_loss")),
                _fmt(e.get("test_accuracy"), 2),
                _fmt(e.get("epoch_time_s"), 1),
                _fmt(e.get("samples_per_sec"), 0),
                _fmt(e.get("step_time_ms_p50"), 2),
                _fmt(e.get("step_time_ms_p95"), 2),
                _fmt(e.get("step_time_ms_p99"), 2),
                _fmt(e.get("mfu_p50")),
                str(e.get("skipped_steps_epoch", 0) or 0),
            ]
            if multi_run:
                row.insert(0, str(ri))
            rows.append(row)
        headers = [
            "ep", "train", "test", "acc%", "t(s)", "sps",
            "p50ms", "p95ms", "p99ms", "mfu50", "skip",
        ]
        if multi_run:
            headers.insert(0, "run")
        _print_table(rows, headers)
        if steps:
            line = (f"\nstep_stats windows: {len(steps)} "
                    f"(finest p99 {max(s.get('step_time_ms_p99') or 0 for s in steps):.2f} ms, "
                    f"window size {steps[0].get('steps')})")
            # pipeline occupancy (schema v3): total host stall across windows
            # plus the deepest staged/in-flight queues any window saw
            stalls = [s.get("host_stall_ms") for s in steps]
            if any(v is not None for v in stalls):
                total_stall = sum(v or 0 for v in stalls)
                line += (
                    f"\npipeline occupancy: host stall {total_stall:.1f} ms total "
                    f"(worst window {max(v or 0 for v in stalls):.1f} ms), "
                    f"staging depth <= {max(s.get('staging_queue_depth') or 0 for s in steps)}, "
                    f"in-flight <= {max(s.get('inflight_depth') or 0 for s in steps)}"
                )
            print(line)
        host_stall_epoch = [e.get("host_stall_ms") for e in epochs]
        if any(v for v in host_stall_epoch):
            print(f"host stall per epoch (ms): "
                  f"{[round(v, 1) for v in host_stall_epoch if v is not None]}")

    # async-writer sidecar rollup: every ckpt_*.npz.writer.json beside the
    # history (the snapshot engine's per-publish statistics — the same
    # sidecar `ckpt` prints next to the v4 cursor, aggregated run-wide
    # here so backlog shows up without opening each checkpoint)
    sidecars = _writer_sidecars(os.path.dirname(os.path.abspath(path)))
    if sidecars:
        snaps = sum(int(w.get("snapshots") or 0) for w in sidecars)
        skipped = sum(int(w.get("skipped_queue_full") or 0) for w in sidecars)
        write_s = sum(float(w.get("write_s") or 0.0) for w in sidecars)
        total_b = sum(int(w.get("bytes") or 0) for w in sidecars)
        n_async = sum(1 for w in sidecars if w.get("async"))
        line = (f"\nsnapshot writer: {len(sidecars)} sidecar(s) "
                f"({n_async} async), {snaps} snapshot(s), "
                f"{skipped} skipped_queue_full, "
                f"{write_s:.2f} s writing, {total_b:,} B")
        if skipped:
            line += "  <- backlog: writer dropped snapshots (queue full)"
        print(line)

    if serving:
        print(f"\nserving_stats windows ({len(serving)}):")
        rows = []
        for s in serving:
            rows.append([
                str(s.get("window")),
                str(s.get("requests")),
                str(s.get("completed")),
                str(s.get("rejected")),
                _fmt(s.get("queue_ms_p50"), 2),
                _fmt(s.get("device_ms_p50"), 2),
                _fmt(s.get("e2e_ms_p50"), 2),
                _fmt(s.get("e2e_ms_p95"), 2),
                _fmt(s.get("e2e_ms_p99"), 2),
                _fmt(s.get("throughput_rps"), 0),
                _fmt(s.get("batch_occupancy"), 3),
                str(s.get("shed") if s.get("shed") is not None else "-"),
                str(s.get("retries")
                    if s.get("retries") is not None else "-"),
            ])
        _print_table(rows, [
            "win", "req", "done", "rej", "q50ms", "d50ms",
            "e2e50", "e2e95", "e2e99", "rps", "occ", "shed", "rty",
        ])
        done = sum(s.get("completed") or 0 for s in serving)
        rej = sum(s.get("rejected") or 0 for s in serving)
        shed = sum(s.get("shed") or 0 for s in serving)
        retries = sum(s.get("retries") or 0 for s in serving)
        worst = max((s.get("e2e_ms_p99") or 0) for s in serving)
        print(f"  totals: {done} completed, {rej} rejected "
              f"({shed} shed past deadline), {retries} retried, "
              f"worst-window e2e p99 {worst:.2f} ms")

    if decode:
        # token-level SLO windows (schema v6, tpuddp/serving/decode/):
        # throughput in tokens/sec plus the two latencies token traffic
        # lives by — TTFT (submit -> first streamed token) and ITL (gap
        # between consecutive tokens of one sequence) — and KV-pool pressure
        print(f"\ndecode_stats windows ({len(decode)}):")
        rows = []
        for s in decode:
            rows.append([
                str(s.get("window")),
                str(s.get("tokens")),
                str(s.get("completed")),
                str(s.get("rejected")),
                _fmt(s.get("tokens_per_sec"), 0),
                _fmt(s.get("ttft_ms_p50"), 2),
                _fmt(s.get("ttft_ms_p95"), 2),
                _fmt(s.get("itl_ms_p50"), 2),
                _fmt(s.get("itl_ms_p99"), 2),
                _fmt(s.get("kv_occupancy"), 3),
                str(s.get("active_sequences")
                    if s.get("active_sequences") is not None else "-"),
                str(s.get("shed") if s.get("shed") is not None else "-"),
                str(s.get("failovers")
                    if s.get("failovers") is not None else "-"),
            ])
        _print_table(rows, [
            "win", "tok", "done", "rej", "tok/s", "ttft50", "ttft95",
            "itl50", "itl99", "kvocc", "act", "shed", "fo",
        ])
        tok = sum(s.get("tokens") or 0 for s in decode)
        done = sum(s.get("completed") or 0 for s in decode)
        shed = sum(s.get("shed") or 0 for s in decode)
        failovers = sum(s.get("failovers") or 0 for s in decode)
        worst_itl = max((s.get("itl_ms_p99") or 0) for s in decode)
        peak_kv = max((s.get("kv_occupancy") or 0) for s in decode)
        print(f"  totals: {tok} tokens across {done} sequences "
              f"({shed} shed past deadline, {failovers} session "
              f"failover(s)), worst-window ITL p99 {worst_itl:.2f} ms, "
              f"peak KV occupancy {peak_kv:.3f}")

    # gradient-comm byte savings: compressed vs the f32 baseline the header
    # records. ONLY the latest run segment's epochs belong to the latest
    # header — after an elastic resume the older epochs trained on a
    # different world (different per-update bytes), and their cumulative
    # counter reset at the resume anyway.
    if metas and latest_epochs:
        m = metas[-1]
        per, base = m.get("grad_comm_bytes_per_update"), m.get(
            "grad_comm_bytes_per_update_f32")
        total = latest_epochs[-1].get("grad_comm_bytes_total")
        if per is not None and base:
            saved = 1.0 - per / base
            line = (f"\ngrad comm: {per:,} B/update on the wire vs {base:,} B "
                    f"uncompressed ({saved * 100:.1f}% saved"
                    f", hook {m.get('comm_hook')}"
                    f", topology {m.get('comm_topology') or 'flat'})")
            if total is not None:
                line += (
                    f"; {total:,} B total this run"
                    + (f" (latest of {len(metas)})" if len(metas) > 1 else "")
                )
            print(line)
            # hierarchical hop split (schema v4): the compressed inter-host
            # share vs the f32 intra-host (ICI) traffic per update
            inter = m.get("grad_comm_bytes_inter_host")
            intra = m.get("grad_comm_bytes_intra_host")
            if inter is not None and intra:
                print(f"  hop split: {inter:,} B inter-host (compressed) + "
                      f"{intra:,} B intra-host (f32 ICI) per update")

    # survivability episode rollup (schema v7): one line a chaos gate (or
    # an operator) reads to know how many sessions migrated, which
    # replicas came back, and whether the pool ever terminally died
    sur_counts = {
        kind: sum(1 for ev in events if ev.get("event") == kind)
        for kind in (
            "session_failover", "replica_unhealthy", "replica_recovered",
            "replica_removed", "no_healthy_replica",
        )
    }
    if any(sur_counts.values()):
        print("\nsurvivability: " + ", ".join(
            f"{k}={v}" for k, v in sur_counts.items() if v
        ))

    # tracing digest (schema v9): the drain-time trace_summary rows — span
    # counts, ring drops, and the single slowest span per traced writer
    for ts in (r for r in records if r.get("type") == "trace_summary"):
        slowest = (ts.get("slowest") or [{}])[0]
        print(f"\ntracing: role={ts.get('role')} spans={ts.get('spans')} "
              f"dropped={ts.get('dropped')} open={ts.get('open_spans')} "
              f"by_kind={ts.get('by_kind')}")
        if slowest:
            print(f"  slowest span: {slowest.get('name')} "
                  f"({slowest.get('kind')}) "
                  f"{_fmt(slowest.get('duration_ms'), 3)} ms")

    if events:
        print(f"\nevents ({len(events)}):")
        for ev in events:
            fields = {
                k: v for k, v in ev.items()
                if k not in ("type", "schema_version", "event")
            }
            print(f"  [{ev.get('epoch', '-')}] {ev.get('event')}: {fields}")
    else:
        print("\nevents: none")


def summarize_flight(path: str) -> None:
    """Pretty-print a flightrec_<reason>.json crash recording (pure-python
    mirror of observability.flight.summarize_recording — this CLI stays
    importable on analysis hosts without the accelerator runtime)."""
    with open(path) as f:
        payload = json.load(f)
    print(f"flight recording: reason={payload.get('reason')} "
          f"process={payload.get('process_index')} "
          f"capacity={payload.get('capacity')} "
          f"observed={payload.get('observed_records')}")
    meta = payload.get("run_meta") or {}
    if meta:
        print(f"  run: api={meta.get('api')} model={meta.get('model')} "
              f"world={meta.get('world_size')} comm_hook={meta.get('comm_hook')}")
    notes = payload.get("notes") or {}
    if notes:
        print(f"  notes: {notes}")
    records = payload.get("records") or {}
    counts = payload.get("counts") or {}
    print("  rings: " + ", ".join(
        f"{k}={counts.get(k, 0)}" for k in sorted(counts)))
    windows = records.get("step_stats") or []
    if windows:
        last = windows[-1]
        print(f"  last window: epoch {last.get('epoch')} steps "
              f"[{last.get('step_start')}, "
              f"{(last.get('step_start') or 0) + (last.get('steps') or 0)}) "
              f"p50 {_fmt(last.get('step_time_ms_p50'), 2)} ms")
    epochs = records.get("epoch") or []
    if epochs:
        last = epochs[-1]
        print(f"  last epoch: {last.get('epoch')} train "
              f"{_fmt(last.get('train_loss'))} test {_fmt(last.get('test_loss'))}"
              f" skips {last.get('skipped_steps_epoch', 0) or 0}")
    events = records.get("event") or []
    if events:
        print(f"  events ({len(events)}):")
        for ev in events:
            fields = {
                k: v for k, v in ev.items()
                if k not in ("type", "schema_version", "event")
            }
            print(f"    [{ev.get('epoch', '-')}] {ev.get('event')}: {fields}")


def summarize_trace(path: str) -> None:
    """Pretty-print a trace_<role>.json artifact: provenance, per-kind time
    share, and the slowest-span table (the ``trace`` subcommand's summary —
    pure python, no accelerator runtime needed)."""
    with open(path) as f:
        payload = json.load(f)
    meta = payload.get("tpuddp") or {}
    print(f"trace: role={meta.get('role')} process={meta.get('process_index')} "
          f"spans={meta.get('spans')} dropped={meta.get('dropped')} "
          f"open={meta.get('open_spans')} traces={meta.get('traces')} "
          f"capacity={meta.get('capacity')}")
    clock = meta.get("clock_sync") or {}
    if clock:
        print(f"  clock_sync: unix_us={clock.get('unix_us')} "
              f"perf_ns={clock.get('perf_ns')}")
    spans = [
        e for e in (payload.get("traceEvents") or [])
        if isinstance(e, dict) and e.get("ph") == "X"
    ]
    # per-kind time share: where the traced wall time went, by span kind.
    # Kinds NEST (a stage span lives inside its epoch span), so shares can
    # exceed 100% of any one kind — the table answers "which kind is the
    # fat one", not "how do these partition the run".
    by_kind = collections.Counter()
    counts = collections.Counter()
    for e in spans:
        kind = e.get("cat") or "?"
        by_kind[kind] += float(e.get("dur") or 0.0)
        counts[kind] += 1
    total = sum(by_kind.values())
    if by_kind and total > 0:
        print(f"\nper-kind device-free host time ({total / 1e3:.1f} ms "
              "summed across nested spans):")
        rows = [
            [k, str(counts[k]), f"{d / 1e3:.1f}", f"{100 * d / total:.1f}%"]
            for k, d in by_kind.most_common()
        ]
        _print_table(rows, ["kind", "spans", "ms", "share"])
    # per-segment collective digest (segmented-backward overlap): the
    # annotation spans are named grad_comm.seg<k>, one per backward segment,
    # so an overlapped run shows K distinct collective rows here where a
    # barrier run shows the single grad_comm span
    seg_counts = collections.Counter(
        e.get("name") for e in spans
        if str(e.get("name") or "").startswith("grad_comm.seg")
    )
    if seg_counts:
        print(f"\ncollective segments ({len(seg_counts)}):")
        for name in sorted(seg_counts):
            a = next(
                (e.get("args") or {} for e in spans if e.get("name") == name),
                {},
            )
            print(f"  {name}: {seg_counts[name]} span(s) "
                  f"layers={a.get('layers')} flat={a.get('flat')} "
                  f"buckets={a.get('buckets')}")
    slowest = meta.get("slowest") or []
    if slowest:
        print(f"\nslowest spans (top {len(slowest)}):")
        rows = [
            [
                str(r.get("name")), str(r.get("kind")),
                _fmt(r.get("duration_ms"), 3),
            ]
            for r in slowest
        ]
        _print_table(rows, ["name", "kind", "ms"])
    opens = [e for e in spans if (e.get("args") or {}).get("open")]
    if opens:
        print(f"\nstill-open at export ({len(opens)}):")
        for e in opens:
            print(f"  {e.get('name')} ({e.get('cat')})")


def summarize_bench(path: str) -> None:
    with open(path) as f:
        payload = json.load(f)
    print(f"bench: {payload.get('metric')} = {payload.get('value')} "
          f"{payload.get('unit')} on {payload.get('device')} "
          f"(vs_baseline {payload.get('vs_baseline')} over "
          f"{payload.get('vs_baseline_basis')})")
    configs = payload.get("configs", {})
    if any(
        isinstance(r, dict) and "comm_topology" in r for r in configs.values()
    ):
        # comm-matrix rows (bench.py --comm): hook x topology A/B with the
        # per-row wire-byte accounting and the loss-parity evidence
        rows = []
        for name, r in configs.items():
            base = r.get("grad_comm_bytes_per_step_f32")
            per = r.get("grad_comm_bytes_per_step")
            cut = (
                f"{(1 - per / base) * 100:.1f}%"
                if per is not None and base else "-"
            )
            rows.append([
                name,
                str(r.get("comm_hook", "-")),
                str(r.get("comm_topology", "-")),
                _fmt(r.get("samples_per_sec_per_chip"), 0),
                _fmt(r.get("ms_per_step"), 2),
                str(per if per is not None else "-"),
                str(r.get("grad_comm_bytes_inter_host", "-")),
                cut,
                _fmt(r.get("final_loss")),
            ])
        _print_table(rows, [
            "config", "hook", "topo", "sps/chip", "ms", "wire B/step",
            "interB", "cut", "loss",
        ])
        return
    if any(
        isinstance(r, dict) and "tokens_per_sec" in r for r in configs.values()
    ):
        # decode token-curve rows (tools/loadgen.py --decode): tokens/sec +
        # TTFT/ITL vs offered sequence rate, with the sequential-decode
        # baseline row anchoring vs_baseline
        rows = []
        for name, r in configs.items():
            rows.append([
                name,
                str(r.get("mode", "-")),
                _fmt(r.get("offered_rps"), 1),
                _fmt(r.get("achieved_rps"), 1),
                _fmt(r.get("tokens_per_sec"), 0),
                _fmt(r.get("ttft_ms_p50"), 2),
                _fmt(r.get("ttft_ms_p95"), 2),
                _fmt(r.get("itl_ms_p50"), 2),
                _fmt(r.get("itl_ms_p99"), 2),
                str(r.get("rejected", "-")),
            ])
        _print_table(rows, [
            "config", "mode", "offered", "seq/s", "tok/s", "ttft50",
            "ttft95", "itl50", "itl99", "rej",
        ])
        return
    if any(isinstance(r, dict) and "offered_rps" in r for r in configs.values()):
        # serving curve rows (tools/loadgen.py): offered-vs-achieved
        # throughput with client-side latency percentiles
        rows = []
        for name, r in configs.items():
            rows.append([
                name,
                _fmt(r.get("offered_rps"), 0),
                _fmt(r.get("achieved_rps"), 0),
                _fmt(r.get("e2e_ms_p50"), 2),
                _fmt(r.get("e2e_ms_p99"), 2),
                _fmt(r.get("batch_occupancy"), 3),
                str(r.get("rejected", "-")),
                _fmt(r.get("samples_per_sec_per_chip"), 0),
            ])
        _print_table(rows, [
            "config", "offered", "rps", "e2e50ms", "e2e99ms", "occ",
            "rej", "rows/chip",
        ])
        return
    rows = []
    for name, r in configs.items():
        rows.append([
            name,
            _fmt(r.get("samples_per_sec_per_chip"), 0),
            _fmt(r.get("ms_per_step"), 2),
            _fmt(r.get("ms_per_step_p50"), 2),
            _fmt(r.get("ms_per_step_p99"), 2),
            _fmt(r.get("mfu")),
            # async-pipeline columns (every row since r6): wall/device ratio
            # and host-stall percentiles — '-' on rows predating them
            _fmt(r.get("wall_to_device_ratio"), 2),
            _fmt(r.get("host_stall_ms_p50"), 2),
            _fmt(r.get("host_stall_ms_p95"), 2),
        ])
    _print_table(rows, [
        "config", "sps/chip", "ms", "p50ms", "p99ms", "mfu",
        "w/dev", "stall50", "stall95",
    ])


def summarize_tune(path: str) -> None:
    """Pretty-print a TUNE_r*.json A/B probe report (schema v12): the
    predicted-vs-measured delta per rule and the endorsement verdicts."""
    with open(path) as f:
        payload = json.load(f)
    print(f"tune report: mode={payload.get('mode')} "
          f"device={payload.get('device')} "
          f"(schema v{payload.get('schema_version')})")
    baseline = payload.get("baseline_metrics") or {}
    if baseline:
        print("  baseline: " + ", ".join(
            f"{k}={_fmt(v, 2)}" for k, v in sorted(baseline.items())
        ))
    results = payload.get("results") or []
    rows = []
    for r in results:
        rows.append([
            str(r.get("rule")),
            str(r.get("rule_class")),
            str(r.get("metric")),
            _fmt(r.get("predicted_delta_pct"), 1),
            _fmt(r.get("measured_delta_pct"), 1),
            "yes" if r.get("endorsed") else "NO",
        ])
    if rows:
        _print_table(rows, [
            "rule", "class", "metric", "pred%", "meas%", "endorsed",
        ])
    n_endorsed = sum(1 for r in results if r.get("endorsed"))
    print(f"  {n_endorsed}/{len(results)} endorsed (measured improvement "
          "only — a regressing diff is never endorsed, whatever was "
          "predicted)")


def tune_main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="tpuddp_inspect.py tune",
        description="Offline advisor: read a run dir's history.jsonl, "
        "trace_*.json, and writer sidecars, and print knob recommendations "
        "with evidence citations + predicted deltas. Read-only — nothing "
        "is applied unless you --emit an overlay and launch with it.",
    )
    parser.add_argument("run_dir", help="run directory (holds history.jsonl)")
    parser.add_argument(
        "--emit", metavar="PATH", default=None,
        help="write the tuned config overlay (the $TPUDDP_TUNE_OVERLAY "
        "payload) for the recommendations to PATH",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the full report as JSON (machine-readable)",
    )
    args = parser.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"no such run dir: {args.run_dir}", file=sys.stderr)
        return 2
    advisor = _load_advisor()
    report = advisor.advise(args.run_dir)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(advisor.format_report(report))
    if args.emit:
        overlay = advisor.overlay_from(report["recommendations"])
        overlay["source"] = "advisor"
        tmp = args.emit + ".tmp"
        with open(tmp, "w") as f:
            json.dump(overlay, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, args.emit)
        print(f"\noverlay written: {args.emit} "
              f"(launch with TPUDDP_TUNE_OVERLAY=\"$(cat {args.emit})\")")
    return 0


def summarize_ckpt(path: str) -> int:
    """Print one checkpoint's recorded topology, shard tags, placement
    table, v4 data cursor (step snapshots), writer statistics, peer-shard
    provenance, and manifest status. Returns 0 (1 when the manifest
    mismatches — a torn file an operator should know about before trusting
    it)."""
    import json as _json

    import numpy as np

    reshard = _load_reshard()
    integrity = _load_integrity()
    with np.load(path) as f:
        stored = dict(f.items())
    topo = reshard.parse_topology(stored)
    leaves = [
        k for k in stored
        if k != reshard.TOPO_MARK
        and not k.startswith(reshard.META_MARK)
        and not k.startswith(reshard.CURSOR_MARK)
    ]
    n_bf16 = sum(1 for k in leaves if k.startswith(reshard.BF16_MARK))
    n_keys = sum(1 for k in leaves if k.startswith(reshard.KEY_MARK))
    total_b = sum(int(stored[k].nbytes) for k in leaves)
    print(f"checkpoint: {path}")
    # peer-redundant spill provenance: the file's own location says whether
    # this is a host's local checkpoint or a ring-neighbor copy under the
    # heartbeat channel's peer_ckpt/ directory
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if "peer_ckpt" in parts:
        ring = parts[parts.index("peer_ckpt") + 1] if (
            parts.index("peer_ckpt") + 1 < len(parts)
        ) else "?"
        print(f"  provenance: peer-redundant spill ({ring} — a ring "
              "neighbor's copy; restore prefers freshest-intact across "
              "local + peers)")
    print(f"  leaves: {len(leaves)} ({n_bf16} bf16-packed, {n_keys} PRNG "
          f"key(s)), {total_b:,} payload bytes")
    # v4 data cursor: the exact-resume record of a step-granular snapshot
    if "__cursor__" in stored:
        cur = _json.loads(str(np.asarray(stored["__cursor__"]).item()))
        acc_keys = cur.get("acc_keys") or []
        print(f"  cursor (v{cur.get('version')}): epoch={cur.get('epoch')} "
              f"step={cur.get('step')} plan_key={cur.get('plan_key')}")
        if acc_keys:
            names = [k[len("__cursor_acc__"):] for k in acc_keys]
            print(f"  cursor accumulator: {len(acc_keys)} partial metric "
                  f"leaf(s) {names}")
        print("  resume: exact — the driver continues this epoch AT the "
              "recorded step (zero batches replayed) when the plan key "
              "matches")
    # async-writer statistics sidecar (deliberately outside the payload:
    # the npz must stay byte-identical between async and sync writers)
    try:
        with open(path + ".writer.json", "r", encoding="utf-8") as wf:
            ws = _json.load(wf)
    except (OSError, ValueError):
        ws = None
    if ws is not None:
        print(f"  writer: async={ws.get('async')} inflight={ws.get('inflight')} "
              f"snapshots={ws.get('snapshots')} "
              f"skipped_queue_full={ws.get('skipped_queue_full')} "
              f"write_s={ws.get('write_s')} bytes={ws.get('bytes'):,} "
              f"peer_redundancy={ws.get('peer_redundancy')}")
    if topo is None:
        print("  topology: MISSING (format v1 — predates shard provenance; "
              "resharding refuses this file, resume it at model=1 or re-save "
              "through save_on_main)")
    else:
        d, m = reshard.topology_shape(topo)
        print(f"  topology: format v{topo.get('format')} world="
              f"{topo.get('world_size')} mesh=(data={d}, model={m}) "
              f"axes={topo.get('mesh_axes')}")
        re_prov = topo.get("resharded")
        if re_prov:
            print(f"  resharded: {re_prov.get('from')} -> {re_prov.get('to')}"
                  + (f", dropped {re_prov['dropped']}"
                     if re_prov.get("dropped") else ""))
        tags = topo.get("leaves") or {}
        if tags:
            print(f"  shard-tagged flat leaves ({len(tags)}):")
            for k in sorted(tags):
                print(f"    {k}: {tags[k]}")
        placement = topo.get("placement") or {}
        if placement:
            print(f"  placement tags ({len(placement)}):")
            for k in sorted(placement):
                print(f"    {k}: {placement[k]}")
        else:
            print("  placement tags: none (every leaf replicated)")
    manifest = integrity.read_manifest(path)
    if manifest is None:
        print("  manifest: ABSENT (.sha256 sidecar missing — structural "
              "zip check only at restore)")
        return 0
    ok = integrity.verify_file(path, require_manifest=True)
    status = (
        "verified"
        if ok
        else "MISMATCH (torn file: restore will skip this candidate)"
    )
    print(f"  manifest: sha256={manifest['digest'][:12]}... "
          f"size={manifest['size']} -> {status}")
    return 0 if ok else 1


def ckpt_main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="tpuddp_inspect.py ckpt",
        description="Summarize a tpuddp checkpoint (topology record, "
        "placement tags, manifest status) or a checkpoint directory.",
    )
    parser.add_argument("path", help="ckpt_<epoch>.npz file, or a run dir")
    args = parser.parse_args(argv)
    if os.path.isdir(args.path):
        import re as _re

        names = sorted(os.listdir(args.path))
        pat = _re.compile(r"^ckpt_(\d+)(?:_s(\d+))?\.npz$")
        matched = [(n, pat.match(n)) for n in names]
        ckpts = [(n, m) for n, m in matched if m]
        n_steps = sum(1 for _, m in ckpts if m.group(2) is not None)
        stale = [
            n for n in names
            if _re.match(r"^ckpt_\d+(_s\d+)?\.npz(\.sha256)?\.tmp$", n)
        ]
        steps_note = f" ({n_steps} step snapshot(s))" if n_steps else ""
        print(f"{args.path}: {len(ckpts)} checkpoint(s){steps_note}, "
              f"{len(stale)} stale .tmp file(s)"
              + (f" {stale}" if stale else ""))
        if not ckpts:
            return 0

        # same family ordering as restore_latest: a full-epoch save ranks
        # newer than any step snapshot of the same epoch
        def family(item):
            _, m = item
            step = m.group(2)
            return (int(m.group(1)), 1 if step is None else 0,
                    0 if step is None else int(step))

        newest = max(ckpts, key=family)[0]
        print()
        return summarize_ckpt(os.path.join(args.path, newest))
    if not os.path.isfile(args.path):
        print(f"no such file: {args.path}", file=sys.stderr)
        return 2
    return summarize_ckpt(args.path)


def reshard_main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="tpuddp_inspect.py reshard",
        description="Offline cross-topology checkpoint reshaper: rewrite a "
        "format-v3 checkpoint saved on one (data, model) mesh for another "
        "(atomic publish + fresh sha256 manifest). The load-time equivalent "
        "is training.reshard_on_mismatch: true.",
    )
    parser.add_argument("src", help="source ckpt_<epoch>.npz")
    parser.add_argument(
        "--to", required=True, metavar="data=D,model=M",
        help="target mesh shape, e.g. --to data=2,model=1",
    )
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="output file (default: <src stem>.d<D>m<M>.npz alongside src; "
        "pass the src path itself to reshape in place)",
    )
    args = parser.parse_args(argv)
    if not os.path.isfile(args.src):
        print(f"no such file: {args.src}", file=sys.stderr)
        return 2
    shape = {}
    for part in args.to.split(","):
        if "=" not in part:
            parser.error(f"--to expects data=D,model=M, got {args.to!r}")
        k, v = part.split("=", 1)
        shape[k.strip()] = v.strip()
    unknown = set(shape) - {"data", "model"}
    if unknown or "data" not in shape:
        parser.error(f"--to expects data=D,model=M, got {args.to!r}")
    try:
        data = int(shape["data"])
        model = int(shape.get("model", 1))
    except ValueError:
        parser.error(f"--to expects integer widths, got {args.to!r}")
    out = args.out
    if out is None:
        stem = args.src[:-len(".npz")] if args.src.endswith(".npz") else args.src
        out = f"{stem}.d{data}m{model}.npz"
    reshard = _load_reshard()
    try:
        report = reshard.reshard_checkpoint(args.src, out, data, model)
    except reshard.ReshardError as e:
        print(f"REFUSED: {e}", file=sys.stderr)
        return 1
    f, t = report["from"], report["to"]
    print(f"resharded {report['src']} -> {report['dst']}")
    print(f"  mesh: (data={f['data']}, model={f['model']}) -> "
          f"(data={t['data']}, model={t['model']}), "
          f"{report['leaves']} leaves")
    for a in report["actions"]:
        detail = {
            k: v for k, v in a.items() if k not in ("leaf", "action")
        }
        print(f"  {a['action']}: {a['leaf']} {detail}")
    if not report["actions"]:
        print("  (no per-leaf surgery needed: payloads are mesh-shape-"
              "independent at these shapes)")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "ckpt":
        return ckpt_main(argv[1:])
    if argv and argv[0] == "reshard":
        return reshard_main(argv[1:])
    if argv and argv[0] == "tune":
        return tune_main(argv[1:])
    # `tpuddp_inspect.py trace <path>` — the explicit trace subcommand:
    # validates the artifact against schema v9 and prints the slowest-span
    # table + per-kind time share (content detection still recognizes a
    # trace artifact passed as a bare path, so both spellings work)
    trace_mode = bool(argv) and argv[0] == "trace"
    if trace_mode:
        argv = argv[1:]
    parser = argparse.ArgumentParser(
        description="Validate/summarize a tpuddp history.jsonl, "
        "bench_results.json, flightrec_*.json, or trace_<role>.json "
        "artifact ('trace <path>' forces the trace reader).",
    )
    parser.add_argument("path", help="artifact to inspect")
    parser.add_argument(
        "--validate", action="store_true",
        help="schema check only: exit 0 when valid, 1 with errors on stderr",
    )
    parser.add_argument(
        "--events", action="store_true",
        help="print only the event timeline (history files)",
    )
    args = parser.parse_args(argv)

    if not os.path.isfile(args.path):
        print(f"no such file: {args.path}", file=sys.stderr)
        return 2

    schema = _load_schema()
    kind = "trace" if trace_mode else _detect_kind(args.path)
    if kind == "bench":
        errors, n = schema.validate_bench_file(args.path)
    elif kind == "flight":
        errors, n = schema.validate_flight_file(args.path)
    elif kind == "trace":
        errors, n = schema.validate_trace_file(args.path)
    elif kind == "tune":
        errors, n = schema.validate_tune_file(args.path)
    else:
        errors, n = schema.validate_history_file(args.path)

    if errors:
        for e in errors:
            print(f"INVALID: {e}", file=sys.stderr)
        if args.validate:
            return 1
        print(f"({len(errors)} schema error(s) — summary follows)\n",
              file=sys.stderr)
    if args.validate:
        print(f"OK: {args.path} — {n} {kind} record(s), schema v"
              f"{schema.SCHEMA_VERSION}")
        return 0

    if kind == "bench":
        summarize_bench(args.path)
    elif kind == "flight":
        summarize_flight(args.path)
    elif kind == "trace":
        summarize_trace(args.path)
    elif kind == "tune":
        summarize_tune(args.path)
    elif args.events:
        for r in _read_history(args.path):
            if r.get("event"):
                print(json.dumps(r))
    else:
        summarize_history(args.path)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
