#!/usr/bin/env python
"""Run the full chaos (fault-injection) resilience suite.

The chaos tier lives outside the tier-1 fast path (every chaos test is also
marked slow): it kills subprocess training runs with SIGTERM, injects
``$TPUDDP_FAULT`` crashes/hangs/corruption/NaN-gradients (``nan@step=N``
exercises the numerical-guard firewall end to end), drives the desync
auditor's exit-77 and rollback-to-last-good paths, and asserts the
exit-code and auto-resume contracts documented in README "Fault tolerance".

Serving chaos rides the SAME env contract (README "Serving survivability"):

    TPUDDP_FAULT=replica_kill@step=N    kill a decode replica at global
                                        decode step N — live sessions park
                                        into failover journals, migrate,
                                        and continue BITWISE; the replica
                                        rejoins after probation
    TPUDDP_FAULT=pool_poison@step=N     delete the replica's donated K/V
                                        pool buffers mid-sweep (the real
                                        accelerator donation death)
    TPUDDP_FAULT=replica_kill@batch=N   kill a request-serving replica at
                                        dispatched batch N
    TPUDDP_FAULT=dispatch_wedge@batch=N fail exactly one dispatch
                                        transiently (the retry-budget
                                        exercise; dispatch_wedge@step=N is
                                        the decode-side equivalent)

``tools/loadgen.py --decode --chaos`` drives the full headline proof
(kill mid-sweep -> zero lost streams, bitwise-equal to undisturbed twins)
and ``tools/run_full_gate.py`` runs it as the serving-chaos leg.

Usage: python tools/run_chaos.py [extra pytest args]
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # chaos runs never need a real TPU
    cmd = [
        sys.executable, "-m", "pytest", "tests", "-q",
        "-m", "chaos",
        "-p", "no:cacheprovider",
        *(argv if argv is not None else sys.argv[1:]),
    ]
    return subprocess.call(cmd, cwd=REPO, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
