#!/usr/bin/env python
"""Run the full chaos (fault-injection) resilience suite.

The chaos tier lives outside the tier-1 fast path (every chaos test is also
marked slow): it kills subprocess training runs with SIGTERM, injects
``$TPUDDP_FAULT`` crashes/hangs/corruption/NaN-gradients (``nan@step=N``
exercises the numerical-guard firewall end to end), drives the desync
auditor's exit-77 and rollback-to-last-good paths, and asserts the
exit-code and auto-resume contracts documented in README "Fault tolerance".

Usage: python tools/run_chaos.py [extra pytest args]
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # chaos runs never need a real TPU
    cmd = [
        sys.executable, "-m", "pytest", "tests", "-q",
        "-m", "chaos",
        "-p", "no:cacheprovider",
        *(argv if argv is not None else sys.argv[1:]),
    ]
    return subprocess.call(cmd, cwd=REPO, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
