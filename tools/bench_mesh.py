#!/usr/bin/env python
"""bench_mesh — the 2-D mesh proving run (ISSUE 14 deliverable).

Trains ``transformer_small`` as a next-token LM on a synthetic token stream
through the REAL epoch driver (``tpuddp.training.loop.run_training_loop``)
in two configurations on the 4-device CPU mesh:

- **TP=2 x DP=2** — the 2-D ``("data", "model")`` mesh: attention heads,
  MLP hidden units, and vocabulary rows sharded 1/2 per chip
  (tpuddp/parallel/tensor.py), gradient collectives over the data axis
  only, schema-v8 history with the ``mesh`` block;
- **DP=4** — the pure data-parallel reference at the SAME global batch.

It then asserts, in-process:

- **loss-trajectory parity**: per-epoch train losses of the two runs agree
  within a float-reduction tolerance (the TP row-split contractions change
  only the summation order of each matmul, never the math — asserted
  |Δloss| <= max(2e-3, 1e-3·|loss|) every epoch);
- **per-chip parameter-byte cut**: the TP run's per-chip parameter bytes
  land under the replicated footprint by ~the sharded fraction of the
  attention+MLP+vocab weights.

The emitted bench payload (``--out``) is the ``MULTICHIP_r06.json`` row
format: both configs with ms_per_step + samples_per_sec_per_chip (token
steps), plus ``param_bytes_per_chip`` / ``param_bytes_cut`` on the TP row.
``tools/bench_trend.py`` ingests the MULTICHIP family; the full gate's mesh
leg runs this with ``--quick`` and re-validates the history independently.

Usage:
    python tools/bench_mesh.py --out MULTICHIP_r06.json [--history-dir DIR]
                               [--quick] [--epochs N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the proving run is a CPU-mesh artifact: pin the 4-device world BEFORE jax
# initializes (mirrors tests/conftest.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()
os.environ.setdefault("TPUDDP_BACKEND", "cpu")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


class TokenLMLoader:
    """Synthetic next-token LM loader with the epoch-driver loader protocol
    (len / set_epoch / make_batch_plan / iter): a fixed token corpus sampled
    per epoch into ``(tokens, shifted targets, weights)`` batches. The same
    seed yields the same global batches on ANY mesh shape — the matched-
    global-batch contract the DP-vs-TP parity comparison needs."""

    def __init__(self, vocab: int, seq_len: int, global_batch: int,
                 n_batches: int, seed: int = 0):
        self.vocab = vocab
        self.seq_len = seq_len
        self.global_batch = global_batch
        self.n_batches = n_batches
        self.seed = seed
        self.epoch = 0
        self.batch_nbytes = global_batch * seq_len * 4

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return self.n_batches

    def make_batch_plan(self):
        rng = np.random.default_rng((self.seed, self.epoch))
        # one contiguous token stream per epoch; batches slice it
        data = rng.integers(
            0, self.vocab,
            (self.n_batches, self.global_batch, self.seq_len + 1),
        ).astype(np.int32)

        def fetch(s: int):
            chunk = data[s]
            x = chunk[:, :-1]
            y = chunk[:, 1:].astype(np.int32)
            w = np.ones(x.shape, np.float32)
            return x, y, w

        return self.n_batches, fetch

    def __iter__(self):
        steps, fetch = self.make_batch_plan()
        for s in range(steps):
            yield fetch(s)


def run_one(tag: str, data: int, model_width: int, *, history_dir, epochs,
            n_batches, global_batch, vocab, seq_len, seed=0):
    """One training run through the real epoch driver; returns the per-epoch
    losses, wall-clock rate, and the wrap's accounting."""
    from tpuddp import nn, optim
    from tpuddp import config as cfg_lib
    from tpuddp.models import load_model
    from tpuddp.parallel.ddp import DistributedDataParallel
    from tpuddp.training.loop import run_training_loop

    mesh = cfg_lib.mesh_from({"data": data, "model": model_width}, data * model_width)
    model = load_model("transformer_small", num_classes=vocab, max_seq_len=seq_len)
    ddp = DistributedDataParallel(
        model, optim.Adam(lr=1e-3), nn.CrossEntropyLoss(), mesh=mesh,
    )
    state = ddp.init_state(
        jax.random.PRNGKey(seed), jnp.zeros((1, seq_len), jnp.int32)
    )
    train = TokenLMLoader(vocab, seq_len, global_batch, n_batches, seed=seed)
    test = TokenLMLoader(vocab, seq_len, global_batch, max(2, n_batches // 4),
                         seed=seed + 1)
    out_dir = os.path.join(history_dir, tag) if history_dir else None
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    state, history = run_training_loop(
        ddp, state, train, test, out_dir,
        num_epochs=epochs, checkpoint_epoch=max(1, epochs - 1),
        set_epoch=True, scan_steps=min(4, n_batches), per_replica_log=False,
        run_meta={"model": "transformer_small", "dataset": "synthetic_tokens"},
        log=lambda *a, **k: None,
    )
    wall = time.perf_counter() - t0
    steps = epochs * n_batches
    tokens = steps * global_batch * seq_len
    from tpuddp.parallel import tensor as tp_lib

    if ddp.model_size > 1:
        tp_params = jax.tree_util.tree_map(np.asarray, state.params)
        per_chip = tp_lib.per_chip_param_bytes(
            tp_params, ddp.tp_param_specs, ddp.model_size
        )
        full = sum(
            int(np.prod(np.shape(l))) * 4
            for l in jax.tree_util.tree_leaves(tp_params)
        )
    else:
        full = sum(
            int(np.prod(np.shape(l))) * 4
            for l in jax.tree_util.tree_leaves(state.params)
        )
        per_chip = full
    return {
        "tag": tag,
        "losses": [h["train_loss"] for h in history],
        "wall_s": wall,
        "ms_per_step": 1000.0 * wall / steps,
        "tokens_per_sec": tokens / wall,
        "samples_per_sec_per_chip": (steps * global_batch) / wall / (data * model_width),
        "param_bytes_per_chip": per_chip,
        "param_bytes_full": full,
        "grad_comm_bytes_per_step": ddp.grad_comm_bytes_per_step,
        "out_dir": out_dir,
        "data": data,
        "model": model_width,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None, help="bench payload path")
    ap.add_argument("--history-dir", default=None,
                    help="keep the runs' history.jsonl under this dir")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--quick", action="store_true",
                    help="small corpus (the gate's setting)")
    args = ap.parse_args(argv)

    devs = jax.devices()
    if len(devs) < 4:
        print(f"bench_mesh: needs 4 devices, found {len(devs)}", file=sys.stderr)
        return 2
    vocab, seq_len = 64, 32
    n_batches = 4 if args.quick else 8
    global_batch = 8
    epochs = max(2, args.epochs if not args.quick else 2)

    import tempfile

    history_dir = args.history_dir or tempfile.mkdtemp(prefix="tpuddp_mesh_")
    common = dict(
        history_dir=history_dir, epochs=epochs, n_batches=n_batches,
        global_batch=global_batch, vocab=vocab, seq_len=seq_len,
    )
    # --quick rows are correctness probes on a compile-dominated corpus, not
    # perf measurements: a distinct row name keeps bench_trend from judging
    # them against the committed full-size MULTICHIP rows
    suffix = "_quick" if args.quick else ""
    tp = run_one(f"transformer_small_tp2xdp2{suffix}", 2, 2, **common)
    dp = run_one(f"transformer_small_dp4{suffix}", 4, 1, **common)

    # ---- loss-trajectory parity at matched global batch -------------------
    worst = 0.0
    for e, (lt, ld) in enumerate(zip(tp["losses"], dp["losses"])):
        tol = max(2e-3, 1e-3 * abs(ld))
        worst = max(worst, abs(lt - ld))
        if abs(lt - ld) > tol:
            print(
                f"bench_mesh: PARITY FAIL epoch {e}: tp {lt:.6f} vs dp "
                f"{ld:.6f} (tol {tol:.1e})", file=sys.stderr,
            )
            return 1
    # ---- per-chip parameter-byte cut --------------------------------------
    cut = 1.0 - tp["param_bytes_per_chip"] / tp["param_bytes_full"]
    # attention+MLP+vocab weights halve at TP=2; LN/bias/pos stay replicated
    # — on transformer_small the sharded fraction is ~97% of all parameters,
    # so the per-chip footprint must land well under 60% of the full copy
    if tp["param_bytes_per_chip"] >= 0.6 * tp["param_bytes_full"]:
        print(
            f"bench_mesh: per-chip cut too small: {cut * 100:.1f}%",
            file=sys.stderr,
        )
        return 1

    payload = {
        "metric": "tokens_per_sec",
        "value": tp["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": tp["tokens_per_sec"] / dp["tokens_per_sec"],
        "device": devs[0].device_kind,
        "note": (
            "2-D (data, model) mesh proving run: transformer_small LM, "
            "TP=2xDP=2 vs pure DP=4 at matched global batch; loss parity "
            f"worst |d|={worst:.2e}; per-chip param bytes cut "
            f"{cut * 100:.1f}% (attention+MLP+vocab sharded 1/2)"
        ),
        "configs": {
            tp["tag"]: {
                "ms_per_step": tp["ms_per_step"],
                "tokens_per_sec": tp["tokens_per_sec"],
                "data": tp["data"], "model": tp["model"],
                "param_bytes_per_chip": tp["param_bytes_per_chip"],
                "param_bytes_full": tp["param_bytes_full"],
                "param_bytes_cut": cut,
                "grad_comm_bytes_per_step": tp["grad_comm_bytes_per_step"],
                "final_train_loss": tp["losses"][-1],
            },
            dp["tag"]: {
                "ms_per_step": dp["ms_per_step"],
                "tokens_per_sec": dp["tokens_per_sec"],
                "data": dp["data"], "model": dp["model"],
                "param_bytes_per_chip": dp["param_bytes_per_chip"],
                "final_train_loss": dp["losses"][-1],
            },
        },
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=2, allow_nan=False)
            f.write("\n")
    # the parseable-summary contract: the LAST stdout line is the payload
    # summary (tools/run_full_gate.py parses it)
    print(json.dumps({
        "ok": True,
        "parity_worst_abs": worst,
        "param_bytes_cut": cut,
        "tp_history": os.path.join(tp["out_dir"], "history.jsonl"),
        "dp_history": os.path.join(dp["out_dir"], "history.jsonl"),
        "tokens_per_sec_tp": tp["tokens_per_sec"],
        "tokens_per_sec_dp": dp["tokens_per_sec"],
    }, allow_nan=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
