#!/usr/bin/env python
"""Run the FULL test gate — both tiers in one explicit invocation.

``pytest.ini`` sets ``addopts = -m "not slow"``, so a bare ``pytest`` run is
the fast tier-1 gate only: the subprocess/CLI end-to-end runs, the multichip
dryrun, the big pretrained-import donors, the fuzz sweeps, and the chaos
suite (chaos tests are also slow-marked) all silently fall out of any default
invocation. This runner makes "run everything" a command instead of a marker
expression someone must remember: it selects ``-m "slow or not slow"`` —
every collected test, both tiers — and inherits pytest's exit-code contract
(non-zero on failures, 4/5 if the expression ever selects nothing, i.e. the
two-tier contract itself drifted).

Tier membership note: the numerical-guard/desync suite (tests/test_guard.py)
is deliberately UNMARKED so it rides in tier-1 — the firewall/auditor
contracts are fast compiled-step assertions, not subprocess chaos; only the
subprocess proofs (nan@step, exit-77, rollback in tests/test_chaos.py) live
in the chaos tier.

Usage: python tools/run_full_gate.py [extra pytest args]

The two-tier contract is documented in README "Testing"; the chaos tier can
still be run alone via tools/run_chaos.py.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # the full gate never needs a real TPU
    cmd = [
        sys.executable, "-m", "pytest", "tests", "-q",
        "-m", "slow or not slow",
        "-p", "no:cacheprovider",
        *(argv if argv is not None else sys.argv[1:]),
    ]
    return subprocess.call(cmd, cwd=REPO, env=env)


if __name__ == "__main__":
    sys.exit(main())
