#!/usr/bin/env python
"""Run the FULL test gate — both tiers plus the telemetry-schema gate.

``pytest.ini`` sets ``addopts = -m "not slow"``, so a bare ``pytest`` run is
the fast tier-1 gate only: the subprocess/CLI end-to-end runs, the multichip
dryrun, the big pretrained-import donors, the fuzz sweeps, and the chaos
suite (chaos tests are also slow-marked) all silently fall out of any default
invocation. This runner makes "run everything" a command instead of a marker
expression someone must remember: it selects ``-m "slow or not slow"`` —
every collected test, both tiers — and inherits pytest's exit-code contract
(non-zero on failures, 4/5 if the expression ever selects nothing, i.e. the
two-tier contract itself drifted).

Tier membership note: the numerical-guard/desync suite (tests/test_guard.py)
is deliberately UNMARKED so it rides in tier-1 — the firewall/auditor
contracts are fast compiled-step assertions, not subprocess chaos; only the
subprocess proofs (nan@step, exit-77, rollback in tests/test_chaos.py) live
in the chaos tier.

Schema gate (after the suites pass): a dryrun training subprocess produces a
``history.jsonl`` and ``tools/tpuddp_inspect.py --validate`` must accept it;
if a ``bench_results.json`` exists at the repo root, it is validated too. A
writer drifting off the typed record schema (tpuddp/observability/schema.py)
fails the gate here instead of corrupting downstream consumers.

Pipeline gate (after the schema gate): a ``pipeline.depth=2`` dryrun and a
``pipeline: false`` (synchronous) dryrun of the same seed must produce a
schema-valid history whose ``step_stats`` windows carry the v3 occupancy
fields (host_stall_ms / inflight_depth / staging_queue_depth), bitwise-equal
checkpoints leaf for leaf, and byte-identical step HLO — the async pipeline's
"zero semantic cost" contract, enforced every gate run.

Overlap gate (after the pipeline gate): a ``comm_overlap: true`` dryrun and
a ``comm_overlap: false`` dryrun of the same seed (bucket cap pinned so the
worker's ToyMLP splits into K>=2 segments) must land bitwise-equal
checkpoints leaf for leaf, the segmented history must validate under schema
v10 with a run_meta ``comm.overlap`` provenance block reporting
``enabled: true`` and ``segments >= 2``, and the dedicated HLO tests must
show K interleaved collectives overlap-on vs one trailing block overlap-off
— the segmented backward's "program shape changes, semantics don't"
contract, enforced every gate run.

Compression-matrix gate (after the overlap gate): dryrun trainings across
the comm hook x topology grid (none/bf16_ef/int8_ef/topk_ef x
flat/hierarchical) must each produce a schema-valid history whose run_meta
carries the comm accounting; the quantized/sparse hooks must show their
acceptance byte cuts (>= 70% / >= 85%) against the header's own f32
baseline, final-epoch losses must sit within the documented per-hook parity
bound of the uncompressed run, and hierarchical rows must report inter-host
bytes below the flat total.

Mesh gate (after the comm-matrix gate): ``tools/bench_mesh.py --quick``
trains transformer_small on the 2-D ``("data", "model")`` mesh (TP=2xDP=2)
AND as pure DP=4 at matched global batch through the real epoch driver,
asserting loss-trajectory parity and the per-chip parameter-byte cut; the
gate independently re-validates the TP history (schema v8, the run_meta
``mesh`` block with a real tp_rules_hash), runs the ``model=1`` HLO
byte-identity test against the flat DDP path, and feeds the fresh
MULTICHIP-format payload through ``tools/bench_trend.py --fresh``.

Serving gate (after the mesh gate): ``tools/loadgen.py --quick`` stands the continuous-
batching engine up on the CPU mesh (2 replicas, 2 tenants, ~170 requests
across a closed-loop calibration + 3 offered-load points) and both emitted
artifacts — the engine's ``history.jsonl`` (run_meta + serving_stats +
events) and the latency-vs-throughput ``bench_results.json`` curve — must
pass ``tpuddp_inspect --validate``. The serving SLO record stream drifting
off schema v2 fails the gate the same way training telemetry drift does.

Decode gate (after the serving gate): ``tools/loadgen.py --decode --quick``
stands the TOKEN-level autoregressive engine (tpuddp/serving/decode/) up on
the CPU mesh — transformer prefill/decode split, paged KV cache, continuous
batching at token granularity — and both artifacts (the schema-v6
``history.jsonl`` with run_meta decode provenance + decode_stats windows,
and the tokens/sec + TTFT ``bench_results.json`` curve) must pass
``tpuddp_inspect --validate``. Then the drain leg: a ``--decode`` server
is SIGTERMed mid-decode and must let every in-flight sequence finish
streaming (summary ``completed == submitted``, zero truncation) before
exiting 75 — the resilience drain contract at token granularity.

Serving-chaos gate (after the decode gate): ``tools/loadgen.py --decode
--quick --chaos`` re-runs the token sweep and then kills a replica
MID-SWEEP through the real ``$TPUDDP_FAULT`` env contract
(``replica_kill@step=N``). The survivability layer (ISSUE 13,
tpuddp/serving/survive.py) must lose ZERO streams: every live sequence
parks into its session journal, fails over, and completes **bitwise-equal**
to an undisturbed same-seed twin (loadgen verifies the equality in-process
and this leg re-checks the accounting: completed == submitted - shed); the
killed replica passes probation and rejoins routing
(``replica_recovered``); an expired queued request is shed with a typed
``deadline_exceeded`` rejection; and both artifacts — the history with its
``session_failover``/``replica_recovered`` event rows and the bench curve's
chaos row — validate under schema v7.

Elastic-resume gate (after the serving-chaos gate): a bf16_ef training run on 4
local devices is preempted (injected SIGTERM -> exit 75, emergency
checkpoint), then resumed on 2 devices THROUGH the restart supervisor
(tools/supervise.py) — the v2 checkpoint reshards onto the smaller world.
The merged history.jsonl must validate and carry a topology_change event
row; elastic restore drifting (a reshard that crashes, or stops recording
its provenance) fails the gate here.

Reshard gate (after the elastic gate): the ISSUE 16 cross-topology leg — a
TP=2 x DP=2 token-LM run is preempted at an epoch boundary (exit 75,
emergency v3 checkpoint with per-leaf placement tags); the checkpoint is
round-tripped offline through ``tpuddp_inspect reshard`` across the
model-width crossing (TP -> canonical -> TP) and must come back
byte-identical; then the same run dir resumes at TP=1 x DP=2 through the
reshard-on-load path and the merged history must validate and carry the
``(model 2 -> 1)`` topology_change event. Placement-tag drift, a lossy QKV
relayout, or a reshard that stops recording provenance fails here.

Snapshot gate (after the reshard gate): the ISSUE 18 exact-resume leg — a
training run with step-granular async snapshots armed
(``training.snapshot.every_steps``) is killed MID-epoch via
``preempt@step=N`` (exit 75; the drain flushes the async writer and lands a
``ckpt_<epoch>_s<step>.npz`` with a v4 data cursor), ``tpuddp_inspect ckpt``
must print that cursor, then the run auto-resumes and must (a) log the
"Exact resume ... zero batches replayed" line, (b) finish with per-epoch
losses BITWISE-equal to an uninterrupted same-seed twin, and (c) leave a
schema-v11 history whose run_meta carries the ``snapshot`` provenance
block. A snapshot drain that replays batches, loses the cursor, or stops
recording provenance fails here.

Fleet gate (after the snapshot gate): ``tools/fleet.py chaos-demo`` shares
one CPU-mesh pool between 2 training jobs and 1 serving job under the
fleet controller (ISSUE 11): one training job is SIGKILLed mid-run and
resumes elastically, a late high-priority arrival preempts capacity
through the drain contract (exit 75 -> shrunk $TPUDDP_WORLD_SIZE resume,
never SIGKILL-first), and the serving job autoscales its replicas on a
p99 SLO breach ($TPUDDP_SERVING_REPLICAS). Every job's namespaced
history.jsonl is then independently re-validated with tpuddp_inspect —
a controller that lets co-scheduled jobs corrupt each other's channels
fails here.

Tracing gate (after the observability gate, last): the causal tracing
plane (ISSUE 15, tpuddp/observability/trace.py). A traced training dryrun
(``observability.tracing: true``) and an untraced same-seed twin must
produce IDENTICAL loss trajectories (train/test loss + accuracy per epoch,
compared bitwise on the serialized values) — tracing changes zero
semantics; the traced run must leave a schema-v9-valid ``trace_train.json``
whose span tree nests (no orphan parent_ids — enforced by the validator
whenever the ring dropped nothing) and a run_meta carrying the ``tracing``
provenance block, while the untraced twin must leave NO trace artifact and
a null ``tracing`` field. Then a traced serving sweep (``python -m
tpuddp.serving --demo`` with tracing on) must drain to a schema-valid
``trace_serving.json`` with request/admission/queue_wait span trees and a
``trace_summary`` history row.

Observability gate: tools/bench_trend.py across the committed
BENCH_r*.json artifacts (a >10% regression of any same-device best row
fails), a live exporter scrape (a serving engine with the
observability.exporter block must answer /healthz + the serving /metrics
families while running, then SIGTERM-drain to exit 75 with a schema-v5
history), and a flight-recorder leg (a chaos-preempted training run must
leave a tpuddp_inspect-valid flightrec_preempt.json which the restart
supervisor summarizes — --flight-dir — before resuming the run to
completion). A dead endpoint, schema-v5 drift, a missing crash recording,
or a bench regression all fail here.

Autotune gate (last): the self-tuning loop (ISSUE 19). A deliberately
mis-knobbed traced dryrun (synchronous pipeline, per-step snapshots, no
comm compression) must make ``tpuddp_inspect tune`` fire recommendations
across >= 3 distinct rule classes with evidence citations; ``tools/
autotune.py --quick`` must A/B the diffs through the real epoch driver and
land a schema-v12-valid TUNE report (endorsement honesty validated, not
trusted); and the fleet tuner's apply/measure/revert unit matrix — with an
injected regression forcing the auto-revert — must pass.

Usage: python tools/run_full_gate.py [extra pytest args]

The two-tier contract is documented in README "Testing"; the chaos tier can
still be run alone via tools/run_chaos.py.
"""

import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _schema_gate(env) -> int:
    """Dryrun-train, then validate the artifacts with tpuddp_inspect."""
    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_gate_") as out_dir:
        # the chaos suite's training worker IS the dryrun entry: the full
        # native spawn path (4 virtual CPU devices, synthetic data) with the
        # telemetry window armed so step_stats rows are exercised too
        worker_env = dict(env)
        worker_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "TPUDDP_CHAOS_TRAINING": '{"step_stats_every": 4}',
        })
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tests", "_chaos_train_worker.py"),
                out_dir, "2",
            ],
            cwd=REPO, env=worker_env,
        )
        if rc != 0:
            print(f"schema gate: dryrun training exited {rc}", file=sys.stderr)
            return rc
        rc = subprocess.call(
            [sys.executable, inspect, "--validate",
             os.path.join(out_dir, "history.jsonl")],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("schema gate: dryrun history.jsonl failed validation",
                  file=sys.stderr)
            return rc
    bench_json = os.path.join(REPO, "bench_results.json")
    if os.path.exists(bench_json):
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", bench_json],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("schema gate: bench_results.json failed validation",
                  file=sys.stderr)
            return rc
    else:
        print("schema gate: no bench_results.json at repo root (skipped)")
    return 0


def _serving_gate(env) -> int:
    """Drive the serving engine with loadgen, then validate its artifacts."""
    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_serve_gate_") as out_dir:
        worker_env = dict(env)
        worker_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        bench_json = os.path.join(out_dir, "bench_results.json")
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "loadgen.py"),
                "--quick", "--replicas", "2", "--tenants", "2",
                "--history-dir", out_dir, "--out", bench_json,
            ],
            cwd=REPO, env=worker_env,
        )
        if rc != 0:
            print(f"serving gate: loadgen exited {rc}", file=sys.stderr)
            return rc
        for artifact in (os.path.join(out_dir, "history.jsonl"), bench_json):
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", artifact],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(
                    f"serving gate: {os.path.basename(artifact)} failed "
                    "validation", file=sys.stderr,
                )
                return rc
    return 0


def _decode_gate(env) -> int:
    """Decode leg (ISSUE 12): (a) loadgen's --quick token sweep on the CPU
    mesh with both artifacts schema-validated; (b) the drain contract — a
    SIGTERM landing mid-decode must let every in-flight sequence finish
    streaming (completed == submitted, nothing truncated) and exit 75."""
    import json
    import signal
    import time

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_decode_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # -- leg a: the token sweep + artifact validation
        sweep_dir = os.path.join(tmp, "sweep")
        os.makedirs(sweep_dir)
        bench_json = os.path.join(sweep_dir, "bench_results.json")
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "loadgen.py"),
                "--decode", "--quick", "--replicas", "2", "--tenants", "2",
                "--history-dir", sweep_dir, "--out", bench_json,
            ],
            cwd=REPO, env=base_env,
        )
        if rc != 0:
            print(f"decode gate: loadgen --decode exited {rc}",
                  file=sys.stderr)
            return rc
        for artifact in (os.path.join(sweep_dir, "history.jsonl"), bench_json):
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", artifact],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(f"decode gate: {os.path.basename(artifact)} failed "
                      "validation", file=sys.stderr)
                return rc
        # -- leg b: SIGTERM mid-decode -> finish in-flight streams -> 75
        out_dir = os.path.join(tmp, "drain")
        settings = os.path.join(tmp, "settings.yaml")
        with open(settings, "w") as f:
            f.write(
                "out_dir: %s\n"
                "serving:\n"
                "  decode:\n"
                "    vocab_size: 64\n"
                "    max_slots: 4\n"
                "    kv_blocks: 65\n"
                "    kv_block_size: 8\n"
                "    max_seq_len: 128\n"
                # 24 sequences x 96 tokens on 4 slots is seconds of decode
                # on the CPU mesh — the SIGTERM below cannot miss the window,
                # and the in_flight_at_drain assertion proves it didn't
                "    max_new_tokens: 96\n"
                "    stats_window: 32\n" % out_dir
            )
        n_demo = 24
        proc = subprocess.Popen(
            [
                sys.executable, "-u", "-m", "tpuddp.serving",
                "--settings", settings, "--decode",
                "--demo", str(n_demo), "--serve", "120",
            ],
            cwd=REPO, env=base_env,
            stdout=subprocess.PIPE, text=True,
        )
        import threading

        # stdout is drained by a daemon thread so the readiness wait below
        # can enforce a REAL deadline — a blocking readline here would hang
        # the whole gate on a server wedged before its first output line
        lines = []
        ready = threading.Event()

        def _drain_stdout():
            for line in proc.stdout:
                lines.append(line)
                if line.strip() == "serving: ready":
                    ready.set()

        reader = threading.Thread(target=_drain_stdout, daemon=True)
        reader.start()
        try:
            # demo prompts are submitted (NOT waited) before the ready line,
            # so a SIGTERM here lands with sequences genuinely in flight
            deadline = time.time() + 300
            while (time.time() < deadline and not ready.is_set()
                   and proc.poll() is None):
                time.sleep(0.2)
            if not ready.is_set():
                proc.kill()
                print("decode gate: server never reached 'serving: ready' "
                      f"(rc {proc.poll()})", file=sys.stderr)
                return 1
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=300)
            except subprocess.TimeoutExpired:
                proc.kill()
                print("decode gate: drain hung after SIGTERM",
                      file=sys.stderr)
                return 1
            reader.join(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if proc.returncode != 75:
            print(f"decode gate: drained server exited {proc.returncode}, "
                  "expected 75", file=sys.stderr)
            return proc.returncode or 1
        summary = json.loads([l for l in lines if l.strip()][-1])
        if summary.get("completed") != n_demo or summary.get("submitted") != n_demo:
            print(
                "decode gate: drain truncated in-flight sequences "
                f"(submitted {summary.get('submitted')}, completed "
                f"{summary.get('completed')}, expected {n_demo})",
                file=sys.stderr,
            )
            return 1
        if not summary.get("in_flight_at_drain"):
            # completed == submitted proves nothing if the engine was idle
            # when the signal landed — the drain contract is only exercised
            # when sequences were genuinely mid-stream
            print(
                "decode gate: SIGTERM landed on an idle engine "
                f"(in_flight_at_drain={summary.get('in_flight_at_drain')}); "
                "the drain contract was not exercised",
                file=sys.stderr,
            )
            return 1
        rc = subprocess.call(
            [sys.executable, inspect, "--validate",
             os.path.join(out_dir, "history.jsonl")],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("decode gate: drained server history failed validation",
                  file=sys.stderr)
            return rc
        print("decode gate: token sweep artifacts valid + SIGTERM drain "
              f"finished all {n_demo} in-flight sequences (exit 75)")
    return 0


def _serving_chaos_gate(env) -> int:
    """Serving-chaos leg (ISSUE 13, README "Serving survivability"): the
    decode sweep re-runs with ``--chaos`` — a replica is killed MID-SWEEP
    via the real ``$TPUDDP_FAULT`` contract and loadgen itself enforces the
    bitwise headline (every migrated stream equal to its undisturbed
    same-seed twin, replica back after probation, typed deadline shed).
    This leg re-checks the OBSERVABLE evidence independently: the summary
    accounting (zero lost streams: completed == submitted - shed, with
    >= 1 failover and >= 1 shed), the ``session_failover`` /
    ``replica_recovered`` event rows in history.jsonl, and schema-v7
    validity of both artifacts."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_schaos_gate_") as out_dir:
        worker_env = dict(env)
        worker_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        bench_json = os.path.join(out_dir, "bench_results.json")
        out = subprocess.run(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "loadgen.py"),
                "--decode", "--quick", "--chaos",
                "--replicas", "2", "--tenants", "2",
                "--history-dir", out_dir, "--out", bench_json,
            ],
            cwd=REPO, env=worker_env, stdout=subprocess.PIPE, text=True,
        )
        sys.stdout.write(out.stdout)
        if out.returncode != 0:
            print(f"serving-chaos gate: loadgen --chaos exited "
                  f"{out.returncode}", file=sys.stderr)
            return out.returncode
        summary = json.loads(
            [l for l in out.stdout.splitlines() if l.strip()][-1]
        )
        if summary.get("failovers", 0) < 1 or summary.get("shed", 0) < 1:
            print(
                "serving-chaos gate: the chaos phase left no evidence "
                f"(failovers={summary.get('failovers')}, "
                f"shed={summary.get('shed')})", file=sys.stderr,
            )
            return 1
        expected = summary.get("submitted", 0) - summary.get("shed", 0)
        if summary.get("completed") != expected:
            print(
                "serving-chaos gate: streams were lost (completed "
                f"{summary.get('completed')} != submitted "
                f"{summary.get('submitted')} - shed {summary.get('shed')})",
                file=sys.stderr,
            )
            return 1
        history = os.path.join(out_dir, "history.jsonl")
        events = set()
        with open(history) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    if rec.get("type") == "event":
                        events.add(rec.get("event"))
        for required in ("session_failover", "replica_unhealthy",
                         "replica_recovered"):
            if required not in events:
                print(
                    f"serving-chaos gate: required event {required!r} "
                    f"missing from history (saw {sorted(events)})",
                    file=sys.stderr,
                )
                return 1
        for artifact in (history, bench_json):
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", artifact],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(
                    f"serving-chaos gate: {os.path.basename(artifact)} "
                    "failed validation", file=sys.stderr,
                )
                return rc
        print(
            "serving-chaos gate: replica killed mid-sweep, zero lost "
            f"streams ({summary['completed']} completed, "
            f"{summary['failovers']} failover(s), {summary['shed']} typed "
            "shed), events + schema v7 verified"
        )
    return 0


def _elastic_gate(env) -> int:
    """Preempt a 4-device run, resume it on 2 via the supervisor, validate."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_elastic_gate_") as out_dir:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # leg 1: train on 4 devices with the bf16_ef residual armed; an
        # injected preempt at the epoch-1 boundary drains to exit 75
        env1 = dict(base_env)
        env1.update({
            "TPUDDP_WORLD_SIZE": "4",
            "TPUDDP_FAULT": "preempt@epoch=1",
            "TPUDDP_CHAOS_TRAINING": '{"comm_hook": "bf16_ef"}',
        })
        rc = subprocess.call(
            [sys.executable, "-u", worker, out_dir, "3"],
            cwd=REPO, env=env1,
        )
        if rc != 75:
            print(f"elastic gate: preempted run exited {rc}, expected 75",
                  file=sys.stderr)
            return rc or 1
        # leg 2: resume on 2 devices through the restart supervisor — the
        # elastic v2 restore redistributes the residual onto the halved world
        env2 = dict(base_env)
        env2["TPUDDP_CHAOS_TRAINING"] = (
            '{"comm_hook": "bf16_ef", "train_batch_size": 16, '
            '"test_batch_size": 16}'
        )
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "supervise.py"),
                "--world", "2", "--max-restarts", "2", "--auto-resume",
                "--backoff-base", "0.2",
                "--",
                sys.executable, "-u", worker, out_dir, "3",
            ],
            cwd=REPO, env=env2,
        )
        if rc != 0:
            print(f"elastic gate: supervised resume exited {rc}",
                  file=sys.stderr)
            return rc
        history = os.path.join(out_dir, "history.jsonl")
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", history],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("elastic gate: merged history.jsonl failed validation",
                  file=sys.stderr)
            return rc
        with open(history) as f:
            records = [json.loads(line) for line in f if line.strip()]
        if not any(r.get("event") == "topology_change" for r in records):
            print("elastic gate: no topology_change event row in the resumed "
                  "history", file=sys.stderr)
            return 1
    return 0


def _reshard_gate(env) -> int:
    """Elastic mesh failover (ISSUE 16): preempt a TP=2 x DP=2 job, round-trip
    its emergency checkpoint offline (W -> W' -> W byte-identical through the
    model-width crossing), then resume it at TP=1 x DP=2 — the reshard-on-load
    path — and validate the merged history names the episode."""
    import json

    import numpy as np

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_tp_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_reshard_gate_") as out_dir:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # leg 1: TP=2 x DP=2 (the worker's default mesh), drained at the
        # epoch-1 boundary -> exit 75 + an emergency v3 checkpoint
        env1 = dict(base_env)
        env1.update({
            "TPUDDP_WORLD_SIZE": "4",
            "TPUDDP_FAULT": "preempt@epoch=1",
        })
        rc = subprocess.call(
            [sys.executable, "-u", worker, out_dir, "3"],
            cwd=REPO, env=env1,
        )
        if rc != 75:
            print(f"reshard gate: preempted TP run exited {rc}, expected 75",
                  file=sys.stderr)
            return rc or 1
        src = os.path.join(out_dir, "ckpt_1.npz")
        # leg 2: the offline round trip through the CLI — TP layout ->
        # canonical -> TP layout must be byte-identical
        down = os.path.join(out_dir, "rt_down.npz")
        back = os.path.join(out_dir, "rt_back.npz")
        for args in (
            [src, "--to", "data=4,model=1", "--out", down],
            [down, "--to", "data=2,model=2", "--out", back],
        ):
            rc = subprocess.call(
                [sys.executable, inspect, "reshard", *args],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(f"reshard gate: tpuddp_inspect reshard {args} exited "
                      f"{rc}", file=sys.stderr)
                return rc
        with np.load(src) as f:
            want = dict(f.items())
        with np.load(back) as f:
            got = dict(f.items())
        keys = {k for k in want if k != "__topology__"}
        if keys != {k for k in got if k != "__topology__"}:
            print("reshard gate: round trip changed the leaf set",
                  file=sys.stderr)
            return 1
        for k in keys:
            if not np.array_equal(want[k], got[k]):
                print(f"reshard gate: round trip not byte-identical at {k}",
                      file=sys.stderr)
                return 1
        # leg 3: resume the SAME run dir at TP=1 x DP=2 — the in-loader
        # reshard (worker sets training.reshard_on_mismatch) re-splits the
        # model-axis leaves onto the surviving mesh
        env3 = dict(base_env)
        env3.update({
            "TPUDDP_WORLD_SIZE": "2",
            "TPUDDP_MODEL_SIZE": "1",
            "TPUDDP_AUTO_RESUME": "1",
        })
        rc = subprocess.call(
            [sys.executable, "-u", worker, out_dir, "3"],
            cwd=REPO, env=env3,
        )
        if rc != 0:
            print(f"reshard gate: cross-shape resume exited {rc}",
                  file=sys.stderr)
            return rc
        history = os.path.join(out_dir, "history.jsonl")
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", history],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("reshard gate: merged history.jsonl failed validation",
                  file=sys.stderr)
            return rc
        with open(history) as f:
            records = [json.loads(line) for line in f if line.strip()]
        changes = [
            r for r in records if r.get("event") == "topology_change"
        ]
        if not any(
            r.get("from_model") == 2 and r.get("to_model") == 1
            for r in changes
        ):
            print("reshard gate: no (model 2 -> 1) topology_change event in "
                  "the resumed history", file=sys.stderr)
            return 1
    return 0


def _snapshot_gate(env) -> int:
    """Async step-granular checkpointing (ISSUE 18): kill a snapshot-armed
    run MID-epoch, inspect the cursor-bearing step snapshot, auto-resume to
    completion, and demand bitwise loss parity with an uninterrupted twin."""
    import json
    import re as _re

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    overrides = json.dumps({
        # scan_steps=1 keeps step dispatches batch-granular so the injected
        # preempt lands mid-epoch between snapshot boundaries
        "snapshot": {"every_steps": 3}, "scan_steps": 1,
    })
    with tempfile.TemporaryDirectory(prefix="tpuddp_snap_gate_") as tmp:
        out_dir = os.path.join(tmp, "run")
        twin_dir = os.path.join(tmp, "twin")
        os.makedirs(out_dir)
        os.makedirs(twin_dir)
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "TPUDDP_CHAOS_TRAINING": overrides,
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # leg 1: the uninterrupted twin — the bitwise reference trajectory
        rc = subprocess.call(
            [sys.executable, "-u", worker, twin_dir, "2"],
            cwd=REPO, env=base_env,
        )
        if rc != 0:
            print(f"snapshot gate: twin run exited {rc}", file=sys.stderr)
            return rc or 1
        # leg 2: same seed, killed mid-epoch-0 by an injected SIGTERM; the
        # drain must flush the async writer and land a step snapshot
        env1 = dict(base_env)
        env1["TPUDDP_FAULT"] = "preempt@step=5"
        rc = subprocess.call(
            [sys.executable, "-u", worker, out_dir, "2"],
            cwd=REPO, env=env1,
        )
        if rc != 75:
            print(f"snapshot gate: preempted run exited {rc}, expected 75",
                  file=sys.stderr)
            return rc or 1
        steps = sorted(
            n for n in os.listdir(out_dir)
            if _re.match(r"^ckpt_\d+_s\d+\.npz$", n)
        )
        if not steps:
            print("snapshot gate: the drain left no ckpt_<epoch>_s<step>.npz "
                  f"step snapshot (dir: {sorted(os.listdir(out_dir))})",
                  file=sys.stderr)
            return 1
        # leg 3: the cursor-bearing ckpt summary — tpuddp_inspect must print
        # the v4 data cursor of the freshest step snapshot
        out = subprocess.run(
            [sys.executable, inspect, "ckpt",
             os.path.join(out_dir, steps[-1])],
            cwd=REPO, env=env, stdout=subprocess.PIPE, text=True,
        )
        sys.stdout.write(out.stdout)
        if out.returncode != 0:
            print(f"snapshot gate: tpuddp_inspect ckpt exited "
                  f"{out.returncode}", file=sys.stderr)
            return out.returncode
        if "cursor (v4):" not in out.stdout:
            print("snapshot gate: inspect summary of the step snapshot "
                  "prints no v4 cursor", file=sys.stderr)
            return 1
        # leg 4: auto-resume — must continue AT the drained step (zero
        # batches replayed), not redo the epoch
        env2 = dict(base_env)
        env2["TPUDDP_AUTO_RESUME"] = "1"
        out = subprocess.run(
            [sys.executable, "-u", worker, out_dir, "2"],
            cwd=REPO, env=env2, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        sys.stdout.write(out.stdout)
        if out.returncode != 0:
            print(f"snapshot gate: resumed run exited {out.returncode}",
                  file=sys.stderr)
            return out.returncode
        if "zero batches replayed" not in out.stdout:
            print("snapshot gate: the resumed run never took the exact-"
                  "resume path (no 'zero batches replayed' line)",
                  file=sys.stderr)
            return 1
        # leg 5: bitwise loss parity + schema-v11 provenance
        def epoch_losses(run_dir):
            with open(os.path.join(run_dir, "history.jsonl")) as f:
                records = [json.loads(l) for l in f if l.strip()]
            return records, {
                r["epoch"]: r["train_loss"]
                for r in records if r["type"] == "epoch"
            }

        records, resumed = epoch_losses(out_dir)
        _, ref = epoch_losses(twin_dir)
        if resumed != ref:
            print(f"snapshot gate: resumed losses {resumed} are not bitwise-"
                  f"equal to the uninterrupted twin's {ref}", file=sys.stderr)
            return 1
        metas = [r for r in records if r["type"] == "run_meta"]
        if not any(
            isinstance(m.get("snapshot"), dict)
            and m["snapshot"].get("every_steps") == 3
            for m in metas
        ):
            print("snapshot gate: no run_meta carries the snapshot "
                  "provenance block", file=sys.stderr)
            return 1
        rc = subprocess.call(
            [sys.executable, inspect, "--validate",
             os.path.join(out_dir, "history.jsonl")],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("snapshot gate: merged history.jsonl failed validation",
                  file=sys.stderr)
            return rc
        print(
            "snapshot gate: mid-epoch kill drained to step snapshot "
            f"{steps[-1]}, cursor inspected, exact resume replayed zero "
            "batches, losses bitwise-equal to the twin, v11 provenance "
            "verified"
        )
    return 0


def _comm_matrix_gate(env) -> int:
    """Compression-matrix leg (ISSUE 9): dryrun trainings across the hook x
    topology grid (none/bf16_ef/int8_ef/topk_ef x flat/hierarchical), each
    producing a history.jsonl that must (a) validate against the typed
    schema, (b) carry the comm accounting fields in its run_meta header,
    (c) show the acceptance byte cuts for the quantized/sparse hooks
    (int8_ef >= 70%, topk_ef >= 85% vs the header's own f32 baseline), and
    (d) finish with a final-epoch train loss within the documented per-hook
    parity bound of the uncompressed flat run
    (tpuddp.parallel.comm.loss_parity_tol). Hierarchical rows must also
    report inter-host bytes BELOW the flat run's total — the topology's
    reason to exist, enforced every gate run."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    sys.path.insert(0, REPO)
    from tpuddp.parallel.comm import loss_parity_tol

    with tempfile.TemporaryDirectory(prefix="tpuddp_comm_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        results = {}
        for hook in ("none", "bf16_ef", "int8_ef", "topk_ef"):
            for topology in ("flat", "hierarchical"):
                out_dir = os.path.join(tmp, f"{hook}_{topology}")
                os.makedirs(out_dir)
                worker_env = dict(base_env)
                worker_env["TPUDDP_CHAOS_TRAINING"] = json.dumps({
                    "comm_hook": hook, "comm_topology": topology,
                    "num_epochs": 3,
                })
                rc = subprocess.call(
                    [sys.executable, "-u", worker, out_dir, "3"],
                    cwd=REPO, env=worker_env,
                )
                if rc != 0:
                    print(f"comm gate: {hook}/{topology} dryrun exited {rc}",
                          file=sys.stderr)
                    return rc or 1
                history = os.path.join(out_dir, "history.jsonl")
                rc = subprocess.call(
                    [sys.executable, inspect, "--validate", history],
                    cwd=REPO, env=env,
                )
                if rc != 0:
                    print(f"comm gate: {hook}/{topology} history failed "
                          "validation", file=sys.stderr)
                    return rc
                with open(history) as f:
                    records = [json.loads(l) for l in f if l.strip()]
                meta = next(r for r in records if r["type"] == "run_meta")
                epochs = [r for r in records if r["type"] == "epoch"]
                if meta.get("comm_topology") != topology:
                    print(f"comm gate: {hook}/{topology} header records "
                          f"topology {meta.get('comm_topology')!r}",
                          file=sys.stderr)
                    return 1
                results[(hook, topology)] = {
                    "meta": meta, "final_loss": epochs[-1]["train_loss"],
                }
        base = results[("none", "flat")]
        f32 = base["meta"]["grad_comm_bytes_per_update_f32"]
        for hook, floor in (("int8_ef", 0.70), ("topk_ef", 0.85)):
            per = results[(hook, "flat")]["meta"]["grad_comm_bytes_per_update"]
            cut = 1 - per / f32
            if cut < floor:
                print(f"comm gate: {hook} byte cut {cut * 100:.1f}% is under "
                      f"the {floor * 100:.0f}% floor", file=sys.stderr)
                return 1
        for (hook, topology), row in results.items():
            tol = loss_parity_tol(hook, base["final_loss"])
            if abs(row["final_loss"] - base["final_loss"]) > tol:
                print(
                    f"comm gate: {hook}/{topology} final-epoch loss "
                    f"{row['final_loss']:.4f} diverged from uncompressed "
                    f"{base['final_loss']:.4f} (documented tol {tol:.4f})",
                    file=sys.stderr,
                )
                return 1
            if topology == "hierarchical":
                inter = row["meta"]["grad_comm_bytes_inter_host"]
                flat_total = results[(hook, "flat")]["meta"][
                    "grad_comm_bytes_per_update"
                ]
                if inter >= flat_total:
                    print(
                        f"comm gate: {hook} hierarchical inter-host bytes "
                        f"{inter} not below the flat total {flat_total}",
                        file=sys.stderr,
                    )
                    return 1
        print("comm gate: byte cuts + loss parity + hierarchical hop split "
              "verified across the hook x topology matrix")
    return 0


def _pipeline_gate(env) -> int:
    """Async-pipeline leg (ISSUE 8): a depth-2 pipelined dryrun must produce
    a schema-valid history whose step_stats windows carry the occupancy
    fields, land bitwise-identical checkpoints to a synchronous (pipeline:
    false) run of the same seed, and keep the step HLO identical pipeline
    on/off (the HLO assertion runs as its test, which lowers both programs)."""
    import json

    import numpy as np

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_pipe_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        dirs = {}
        for mode, pipe_cfg in (("on", '{"depth": 2}'), ("off", "false")):
            out_dir = os.path.join(tmp, mode)
            os.makedirs(out_dir)
            dirs[mode] = out_dir
            worker_env = dict(base_env)
            worker_env["TPUDDP_CHAOS_TRAINING"] = (
                '{"step_stats_every": 4, "pipeline": %s}' % pipe_cfg
            )
            rc = subprocess.call(
                [sys.executable, "-u", worker, out_dir, "2"],
                cwd=REPO, env=worker_env,
            )
            if rc != 0:
                print(f"pipeline gate: {mode} dryrun exited {rc}",
                      file=sys.stderr)
                return rc or 1
        history = os.path.join(dirs["on"], "history.jsonl")
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", history],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("pipeline gate: pipelined history.jsonl failed validation",
                  file=sys.stderr)
            return rc
        with open(history) as f:
            records = [json.loads(line) for line in f if line.strip()]
        windows = [r for r in records if r.get("type") == "step_stats"]
        if not windows or any(
            k not in w
            for w in windows
            for k in ("host_stall_ms", "inflight_depth", "staging_queue_depth")
        ):
            print("pipeline gate: step_stats windows missing the occupancy "
                  "fields", file=sys.stderr)
            return 1
        # bitwise parity: the pipelined run's checkpoints must equal the
        # synchronous run's, leaf for leaf (params, moments, counters — the
        # whole TrainState lands in ckpt_{epoch}.npz)
        for fname in ("ckpt_0.npz", "ckpt_1.npz"):
            a = np.load(os.path.join(dirs["on"], fname), allow_pickle=False)
            b = np.load(os.path.join(dirs["off"], fname), allow_pickle=False)
            if sorted(a.files) != sorted(b.files):
                print(f"pipeline gate: {fname} key sets differ",
                      file=sys.stderr)
                return 1
            for k in a.files:
                if a[k].dtype.kind in "SU" or b[k].dtype.kind in "SU":
                    ok = bool(np.array_equal(a[k], b[k]))
                else:
                    ok = a[k].tobytes() == b[k].tobytes()
                if not ok:
                    print(
                        f"pipeline gate: {fname} leaf {k!r} differs between "
                        "pipelined and synchronous runs", file=sys.stderr,
                    )
                    return 1
        # HLO identity pipeline-on/off: the dedicated test lowers the step
        # program under both configs and compares the text byte for byte.
        # Plain env: tests/conftest.py owns its own 8-device XLA_FLAGS and
        # refuses a world pre-pinned to the gate's 4.
        rc = subprocess.call(
            [
                sys.executable, "-m", "pytest", "-q",
                "tests/test_pipeline.py", "-k", "hlo_identity",
                "-p", "no:cacheprovider",
            ],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("pipeline gate: HLO identity test failed", file=sys.stderr)
            return rc
    return 0


def _overlap_gate(env) -> int:
    """Backward/comm-overlap leg (ISSUE 17): a ``comm_overlap: true`` dryrun
    must produce a schema-v10-valid history whose run_meta ``comm.overlap``
    block records ``enabled: true`` with ``segments >= 2``, land bitwise-
    identical checkpoints to a ``comm_overlap: false`` run of the same seed,
    and the HLO tests must show the K interleaved collectives overlap-on
    that barrier mode lacks (the program shape is the claim; the bitwise
    parity is the proof that it cost nothing)."""
    import json

    import numpy as np

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_overlap_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        dirs = {}
        for mode, flag in (("on", "true"), ("off", "false")):
            out_dir = os.path.join(tmp, mode)
            os.makedirs(out_dir)
            dirs[mode] = out_dir
            worker_env = dict(base_env)
            # bucket_cap_mb=2.0 splits the worker's ToyMLP (3072->256->128
            # ->10 on 32x32x3 synthetic CIFAR) into 2 buckets whose edge
            # lands on a layer boundary, so overlap-on genuinely runs K=2
            # segments rather than degenerating to the barrier program.
            worker_env["TPUDDP_CHAOS_TRAINING"] = (
                '{"comm_hook": "bf16_ef", "bucket_cap_mb": 2.0, '
                '"comm_overlap": %s, "step_stats_every": 4}' % flag
            )
            rc = subprocess.call(
                [sys.executable, "-u", worker, out_dir, "2"],
                cwd=REPO, env=worker_env,
            )
            if rc != 0:
                print(f"overlap gate: {mode} dryrun exited {rc}",
                      file=sys.stderr)
                return rc or 1
        history = os.path.join(dirs["on"], "history.jsonl")
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", history],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("overlap gate: segmented history.jsonl failed validation",
                  file=sys.stderr)
            return rc
        with open(history) as f:
            records = [json.loads(line) for line in f if line.strip()]
        metas = [r for r in records if r.get("type") == "run_meta"]
        overlaps = [
            (m.get("comm") or {}).get("overlap") or {} for m in metas
        ]
        if not overlaps or any(
            not o.get("enabled") or int(o.get("segments") or 0) < 2
            for o in overlaps
        ):
            print("overlap gate: run_meta comm.overlap must report "
                  "enabled=true with segments >= 2, got "
                  f"{overlaps!r}", file=sys.stderr)
            return 1
        # bitwise parity: segmentation reorders the collectives inside the
        # step, it must not move a single bit of the TrainState — params,
        # moments, EF residuals (comm_state), counters, all of it.
        for fname in ("ckpt_0.npz", "ckpt_1.npz"):
            a = np.load(os.path.join(dirs["on"], fname), allow_pickle=False)
            b = np.load(os.path.join(dirs["off"], fname), allow_pickle=False)
            if sorted(a.files) != sorted(b.files):
                print(f"overlap gate: {fname} key sets differ",
                      file=sys.stderr)
                return 1
            for k in a.files:
                if a[k].dtype.kind in "SU" or b[k].dtype.kind in "SU":
                    ok = bool(np.array_equal(a[k], b[k]))
                else:
                    ok = a[k].tobytes() == b[k].tobytes()
                if not ok:
                    print(
                        f"overlap gate: {fname} leaf {k!r} differs between "
                        "segmented and barrier runs", file=sys.stderr,
                    )
                    return 1
        # HLO interleaving: the dedicated tests lower the step program under
        # both configs and assert K collectives with compute between them
        # overlap-on vs a single trailing block overlap-off. Plain env:
        # tests/conftest.py owns its own 8-device XLA_FLAGS.
        rc = subprocess.call(
            [
                sys.executable, "-m", "pytest", "-q",
                "tests/test_overlap.py", "-k", "hlo",
                "-p", "no:cacheprovider",
            ],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("overlap gate: HLO interleaving tests failed",
                  file=sys.stderr)
            return rc
    return 0


def _mesh_gate(env) -> int:
    """2-D mesh leg (ISSUE 14): ``tools/bench_mesh.py --quick`` trains
    transformer_small TP=2xDP=2 AND pure DP=4 at matched global batch
    through the real epoch driver on the 4-device CPU mesh, asserting
    loss-trajectory parity and the per-chip parameter-byte cut in-process.
    This leg re-checks the observable evidence independently: the TP
    history validates under schema v8 and its run_meta carries the mesh
    block ({data: 2, model: 2} + a real tp_rules_hash); the ``model=1``
    configuration lowers to HLO byte-identical with the flat DDP path (the
    dedicated test lowers both programs and compares text); and
    ``tools/bench_trend.py --fresh`` ingests the fresh MULTICHIP-format
    payload without a regression verdict."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_mesh_gate_") as tmp:
        worker_env = dict(env)
        worker_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        bench_json = os.path.join(tmp, "mesh_bench.json")
        out = subprocess.run(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "bench_mesh.py"),
                "--quick", "--history-dir", tmp, "--out", bench_json,
            ],
            cwd=REPO, env=worker_env, stdout=subprocess.PIPE, text=True,
        )
        sys.stdout.write(out.stdout)
        if out.returncode != 0:
            print(f"mesh gate: bench_mesh exited {out.returncode}",
                  file=sys.stderr)
            return out.returncode or 1
        summary = json.loads(
            [l for l in out.stdout.splitlines() if l.strip()][-1]
        )
        history = summary["tp_history"]
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", history],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("mesh gate: TP=2xDP=2 history failed validation",
                  file=sys.stderr)
            return rc
        with open(history) as f:
            meta = next(
                json.loads(l) for l in f
                if l.strip() and json.loads(l).get("type") == "run_meta"
            )
        mesh_block = meta.get("mesh")
        if (
            not isinstance(mesh_block, dict)
            or mesh_block.get("data") != 2
            or mesh_block.get("model") != 2
            or not mesh_block.get("tp_rules_hash")
        ):
            print(f"mesh gate: run_meta mesh block wrong: {mesh_block!r}",
                  file=sys.stderr)
            return 1
        # model=1 HLO byte-identity with the flat DDP path: the dedicated
        # test lowers both programs and compares text. Plain env —
        # tests/conftest.py owns its own 8-device XLA_FLAGS.
        rc = subprocess.call(
            [
                sys.executable, "-m", "pytest", "-q",
                "tests/test_mesh2d.py", "-k", "hlo_identity",
                "-p", "no:cacheprovider",
            ],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("mesh gate: model=1 HLO identity test failed",
                  file=sys.stderr)
            return rc
        rc = subprocess.call(
            [
                sys.executable,
                os.path.join(REPO, "tools", "bench_trend.py"),
                "--fresh", bench_json,
            ],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("mesh gate: bench_trend rejected the fresh mesh payload",
                  file=sys.stderr)
            return rc
        print(
            "mesh gate: TP=2xDP=2 parity "
            f"(worst |dloss| {summary['parity_worst_abs']:.2e}), per-chip "
            f"param cut {summary['param_bytes_cut'] * 100:.1f}%, schema-v8 "
            "mesh block + model=1 HLO identity + trend ingest verified"
        )
    return 0


def _fleet_gate(env) -> int:
    """Fleet-control-plane leg (ISSUE 11): the scripted multi-job chaos
    demo (2 training + 1 serving + 1 late high-priority arrival on one
    pool: kill one, preempt one, autoscale one) must pass its own checks,
    and every job's namespaced history must ALSO validate when this gate
    re-runs tpuddp_inspect over it independently."""
    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_fleet_gate_") as out_dir:
        gate_env = dict(env)
        gate_env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "fleet.py"),
                "chaos-demo", "--out", out_dir,
            ],
            cwd=REPO, env=gate_env,
        )
        if rc != 0:
            print(f"fleet gate: chaos demo exited {rc}", file=sys.stderr)
            return rc
        jobs_dir = os.path.join(out_dir, "jobs")
        job_names = sorted(os.listdir(jobs_dir))
        if len(job_names) < 4:
            print(f"fleet gate: expected >= 4 namespaced job dirs, found "
                  f"{job_names}", file=sys.stderr)
            return 1
        for name in job_names:
            history = os.path.join(jobs_dir, name, "history.jsonl")
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", history],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(f"fleet gate: {name}/history.jsonl failed validation",
                      file=sys.stderr)
                return rc
        print("fleet gate: kill + preempt + autoscale survived with every "
              "namespaced history valid")
    return 0


def _observability_gate(env) -> int:
    """Live-telemetry leg (ISSUE 10): (a) tools/bench_trend.py across the
    committed BENCH_r*.json artifacts — a >10% regression of any best
    same-device row fails the gate; (b) exporter scrape — a serving engine
    stood up with the observability.exporter block must answer /healthz and
    serve the expected /metrics families while live, then drain to exit 75
    with a schema-v5-valid history; (c) flight recorder — a chaos-preempted
    training run (exit 75) must leave a flightrec_preempt.json that
    tpuddp_inspect validates, and the restart supervisor must summarize it
    (--flight-dir) before resuming the run to completion."""
    import json
    import signal
    import time
    import urllib.request

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    rc = subprocess.call(
        [sys.executable, os.path.join(REPO, "tools", "bench_trend.py")],
        cwd=REPO, env=env,
    )
    if rc != 0:
        print("observability gate: bench_trend regression", file=sys.stderr)
        return rc

    # -- exporter scrape leg ------------------------------------------------
    with tempfile.TemporaryDirectory(prefix="tpuddp_obs_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        out_dir = os.path.join(tmp, "serve")
        os.makedirs(out_dir)
        settings = os.path.join(tmp, "settings.yaml")
        with open(settings, "w") as f:
            f.write(
                "out_dir: %s\n"
                "serving:\n"
                "  num_replicas: 2\n"
                "  max_batch_size: 8\n"
                "  stats_window: 16\n"
                "observability:\n"
                "  exporter: true\n"
                "  exporter_port: 0\n" % out_dir
            )
        proc = subprocess.Popen(
            [
                sys.executable, "-u", "-m", "tpuddp.serving",
                "--settings", settings, "--demo", "48", "--serve", "120",
            ],
            cwd=REPO, env=base_env,
        )
        try:
            port_file = os.path.join(out_dir, "exporter.port")
            deadline = time.time() + 120
            port = None
            while time.time() < deadline:
                if os.path.exists(port_file):
                    # line 1 is the port; line 2 the bound host
                    port = int(open(port_file).read().splitlines()[0])
                    break
                if proc.poll() is not None:
                    print("observability gate: serving process died before "
                          f"binding the exporter (rc {proc.returncode})",
                          file=sys.stderr)
                    return proc.returncode or 1
                time.sleep(0.2)
            if port is None:
                print("observability gate: exporter.port never appeared",
                      file=sys.stderr)
                return 1
            # the engine may still be mid-demo: poll until the serving
            # series report traffic (a dead endpoint fails the gate here)
            scraped = None
            while time.time() < deadline:
                health = json.load(urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10
                ))
                if health.get("status") != "ok":
                    print(f"observability gate: /healthz said {health}",
                          file=sys.stderr)
                    return 1
                scraped = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                ).read().decode()
                done = [
                    line for line in scraped.splitlines()
                    if line.startswith("tpuddp_serving_completed_total ")
                ]
                if done and float(done[0].split()[-1]) >= 48:
                    break
                time.sleep(0.2)
            for family in (
                "tpuddp_serving_completed_total",
                "tpuddp_serving_e2e_ms",
                "tpuddp_serving_throughput_rps",
                "tpuddp_serving_replicas_healthy",
            ):
                if family not in (scraped or ""):
                    print(f"observability gate: /metrics is missing "
                          f"{family}", file=sys.stderr)
                    return 1
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if rc != 75:
            print(f"observability gate: drained server exited {rc}, "
                  "expected 75", file=sys.stderr)
            return rc or 1
        rc = subprocess.call(
            [sys.executable, inspect, "--validate",
             os.path.join(out_dir, "history.jsonl")],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("observability gate: drained server history failed "
                  "validation", file=sys.stderr)
            return rc

        # -- flight recorder leg -------------------------------------------
        train_dir = os.path.join(tmp, "train")
        os.makedirs(train_dir)
        env1 = dict(base_env)
        env1.update({
            "TPUDDP_FAULT": "preempt@epoch=1",
            "TPUDDP_CHAOS_TRAINING": '{"step_stats_every": 2}',
        })
        worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
        rc = subprocess.call(
            [sys.executable, "-u", worker, train_dir, "3"],
            cwd=REPO, env=env1,
        )
        if rc != 75:
            print(f"observability gate: preempted run exited {rc}, "
                  "expected 75", file=sys.stderr)
            return rc or 1
        flightrec = os.path.join(train_dir, "flightrec_preempt.json")
        if not os.path.exists(flightrec):
            print("observability gate: no flightrec_preempt.json after the "
                  "exit-75 drain", file=sys.stderr)
            return 1
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", flightrec],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("observability gate: flight recording failed validation",
                  file=sys.stderr)
            return rc
        # the supervisor picks the recording up (--flight-dir) and resumes
        # the run to completion
        resume = subprocess.run(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "supervise.py"),
                "--max-restarts", "2", "--auto-resume",
                "--backoff-base", "0.2", "--flight-dir", train_dir,
                "--",
                sys.executable, "-u", worker, train_dir, "3",
            ],
            cwd=REPO, env=base_env, capture_output=True, text=True,
        )
        if resume.returncode != 0:
            print("observability gate: supervised resume exited "
                  f"{resume.returncode}\n{resume.stdout}\n{resume.stderr}",
                  file=sys.stderr)
            return resume.returncode
        if "flight recording" not in resume.stderr + resume.stdout:
            print("observability gate: supervisor never summarized the "
                  "flight recording", file=sys.stderr)
            return 1
    print("observability gate: bench trend + live scrape + flight "
          "recording verified")
    return 0


def _tracing_gate(env) -> int:
    """Causal-tracing leg (ISSUE 15): (a) a traced training dryrun vs an
    untraced same-seed twin — identical loss trajectories, a valid
    trace_train.json with correctly-nesting spans on the traced side, no
    artifact on the untraced side; (b) a traced serving demo draining to a
    valid trace_serving.json with request-tree spans."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_trace_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # -- leg a: traced vs untraced training twins (same seed 0)
        dirs = {}
        for mode, obs in (("traced", '{"tracing": true}'), ("plain", "null")):
            out_dir = os.path.join(tmp, mode)
            os.makedirs(out_dir)
            dirs[mode] = out_dir
            worker_env = dict(base_env)
            worker_env["TPUDDP_CHAOS_OBS"] = obs
            rc = subprocess.call(
                [sys.executable, "-u", worker, out_dir, "2"],
                cwd=REPO, env=worker_env,
            )
            if rc != 0:
                print(f"tracing gate: {mode} dryrun exited {rc}",
                      file=sys.stderr)
                return rc or 1
        trajectories = {}
        metas = {}
        for mode, out_dir in dirs.items():
            with open(os.path.join(out_dir, "history.jsonl")) as f:
                records = [json.loads(l) for l in f if l.strip()]
            metas[mode] = next(r for r in records if r["type"] == "run_meta")
            trajectories[mode] = [
                (r["epoch"], r["train_loss"], r["test_loss"],
                 r["test_accuracy"])
                for r in records if r["type"] == "epoch"
            ]
        if trajectories["traced"] != trajectories["plain"]:
            print("tracing gate: traced and untraced loss trajectories "
                  f"differ:\n  traced: {trajectories['traced']}\n  plain:  "
                  f"{trajectories['plain']}", file=sys.stderr)
            return 1
        if not isinstance(metas["traced"].get("tracing"), dict):
            print("tracing gate: traced run_meta carries no tracing block",
                  file=sys.stderr)
            return 1
        if metas["plain"].get("tracing") is not None:
            print("tracing gate: UNTRACED run_meta carries a tracing block",
                  file=sys.stderr)
            return 1
        trace_art = os.path.join(dirs["traced"], "trace_train.json")
        if not os.path.exists(trace_art):
            print("tracing gate: traced run left no trace_train.json",
                  file=sys.stderr)
            return 1
        if os.path.exists(os.path.join(dirs["plain"], "trace_train.json")):
            print("tracing gate: UNTRACED run left a trace_train.json",
                  file=sys.stderr)
            return 1
        for target in (trace_art, os.path.join(dirs["traced"], "history.jsonl")):
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", target],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(f"tracing gate: {os.path.basename(target)} failed "
                      "validation", file=sys.stderr)
                return rc
        with open(trace_art) as f:
            payload = json.load(f)
        spans = [
            e for e in payload["traceEvents"]
            if isinstance(e, dict) and e.get("ph") == "X"
        ]
        kinds = {e.get("cat") for e in spans}
        for required in ("epoch", "stage", "dispatch", "readback"):
            if required not in kinds:
                print(f"tracing gate: training trace has no {required!r} "
                      f"spans (saw {sorted(kinds)})", file=sys.stderr)
                return 1
        if payload["tpuddp"]["dropped"] == 0:
            # the validator already enforced no-orphans; double-check here
            # so the gate's contract is explicit even if the validator drifts
            ids = {e["args"]["span_id"] for e in spans}
            orphans = [
                e for e in spans
                if e["args"].get("parent_id") is not None
                and e["args"]["parent_id"] not in ids
            ]
            if orphans:
                print(f"tracing gate: {len(orphans)} orphan parent_id(s) in "
                      "the training trace", file=sys.stderr)
                return 1
        # -- leg b: traced serving demo
        serve_dir = os.path.join(tmp, "serve")
        os.makedirs(serve_dir)
        settings = os.path.join(tmp, "settings.yaml")
        with open(settings, "w") as f:
            f.write(
                "out_dir: %s\n"
                "serving:\n"
                "  num_replicas: 2\n"
                "  max_batch_size: 8\n"
                "  stats_window: 16\n"
                "observability:\n"
                "  tracing: true\n" % serve_dir
            )
        rc = subprocess.call(
            [
                sys.executable, "-u", "-m", "tpuddp.serving",
                "--settings", settings, "--demo", "24",
            ],
            cwd=REPO, env=base_env, stdout=subprocess.DEVNULL,
        )
        if rc != 0:
            print(f"tracing gate: traced serving demo exited {rc}",
                  file=sys.stderr)
            return rc
        serve_trace = os.path.join(serve_dir, "trace_serving.json")
        if not os.path.exists(serve_trace):
            print("tracing gate: serving drain left no trace_serving.json",
                  file=sys.stderr)
            return 1
        for target in (serve_trace, os.path.join(serve_dir, "history.jsonl")):
            rc = subprocess.call(
                [sys.executable, inspect, "--validate", target],
                cwd=REPO, env=env,
            )
            if rc != 0:
                print(f"tracing gate: {os.path.basename(target)} failed "
                      "validation", file=sys.stderr)
                return rc
        with open(serve_trace) as f:
            kinds = {
                e.get("cat")
                for e in json.load(f)["traceEvents"]
                if isinstance(e, dict) and e.get("ph") == "X"
            }
        for required in ("request", "admission", "queue_wait"):
            if required not in kinds:
                print(f"tracing gate: serving trace has no {required!r} "
                      f"spans (saw {sorted(kinds)})", file=sys.stderr)
                return 1
        with open(os.path.join(serve_dir, "history.jsonl")) as f:
            has_summary = any(
                json.loads(l).get("type") == "trace_summary"
                for l in f if l.strip()
            )
        if not has_summary:
            print("tracing gate: serving history has no trace_summary row",
                  file=sys.stderr)
            return 1
    print("tracing gate: traced/untraced twins bitwise-equal, both trace "
          "artifacts schema-v9 valid with nesting span trees")
    return 0


def _autotune_gate(env) -> int:
    """Self-tuning leg (ISSUE 19): (a) a deliberately mis-knobbed traced
    dryrun (synchronous pipeline, per-step snapshots, no comm compression)
    must make ``tpuddp_inspect tune`` fire recommendations across >= 3
    distinct rule classes, each citing its evidence; (b) ``tools/autotune.py
    --quick`` must A/B the advisor's diffs through the real epoch driver and
    write a TUNE report that ``tpuddp_inspect --validate`` accepts under
    schema v12 (the endorsement-honesty contract is validated, not trusted);
    (c) the fleet tuner's apply/measure/revert state machine must pass its
    unit matrix — including the injected-regression auto-revert — via
    ``pytest tests/test_tune.py -k fleet``."""
    import json

    inspect = os.path.join(REPO, "tools", "tpuddp_inspect.py")
    worker = os.path.join(REPO, "tests", "_chaos_train_worker.py")
    with tempfile.TemporaryDirectory(prefix="tpuddp_tune_gate_") as tmp:
        base_env = dict(env)
        base_env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPUDDP_BACKEND": "cpu",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        # -- leg a: the bad-knob dryrun the advisor must see through
        run_dir = os.path.join(tmp, "badknobs")
        os.makedirs(run_dir)
        worker_env = dict(base_env)
        worker_env.update({
            "TPUDDP_CHAOS_TRAINING": json.dumps({
                "pipeline": False,
                "snapshot": {"every_steps": 1, "inflight": 1},
                "step_stats_every": 4,
            }),
            "TPUDDP_CHAOS_OBS": '{"tracing": true}',
        })
        rc = subprocess.call(
            [sys.executable, "-u", worker, run_dir, "2"],
            cwd=REPO, env=worker_env,
        )
        if rc != 0:
            print(f"autotune gate: bad-knob dryrun exited {rc}",
                  file=sys.stderr)
            return rc or 1
        out = subprocess.run(
            [sys.executable, inspect, "tune", run_dir, "--json"],
            cwd=REPO, env=env, stdout=subprocess.PIPE, text=True,
        )
        if out.returncode != 0:
            print(f"autotune gate: tpuddp_inspect tune exited "
                  f"{out.returncode}", file=sys.stderr)
            return out.returncode
        report = json.loads(out.stdout)
        recs = report.get("recommendations") or []
        classes = sorted({r.get("rule_class") for r in recs})
        if len(classes) < 3:
            print(
                "autotune gate: the advisor fired "
                f"{[r.get('rule') for r in recs]} — expected >= 3 distinct "
                f"rule classes on the bad-knob run, got {classes}",
                file=sys.stderr,
            )
            return 1
        if any(not r.get("evidence") for r in recs):
            print("autotune gate: a recommendation shipped without evidence "
                  "citations", file=sys.stderr)
            return 1
        # -- leg b: the A/B probe must measure the diffs and write a report
        # its own reader accepts (validated again here, independently)
        tune_json = os.path.join(tmp, "TUNE_gate.json")
        rc = subprocess.call(
            [
                sys.executable, "-u",
                os.path.join(REPO, "tools", "autotune.py"),
                "--quick", "--out", tune_json,
            ],
            cwd=REPO, env=base_env,
        )
        if rc != 0:
            print(f"autotune gate: autotune --quick exited {rc}",
                  file=sys.stderr)
            return rc
        if not os.path.exists(tune_json):
            print("autotune gate: autotune --quick wrote no report",
                  file=sys.stderr)
            return 1
        rc = subprocess.call(
            [sys.executable, inspect, "--validate", tune_json],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("autotune gate: the TUNE report failed schema-v12 "
                  "validation", file=sys.stderr)
            return rc
        # -- leg c: the online tuner's unit matrix (apply -> measure ->
        # keep/revert, injected regression, endorsement gating). Plain env:
        # tests/conftest.py owns its own 8-device XLA_FLAGS.
        rc = subprocess.call(
            [
                sys.executable, "-m", "pytest", "-q",
                "tests/test_tune.py", "-k", "fleet",
                "-p", "no:cacheprovider",
            ],
            cwd=REPO, env=env,
        )
        if rc != 0:
            print("autotune gate: fleet tuner unit matrix failed",
                  file=sys.stderr)
            return rc
        print(
            f"autotune gate: advisor fired rule classes {classes} on the "
            "bad-knob run, A/B probe report schema-v12 valid, fleet "
            "apply/measure/revert matrix green"
        )
    return 0


def main(argv=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # the full gate never needs a real TPU
    cmd = [
        sys.executable, "-m", "pytest", "tests", "-q",
        "-m", "slow or not slow",
        "-p", "no:cacheprovider",
        *(argv if argv is not None else sys.argv[1:]),
    ]
    rc = subprocess.call(cmd, cwd=REPO, env=env)
    if rc != 0:
        return rc
    rc = _schema_gate(env)
    if rc != 0:
        return rc
    rc = _pipeline_gate(env)
    if rc != 0:
        return rc
    rc = _overlap_gate(env)
    if rc != 0:
        return rc
    rc = _comm_matrix_gate(env)
    if rc != 0:
        return rc
    rc = _mesh_gate(env)
    if rc != 0:
        return rc
    rc = _serving_gate(env)
    if rc != 0:
        return rc
    rc = _decode_gate(env)
    if rc:
        return rc
    rc = _serving_chaos_gate(env)
    if rc != 0:
        return rc
    rc = _elastic_gate(env)
    if rc != 0:
        return rc
    rc = _reshard_gate(env)
    if rc != 0:
        return rc
    rc = _snapshot_gate(env)
    if rc != 0:
        return rc
    rc = _fleet_gate(env)
    if rc != 0:
        return rc
    rc = _observability_gate(env)
    if rc != 0:
        return rc
    rc = _tracing_gate(env)
    if rc != 0:
        return rc
    return _autotune_gate(env)


if __name__ == "__main__":
    sys.exit(main())
