"""Categorize a captured XLA/TPU profiler trace into a per-component device
time breakdown (the analysis behind BASELINE.md's MFU section).

Usage:
    TPUDDP_PROFILE=<dir> python train_native.py --settings_file ...   # capture
    python tools/trace_breakdown.py <dir>                              # analyze

Works on the trace-viewer JSON the profiler writes (vm.trace.json.gz); does
not need the tensorboard profile plugin (whose converter does not match the
installed TF build). Buckets each device op by its `source`/`tf_op`/shape
metadata into: matmul/conv compute, optimizer+weight HBM traffic,
augment/resize, copies/slices, other elementwise.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import sys


def load_ops(trace_dir: str):
    pattern = f"{trace_dir}/**/*.trace.json.gz"
    files = sorted(glob.glob(pattern, recursive=True))
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    events = data["traceEvents"]
    tids = {}
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"]["name"]
        if e.get("ph") == "M" and e.get("name") == "process_name":
            if "TPU" in e["args"].get("name", ""):
                device_pids.add(e["pid"])
    return [
        e
        for e in events
        if e.get("ph") == "X"
        and e["pid"] in device_pids
        and tids.get((e["pid"], e["tid"])) == "XLA Ops"
        and not e["name"].startswith("while")
    ]


def categorize(e) -> str:
    a = e.get("args") or {}
    src, tf_op = a.get("source", ""), a.get("tf_op", "")
    swl = a.get("shape_with_layout", "")
    if "transforms.py" in src or "_resize" in tf_op:
        return "augment/resize"
    # an op whose output tuple repeats a large weight shape is the fused
    # optimizer update (param, m, v) riding on the weight-grad dot
    if "optim" in src or any(
        swl.count(s) >= 2
        for s in ("f32[9216,4096]", "f32[4096,4096]", "f32[4096,10]")
    ):
        return "optimizer+weight traffic"
    if "conv" in tf_op or "dot_general" in tf_op:
        return "matmul/conv compute"
    if "copy" in e["name"] or "slice" in e["name"]:
        return "copies/slices"
    return "other elementwise"


def main(trace_dir: str, steps: int = 0):
    ops = load_ops(trace_dir)
    total = sum(e["dur"] for e in ops)
    by = collections.Counter()
    flops = collections.Counter()
    for e in ops:
        k = categorize(e)
        by[k] += e["dur"]
        flops[k] += float((e.get("args") or {}).get("model_flops", 0) or 0)
    per_step = f" ({total / steps / 1e3:.2f} ms/step)" if steps else ""
    print(f"device op time {total / 1e3:.1f} ms{per_step}")
    for k, d in by.most_common():
        print(
            f"  {k:26s} {d / 1e3:8.1f} ms  {100 * d / total:5.1f}%  "
            f"{flops[k] / 1e12:6.2f} TF"
        )


if __name__ == "__main__":
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 0)
