"""Categorize a captured XLA/TPU profiler trace into a per-component device
time breakdown (the analysis behind BASELINE.md's MFU section).

Usage:
    TPUDDP_PROFILE=<dir> python train_native.py --settings_file ...   # capture
    python tools/trace_breakdown.py <dir>                              # analyze

Works on the trace-viewer JSON the profiler writes (vm.trace.json.gz); does
not need the tensorboard profile plugin (whose converter does not match the
installed TF build). Buckets each device op by its `source`/`tf_op`/shape
metadata into: matmul/conv compute, optimizer+weight HBM traffic,
augment/resize, copies/slices, other elementwise.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import sys


def load_ops(trace_dir: str):
    pattern = f"{trace_dir}/**/*.trace.json.gz"
    files = sorted(glob.glob(pattern, recursive=True))
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(files[-1]) as fh:
        data = json.load(fh)
    events = data["traceEvents"]
    tids = {}
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"]["name"]
        if e.get("ph") == "M" and e.get("name") == "process_name":
            if "TPU" in e["args"].get("name", ""):
                device_pids.add(e["pid"])
    ops = [
        e
        for e in events
        if e.get("ph") == "X"
        and e["pid"] in device_pids
        and tids.get((e["pid"], e["tid"])) == "XLA Ops"
        and not e["name"].startswith("while")
    ]
    if len(events) >= 900_000:
        # The trace-viewer JSON export caps around 1M events; a long epoch's
        # host python spans can crowd device ops out — completely (zero
        # device rows) or partially (an understated breakdown). With no way
        # to tell WHAT got cut, refuse when no device rows survived and warn
        # loudly otherwise: validate a surviving breakdown against known
        # model FLOPs (the BASELINE.md cross-check) before trusting it.
        if not ops:
            raise SystemExit(
                f"trace has {len(events)} events but zero device 'XLA Ops' — "
                "the exporter's ~1M-event cap crowded the device rows out. "
                "Capture a SHORTER window (fewer steps, e.g. "
                "training.synthetic_n: [2048, 256]) and re-run."
            )
        print(
            f"WARNING: trace has {len(events)} events — at the exporter's "
            "~1M-event cap, so rows may be truncated. Cross-check the TF "
            "totals against the model's known FLOPs before trusting this "
            "breakdown (or capture a shorter window).",
            file=sys.stderr,
        )
    return ops


import re

_SHAPE_TOKEN = re.compile(r"\b(?:f32|bf16|f16)\[[\d,]+\]")


def _looks_like_optimizer_update(shape_with_layout: str) -> bool:
    """An op whose output tuple repeats the same weight shape >= 3 times is a
    fused stateful-optimizer update — Adam's (new_param, m, v) riding on the
    weight-grad dot. (A 2-slot optimizer like SGD+momentum would need >= 2,
    but 2 identical outputs also matches fwd activation+stash pairs, so this
    heuristic stays at 3; ops from tpuddp/optim sources are caught by name.)

    Under ``optimizer_state_dtype: bfloat16`` the tuple is
    ``(f32[shape], bf16[shape], bf16[shape])`` — mixed dtypes, so the
    same-dtype >=3 rule misses it. That exact mixed pattern (one f32 master
    + >=2 low-precision moments of the SAME shape) is accepted as a second
    signature. Caveat: a fwd op emitting a same-shape bf16 act+stash pair
    PLUS an f32 upcast of that shape would match it too — tpuddp's traced
    programs contain no such op (the per-bucket TF totals cross-check
    against the model's known FLOPs; see BASELINE.md), but re-verify that
    accounting if this tool is pointed at other programs."""
    if not shape_with_layout.startswith("("):
        return False
    tokens = _SHAPE_TOKEN.findall(shape_with_layout)
    by_dtype = collections.Counter()  # (dtype, shape) -> count
    for t in tokens:
        dtype, shape = t.split("[", 1)
        by_dtype[(dtype, shape)] += 1
    if any(c >= 3 for c in by_dtype.values()):
        return True
    return any(
        dtype != "f32" and c >= 2 and by_dtype.get(("f32", shape), 0) >= 1
        for (dtype, shape), c in by_dtype.items()
    )


def categorize(e) -> str:
    a = e.get("args") or {}
    src, tf_op = a.get("source", ""), a.get("tf_op", "")
    if "transforms.py" in src or "_resize" in tf_op:
        return "augment/resize"
    if "optim" in src or _looks_like_optimizer_update(
        a.get("shape_with_layout", "")
    ):
        # these fused ops contain BOTH the weight-grad dot/conv and the
        # optimizer state update; their byte/flop ratio tells which side
        # dominates (see BASELINE.md's analysis)
        return "weight-grad + optimizer (fused)"
    if "conv" in tf_op or "dot_general" in tf_op:
        return "fwd/input-grad conv+matmul"
    if "copy" in e["name"] or "slice" in e["name"]:
        return "copies/slices"
    return "other elementwise"


def main(trace_dir: str, steps: int = 0):
    ops = load_ops(trace_dir)
    total = sum(e["dur"] for e in ops)
    by = collections.Counter()
    flops = collections.Counter()
    for e in ops:
        k = categorize(e)
        by[k] += e["dur"]
        flops[k] += float((e.get("args") or {}).get("model_flops", 0) or 0)
    per_step = f" ({total / steps / 1e3:.2f} ms/step)" if steps else ""
    print(f"device op time {total / 1e3:.1f} ms{per_step}")
    for k, d in by.most_common():
        print(
            f"  {k:26s} {d / 1e3:8.1f} ms  {100 * d / total:5.1f}%  "
            f"{flops[k] / 1e12:6.2f} TF"
        )


if __name__ == "__main__":
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    main(sys.argv[1], int(sys.argv[2]) if len(sys.argv) > 2 else 0)
