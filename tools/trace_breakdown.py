"""Categorize a captured XLA/TPU profiler trace into a per-component device
time breakdown (the analysis behind BASELINE.md's MFU section).

Usage:
    TPUDDP_PROFILE=<dir> python train_native.py --settings_file ...   # capture
    python tools/trace_breakdown.py <dir>                              # analyze
    python tools/trace_breakdown.py <dir> --merge-host <trace_role.json> \
        --out merged.json                                              # overlay

Works on the trace-viewer JSON the profiler writes (vm.trace.json.gz); does
not need the tensorboard profile plugin (whose converter does not match the
installed TF build). Buckets each device op by its `source`/`tf_op`/shape
metadata into: matmul/conv compute, optimizer+weight HBM traffic,
augment/resize, copies/slices, other elementwise.

Robustness contract: ALL ``*.trace.json.gz`` capture files under the dir are
merged (a multi-step-window run writes one per capture; picking only the
last silently dropped the rest), and events with missing metadata — bare ops
without ``args``, thread-name records without a name, X events without a
``dur`` — are tolerated, never a KeyError.

``--merge-host`` overlays a host-side span artifact (``trace_<role>.json``,
tpuddp/observability/trace.py — the causal tracing plane's export) onto the
device timeline and writes one merged Chrome-trace JSON loadable in
Perfetto: device XLA ops and host epoch/stage/dispatch/readback (or
request/prefill/decode-step) spans on adjacent tracks. Host spans carry
unix-epoch timestamps through their artifact's ``clock_sync`` anchor; device
captures use the profiler's own epoch, so alignment defaults to
``--align earliest`` (shift the host timeline so both start together) —
pass ``--align wall`` only when the device trace is known to be
unix-anchored, or ``--offset-us`` to apply a measured skew (e.g. the
difference of two hosts' heartbeat-shard ``clock`` anchors,
tpuddp/observability/aggregate.py).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import re
import sys


def _capture_files(trace_dir: str):
    pattern = f"{trace_dir}/**/*.trace.json.gz"
    files = sorted(glob.glob(pattern, recursive=True))
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    return files


def _load_events(path: str):
    with gzip.open(path) as fh:
        data = json.load(fh)
    events = data.get("traceEvents")
    if not isinstance(events, list):
        print(f"WARNING: {path} has no traceEvents list; skipped",
              file=sys.stderr)
        return []
    return events


def load_ops(trace_dir: str):
    """Device 'XLA Ops' events from EVERY capture file under ``trace_dir``
    (merged — a step-window run writes one file per capture and a breakdown
    over only the newest understates everything else). Tolerant of bare
    ops: missing ``args``/``name``/``dur`` metadata never raises."""
    all_ops = []
    capped_files = []
    for path in _capture_files(trace_dir):
        events = _load_events(path)
        # the exporter's ~1M-event cap applies PER CAPTURE FILE: three
        # healthy 350k-event captures are not "over the cap" just because
        # they sum past it
        if len(events) >= 900_000:
            capped_files.append(path)
        tids = {}
        device_pids = set()
        for e in events:
            if not isinstance(e, dict):
                continue
            args = e.get("args") or {}
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                # bare metadata (no args.name) is tolerated, not a KeyError
                tids[(e.get("pid"), e.get("tid"))] = args.get("name", "")
            if e.get("ph") == "M" and e.get("name") == "process_name":
                if "TPU" in (args.get("name") or ""):
                    device_pids.add(e.get("pid"))
        all_ops.extend(
            e
            for e in events
            if isinstance(e, dict)
            and e.get("ph") == "X"
            and e.get("pid") in device_pids
            and tids.get((e.get("pid"), e.get("tid"))) == "XLA Ops"
            and not (e.get("name") or "").startswith("while")
        )
    if capped_files:
        # The trace-viewer JSON export caps around 1M events per file; a
        # long epoch's host python spans can crowd device ops out —
        # completely (zero device rows) or partially (an understated
        # breakdown). With no way to tell WHAT got cut, refuse when no
        # device rows survived and warn loudly otherwise: validate a
        # surviving breakdown against known model FLOPs (the BASELINE.md
        # cross-check) before trusting it.
        if not all_ops:
            raise SystemExit(
                f"{len(capped_files)} capture file(s) sit at the exporter's "
                "~1M-event cap and zero device 'XLA Ops' survived — the cap "
                "crowded the device rows out. Capture a SHORTER window "
                "(fewer steps, e.g. training.synthetic_n: [2048, 256]) and "
                "re-run."
            )
        print(
            f"WARNING: {len(capped_files)} capture file(s) at the exporter's "
            "~1M-event cap — rows may be truncated. Cross-check the TF "
            "totals against the model's known FLOPs before trusting this "
            "breakdown (or capture a shorter window).",
            file=sys.stderr,
        )
    return all_ops


_SHAPE_TOKEN = re.compile(r"\b(?:f32|bf16|f16)\[[\d,]+\]")


def _looks_like_optimizer_update(shape_with_layout: str) -> bool:
    """An op whose output tuple repeats the same weight shape >= 3 times is a
    fused stateful-optimizer update — Adam's (new_param, m, v) riding on the
    weight-grad dot. (A 2-slot optimizer like SGD+momentum would need >= 2,
    but 2 identical outputs also matches fwd activation+stash pairs, so this
    heuristic stays at 3; ops from tpuddp/optim sources are caught by name.)

    Under ``optimizer_state_dtype: bfloat16`` the tuple is
    ``(f32[shape], bf16[shape], bf16[shape])`` — mixed dtypes, so the
    same-dtype >=3 rule misses it. That exact mixed pattern (one f32 master
    + >=2 low-precision moments of the SAME shape) is accepted as a second
    signature. Caveat: a fwd op emitting a same-shape bf16 act+stash pair
    PLUS an f32 upcast of that shape would match it too — tpuddp's traced
    programs contain no such op (the per-bucket TF totals cross-check
    against the model's known FLOPs; see BASELINE.md), but re-verify that
    accounting if this tool is pointed at other programs."""
    if not shape_with_layout.startswith("("):
        return False
    tokens = _SHAPE_TOKEN.findall(shape_with_layout)
    by_dtype = collections.Counter()  # (dtype, shape) -> count
    for t in tokens:
        dtype, shape = t.split("[", 1)
        by_dtype[(dtype, shape)] += 1
    if any(c >= 3 for c in by_dtype.values()):
        return True
    return any(
        dtype != "f32" and c >= 2 and by_dtype.get(("f32", shape), 0) >= 1
        for (dtype, shape), c in by_dtype.items()
    )


def categorize(e) -> str:
    a = e.get("args") or {}
    src, tf_op = a.get("source") or "", a.get("tf_op") or ""
    name = e.get("name") or ""
    if "transforms.py" in src or "_resize" in tf_op:
        return "augment/resize"
    if "optim" in src or _looks_like_optimizer_update(
        a.get("shape_with_layout") or ""
    ):
        # these fused ops contain BOTH the weight-grad dot/conv and the
        # optimizer state update; their byte/flop ratio tells which side
        # dominates (see BASELINE.md's analysis)
        return "weight-grad + optimizer (fused)"
    if "conv" in tf_op or "dot_general" in tf_op:
        return "fwd/input-grad conv+matmul"
    if "copy" in name or "slice" in name:
        return "copies/slices"
    return "other elementwise"


def breakdown(trace_dir: str, steps: int = 0) -> None:
    ops = load_ops(trace_dir)
    total = sum(e.get("dur") or 0 for e in ops)
    if total <= 0:
        raise SystemExit("no device op time recorded (all durations missing)")
    by = collections.Counter()
    flops = collections.Counter()
    for e in ops:
        k = categorize(e)
        by[k] += e.get("dur") or 0
        flops[k] += float((e.get("args") or {}).get("model_flops", 0) or 0)
    per_step = f" ({total / steps / 1e3:.2f} ms/step)" if steps else ""
    print(f"device op time {total / 1e3:.1f} ms{per_step}")
    for k, d in by.most_common():
        print(
            f"  {k:26s} {d / 1e3:8.1f} ms  {100 * d / total:5.1f}%  "
            f"{flops[k] / 1e12:6.2f} TF"
        )


def merge_host(
    trace_dir: str,
    host_path: str,
    out_path: str,
    align: str = "earliest",
    offset_us: float = 0.0,
) -> None:
    """Overlay the host span artifact onto the device timeline: one merged
    Chrome-trace JSON with the device events verbatim and the host spans on
    their own process rows (pids offset past the device pids so tracks
    never collide). ``align``:

    - ``earliest`` (default) — shift the host timeline so the earliest host
      span starts where the earliest device event does (the device
      profiler's clock epoch is not unix time, so absolute alignment is
      unknowable without a shared anchor);
    - ``wall`` — trust both timelines as-is (host spans are unix-µs through
      their ``clock_sync`` anchor; correct only for unix-anchored device
      captures).

    ``offset_us`` is added to every host timestamp AFTER alignment — the
    measured-skew knob (difference of two hosts' heartbeat-shard ``clock``
    anchors)."""
    device_events = []
    for path in _capture_files(trace_dir):
        device_events.extend(
            e for e in _load_events(path) if isinstance(e, dict)
        )
    try:
        with open(host_path) as f:
            host = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"cannot parse host trace {host_path}: {e}")
    host_events = [
        e for e in (host.get("traceEvents") or []) if isinstance(e, dict)
    ]
    if not host_events:
        raise SystemExit(f"{host_path} carries no traceEvents")
    # keep host tracks clear of device pids
    device_pids = {
        e.get("pid") for e in device_events if e.get("pid") is not None
    }
    numeric = [p for p in device_pids if isinstance(p, (int, float))]
    pid_base = int(max(numeric) + 1000) if numeric else 1_000_000
    shift = float(offset_us)
    if align == "earliest":
        dev_ts = [
            e["ts"] for e in device_events
            if e.get("ph") == "X" and isinstance(e.get("ts"), (int, float))
        ]
        host_ts = [
            e["ts"] for e in host_events
            if e.get("ph") == "X" and isinstance(e.get("ts"), (int, float))
        ]
        if dev_ts and host_ts:
            shift += min(dev_ts) - min(host_ts)
    elif align != "wall":
        raise SystemExit(f"unknown --align {align!r} (earliest|wall)")
    merged = list(device_events)
    for e in host_events:
        e = dict(e)
        if isinstance(e.get("pid"), (int, float)):
            e["pid"] = pid_base + int(e["pid"])
        if isinstance(e.get("ts"), (int, float)):
            e["ts"] = e["ts"] + shift
        merged.append(e)
    payload = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "tpuddp_merge": {
            "host_artifact": host_path,
            "host_role": (host.get("tpuddp") or {}).get("role"),
            "align": align,
            "host_shift_us": round(shift, 3),
        },
    }
    opener = gzip.open if out_path.endswith(".gz") else open
    with opener(out_path, "wt") as f:
        json.dump(payload, f)
    print(
        f"merged {len(device_events)} device event(s) + {len(host_events)} "
        f"host event(s) -> {out_path} (host timeline shifted "
        f"{shift / 1e3:.3f} ms, align={align})"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Device-trace breakdown + host-span overlay.",
    )
    parser.add_argument("trace_dir", help="profiler capture dir")
    parser.add_argument(
        "steps", nargs="?", type=int, default=0,
        help="steps covered by the capture (prints ms/step)",
    )
    parser.add_argument(
        "--merge-host", metavar="TRACE_JSON",
        help="host span artifact (trace_<role>.json) to overlay onto the "
        "device timeline",
    )
    parser.add_argument(
        "--out", default=None,
        help="merged trace output path (default: merged_trace.json in the "
        "capture dir; .gz writes gzip)",
    )
    parser.add_argument(
        "--align", choices=("earliest", "wall"), default="earliest",
        help="host-vs-device clock alignment (see module doc)",
    )
    parser.add_argument(
        "--offset-us", type=float, default=0.0,
        help="extra host-timeline shift in µs (measured cross-host skew)",
    )
    args = parser.parse_args(argv)
    if args.merge_host:
        out = args.out or f"{args.trace_dir}/merged_trace.json"
        merge_host(
            args.trace_dir, args.merge_host, out,
            align=args.align, offset_us=args.offset_us,
        )
        return 0
    breakdown(args.trace_dir, args.steps)
    return 0


if __name__ == "__main__":
    sys.exit(main())
