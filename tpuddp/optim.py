"""Native optimizers as pure pytree transforms.

The reference uses ``optim.Adam(lr=0.001)`` (multi-GPU-training-torch.py:249);
these implementations follow torch's update rules exactly (bias-corrected Adam,
momentum/nesterov SGD, decoupled-from-grads weight decay matching torch's
L2-into-grad convention) so converged behavior is comparable.

API: ``opt.init(params) -> opt_state``;
``opt.update(grads, opt_state, params) -> (new_params, new_opt_state)``.
Both are jit-safe pure functions over pytrees.

``clip_grad_norm_`` implements the clip-before-aggregate guidance the
reference README documents (README.md, gradient clipping note): under DDP it
must run on the *averaged* gradient, identically on every replica — tpuddp's
train step applies it after the pmean.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


class Optimizer:
    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, opt_state, params) -> Tuple[Any, Any]:
        raise NotImplementedError


class SGDState(NamedTuple):
    momentum: Any


class SGD(Optimizer):
    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
    ):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        if self.momentum == 0.0:
            return SGDState(momentum=None)
        return SGDState(momentum=tmap(jnp.zeros_like, params))

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.momentum == 0.0:
            new_params = tmap(lambda p, g: p - self.lr * g, params, grads)
            return new_params, opt_state
        buf = tmap(lambda b, g: self.momentum * b + g, opt_state.momentum, grads)
        if self.nesterov:
            step = tmap(lambda g, b: g + self.momentum * b, grads, buf)
        else:
            step = buf
        new_params = tmap(lambda p, s: p - self.lr * s, params, step)
        return new_params, SGDState(momentum=buf)


class AdamState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


def _stochastic_round_bf16(x: jax.Array, step: jax.Array, salt: int) -> jax.Array:
    """Round f32 -> bf16 stochastically (probability proportional to distance
    to each neighbor), via the classic bit trick: add sub-ulp dither noise to
    the f32 bit pattern, then truncate the low mantissa bits.

    Why not round-to-nearest: an EMA with decay b close to 1 moves by
    ``(1-b)*(target-x)`` per step — for Adam's v (b2=0.999) that is ~0.1% of
    x, below bf16's half-ulp (~0.2% of x), so nearest-rounding would snap
    every decrement back to the old value and v could never decay from a
    peak. Dithered rounding lets sub-ulp updates accumulate in expectation.

    Why not ``jax.random.bits``: per-element counter-based RNG (threefry and
    even hardware rbg) measured ~12 ms for one AlexNet FC leaf on v5e — more
    than the whole train step, erasing the HBM saving this dtype exists for.
    The noise here is a Weyl sequence ``(A*i + B*t + salt) mod 2^16`` (A, B
    odd): ~3 fused ALU ops per element, value-independent, and for every
    fixed element i the noise over steps t visits all 2^16 thresholds exactly
    once per 2^16 steps — *exact* temporal equidistribution, which is the
    property that keeps the EMA unbiased.

    Layout note: ``i`` is the element's index within the array being rounded,
    so the same logical parameter gets a DIFFERENT (equally valid, still
    unbiased — the per-element temporal equidistribution holds for any fixed
    i) noise realization under weight-update sharding, where moments are
    rounded as flat per-replica shards instead of per-leaf trees. bf16-moment
    runs are therefore reproducible within a layout but not bit-identical
    across layouts.
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    flat_iota = jax.lax.iota(jnp.uint32, x.size).reshape(x.shape)
    t = step.astype(jnp.uint32)
    noise = (
        flat_iota * jnp.uint32(0x9E3779B1)
        + t * jnp.uint32(0x85EBCA77)
        + jnp.uint32(salt & 0xFFFFFFFF)
    ) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    # the masked pattern is exactly representable in bf16, so this cast is exact
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


def _cast_state_tree(tree, dtype, step, salt0: int):
    """Cast a moment tree to its storage dtype; bf16 uses dithered stochastic
    rounding (see :func:`_stochastic_round_bf16`), phase-shifted per leaf."""
    if dtype != jnp.bfloat16:
        return tmap(lambda x: x.astype(dtype), tree)
    flat, treedef = jax.tree_util.tree_flatten(tree)
    out = [
        _stochastic_round_bf16(x, step, salt0 + 0x68E31DA4 * (i + 1))
        for i, x in enumerate(flat)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


class Adam(Optimizer):
    """torch-rule Adam with optional low-precision moment storage.

    ``state_dtype`` (e.g. ``jnp.bfloat16`` or ``"bfloat16"``) stores m/v in
    that dtype while keeping params full-precision masters. The moment math
    itself ALWAYS runs in f32 — stored moments are upcast on read and
    stochastically rounded on write (deterministically keyed off the step
    counter, so runs stay reproducible). On TPU this halves the
    optimizer-state HBM traffic, which profiling showed is the dominant cost
    of the fused weight-grad+update bucket for FC-heavy models (BASELINE.md
    "Where the time goes"); XLA fuses casts and rounding into the update
    kernel so no extra memory passes are materialized.
    Default ``None`` stores moments in f32 regardless of param/grad dtype:
    sub-f32 EMA storage without stochastic rounding would freeze v (see
    :func:`_stochastic_round_bf16`).
    """

    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        state_dtype: Optional[Any] = None,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        if state_dtype is None:
            self.state_dtype = None
        else:
            aliases = {"bf16": "bfloat16", "fp32": "float32", "f32": "float32"}
            if isinstance(state_dtype, str):
                state_dtype = aliases.get(state_dtype, state_dtype)
            try:
                dt = jnp.dtype(state_dtype)
            except TypeError:
                dt = None
            # only these two have a correct storage path: bf16 gets dithered
            # stochastic rounding; any other low-precision dtype would take a
            # plain astype and silently hit the frozen-EMA bug documented on
            # _stochastic_round_bf16 (or overflow, for f16's narrow range)
            if dt not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)):
                raise ValueError(
                    f"unsupported state_dtype {state_dtype!r} (training."
                    "optimizer_state_dtype); use bfloat16 or float32"
                )
            self.state_dtype = dt

    def init(self, params):
        # moments default to f32 storage even for low-precision params: the
        # EMA math must never run below f32 (sub-ulp decrements vanish — see
        # _stochastic_round_bf16), and the storage dtype must match what
        # update() returns so scan carries stay shape/dtype-stable
        dt = self.state_dtype or jnp.float32
        zeros = lambda p: jnp.zeros_like(p, dtype=dt)
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            m=tmap(zeros, params),
            v=tmap(zeros, params),
        )

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        step = opt_state.step + 1
        b1, b2 = self.b1, self.b2
        # EMA math in f32 regardless of grad/param/storage dtype (bf16 math
        # would freeze v: its 0.1% decrement is below bf16's half-ulp)
        f32 = jnp.float32
        m = tmap(
            lambda m_, g: b1 * m_.astype(f32) + (1 - b1) * g.astype(f32),
            opt_state.m, grads,
        )
        v = tmap(
            lambda v_, g: b2 * v_.astype(f32) + (1 - b2) * jnp.square(g.astype(f32)),
            opt_state.v, grads,
        )
        t = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(b1, t)
        bc2 = 1 - jnp.power(b2, t)
        new_params = tmap(
            lambda p, m_, v_: p
            - (self.lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.eps)).astype(p.dtype),
            params,
            m,
            v,
        )
        if self.state_dtype == jnp.bfloat16:
            m = _cast_state_tree(m, self.state_dtype, step, 0x5ADA0000)
            v = _cast_state_tree(v, self.state_dtype, step, 0x7EE70000)
        return new_params, AdamState(step=step, m=m, v=v)


def global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))


def clip_grad_norm_(grads, max_norm: float):
    """Scale grads so their global L2 norm is <= max_norm.
    Returns (clipped_grads, pre-clip norm)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return tmap(lambda g: g * scale, grads), norm
