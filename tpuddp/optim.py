"""Native optimizers as pure pytree transforms.

The reference uses ``optim.Adam(lr=0.001)`` (multi-GPU-training-torch.py:249);
these implementations follow torch's update rules exactly (bias-corrected Adam,
momentum/nesterov SGD, decoupled-from-grads weight decay matching torch's
L2-into-grad convention) so converged behavior is comparable.

API: ``opt.init(params) -> opt_state``;
``opt.update(grads, opt_state, params) -> (new_params, new_opt_state)``.
Both are jit-safe pure functions over pytrees.

``clip_grad_norm_`` implements the clip-before-aggregate guidance the
reference README documents (README.md, gradient clipping note): under DDP it
must run on the *averaged* gradient, identically on every replica — tpuddp's
train step applies it after the pmean.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


class Optimizer:
    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, opt_state, params) -> Tuple[Any, Any]:
        raise NotImplementedError


class SGDState(NamedTuple):
    momentum: Any


class SGD(Optimizer):
    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
    ):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        if self.momentum == 0.0:
            return SGDState(momentum=None)
        return SGDState(momentum=tmap(jnp.zeros_like, params))

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.momentum == 0.0:
            new_params = tmap(lambda p, g: p - self.lr * g, params, grads)
            return new_params, opt_state
        buf = tmap(lambda b, g: self.momentum * b + g, opt_state.momentum, grads)
        if self.nesterov:
            step = tmap(lambda g, b: g + self.momentum * b, grads, buf)
        else:
            step = buf
        new_params = tmap(lambda p, s: p - self.lr * s, params, step)
        return new_params, SGDState(momentum=buf)


class AdamState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


def _stochastic_round_bf16(x: jax.Array, step: jax.Array, salt: int) -> jax.Array:
    """Round f32 -> bf16 stochastically (probability proportional to distance
    to each neighbor), via the classic bit trick: add sub-ulp dither noise to
    the f32 bit pattern, then truncate the low mantissa bits.

    Why not round-to-nearest: an EMA with decay b close to 1 moves by
    ``(1-b)*(target-x)`` per step — for Adam's v (b2=0.999) that is ~0.1% of
    x, below bf16's half-ulp (~0.2% of x), so nearest-rounding would snap
    every decrement back to the old value and v could never decay from a
    peak. Dithered rounding lets sub-ulp updates accumulate in expectation.

    Why not ``jax.random.bits``: per-element counter-based RNG (threefry and
    even hardware rbg) measured ~12 ms for one AlexNet FC leaf on v5e — more
    than the whole train step, erasing the HBM saving this dtype exists for.
    The noise here is a Weyl sequence ``(A*i + B*t + salt) mod 2^16`` (A, B
    odd): ~3 fused ALU ops per element, value-independent, and for every
    fixed element i the noise over steps t visits all 2^16 thresholds exactly
    once per 2^16 steps — *exact* temporal equidistribution, which is the
    property that keeps the EMA unbiased.

    Layout note: ``i`` is the element's index within the array being rounded,
    so the same logical parameter gets a DIFFERENT (equally valid, still
    unbiased — the per-element temporal equidistribution holds for any fixed
    i) noise realization under weight-update sharding, where moments are
    rounded as flat per-replica shards instead of per-leaf trees. bf16-moment
    runs are therefore reproducible within a layout but not bit-identical
    across layouts.
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    flat_iota = jax.lax.iota(jnp.uint32, x.size).reshape(x.shape)
    t = step.astype(jnp.uint32)
    noise = (
        flat_iota * jnp.uint32(0x9E3779B1)
        + t * jnp.uint32(0x85EBCA77)
        + jnp.uint32(salt & 0xFFFFFFFF)
    ) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    # the masked pattern is exactly representable in bf16, so this cast is exact
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


def _cast_state_tree(tree, dtype, step, salt0: int):
    """Cast a moment tree to its storage dtype; bf16 uses dithered stochastic
    rounding (see :func:`_stochastic_round_bf16`), phase-shifted per leaf."""
    if dtype != jnp.bfloat16:
        return tmap(lambda x: x.astype(dtype), tree)
    flat, treedef = jax.tree_util.tree_flatten(tree)
    out = [
        _stochastic_round_bf16(x, step, salt0 + 0x68E31DA4 * (i + 1))
        for i, x in enumerate(flat)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


class Adam(Optimizer):
    """torch-rule Adam with optional low-precision moment storage.

    ``state_dtype`` (e.g. ``jnp.bfloat16`` or ``"bfloat16"``) stores m/v in
    that dtype while keeping params full-precision masters. The moment math
    itself ALWAYS runs in f32 — stored moments are upcast on read and
    stochastically rounded on write (deterministically keyed off the step
    counter, so runs stay reproducible). On TPU this halves the
    optimizer-state HBM traffic, which profiling showed is the dominant cost
    of the fused weight-grad+update bucket for FC-heavy models (BASELINE.md
    "Where the time goes"); XLA fuses casts and rounding into the update
    kernel so no extra memory passes are materialized.
    Default ``None`` stores moments in f32 regardless of param/grad dtype:
    sub-f32 EMA storage without stochastic rounding would freeze v (see
    :func:`_stochastic_round_bf16`).
    """

    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        state_dtype: Optional[Any] = None,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        if state_dtype is None:
            self.state_dtype = None
        else:
            aliases = {"bf16": "bfloat16", "fp32": "float32", "f32": "float32"}
            if isinstance(state_dtype, str):
                state_dtype = aliases.get(state_dtype, state_dtype)
            try:
                dt = jnp.dtype(state_dtype)
            except TypeError:
                dt = None
            # only these two have a correct storage path: bf16 gets dithered
            # stochastic rounding; any other low-precision dtype would take a
            # plain astype and silently hit the frozen-EMA bug documented on
            # _stochastic_round_bf16 (or overflow, for f16's narrow range)
            if dt not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)):
                raise ValueError(
                    f"unsupported state_dtype {state_dtype!r} (training."
                    "optimizer_state_dtype); use bfloat16 or float32"
                )
            self.state_dtype = dt

    def init(self, params):
        # moments default to f32 storage even for low-precision params: the
        # EMA math must never run below f32 (sub-ulp decrements vanish — see
        # _stochastic_round_bf16), and the storage dtype must match what
        # update() returns so scan carries stay shape/dtype-stable
        dt = self.state_dtype or jnp.float32
        zeros = lambda p: jnp.zeros_like(p, dtype=dt)
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            m=tmap(zeros, params),
            v=tmap(zeros, params),
        )

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        step = opt_state.step + 1
        b1, b2 = self.b1, self.b2
        # EMA math in f32 regardless of grad/param/storage dtype (bf16 math
        # would freeze v: its 0.1% decrement is below bf16's half-ulp)
        f32 = jnp.float32
        m = tmap(
            lambda m_, g: b1 * m_.astype(f32) + (1 - b1) * g.astype(f32),
            opt_state.m, grads,
        )
        v = tmap(
            lambda v_, g: b2 * v_.astype(f32) + (1 - b2) * jnp.square(g.astype(f32)),
            opt_state.v, grads,
        )
        t = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(b1, t)
        bc2 = 1 - jnp.power(b2, t)
        new_params = tmap(
            lambda p, m_, v_: p
            - (self.lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.eps)).astype(p.dtype),
            params,
            m,
            v,
        )
        if self.state_dtype == jnp.bfloat16:
            m = _cast_state_tree(m, self.state_dtype, step, 0x5ADA0000)
            v = _cast_state_tree(v, self.state_dtype, step, 0x7EE70000)
        return new_params, AdamState(step=step, m=m, v=v)


# ------------------------------------------------ large-batch optimizers --
#
# LARS / LAMB (You et al., arxiv 1708.03888 / 1904.00962 — the
# MLPerf-on-TPU-pods large-batch recipe, arxiv 1909.09756) rescale every
# layer's update by a trust ratio ||p|| / ||update||, which keeps very large
# global batches (the ones comm compression frees bandwidth for) converging
# where plain SGD/Adam diverge or stall. SGDW is the trust-ratio-free
# decoupled-weight-decay baseline the ablation compares against.
#
# Layer boundaries: in tree mode a "layer" is a pytree leaf. Under
# weight-update sharding the optimizer sees a flat (total/N,) shard instead,
# so LARS/LAMB additionally implement ``update_flat``: per-element leaf ids
# are recovered from the FlatParamSpec's static leaf offsets (a searchsorted
# over the shard's global positions), per-layer norms become segment sums —
# psum'd across the data axis when the vector is sharded — and the trust
# ratios gather back per element. Same leaf boundaries, same math, so the
# sharded update composes with WUS moment sharding exactly as Adam does.


def _flat_segment_ids(spec, start, n: int):
    """Leaf ids of flat-vector positions ``[start, start + n)`` (traced-safe:
    ``start`` may be ``shard_index * shard_n``). Positions past the raw leaf
    sum — the world-multiple padding — land in one extra trailing segment;
    its elements are zeros, so whatever ratio it gets multiplies nothing."""
    import numpy as np

    ends = jnp.asarray(np.cumsum(spec.sizes), jnp.int32)
    positions = start + jax.lax.iota(jnp.int32, n)
    return jnp.searchsorted(ends, positions, side="right"), len(spec.sizes) + 1


def _segment_sqsum(x, seg, num_segments: int, axis_name=None):
    """Per-layer sum of squares of a flat (shard of a) vector; ``axis_name``
    psums the partial sums into global norms when the vector is sharded
    (layer boundaries need not align with shard boundaries)."""
    s = jax.ops.segment_sum(
        jnp.square(x.astype(jnp.float32)), seg, num_segments=num_segments
    )
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
    return s


def _safe_ratio(p_norm, d_norm, scale):
    """``scale * p_norm / d_norm`` where both norms are positive, else 1.0 —
    the LARS/LAMB convention for zero-norm layers (biases at init, frozen
    leaves): fall back to the unscaled update."""
    ok = (p_norm > 0) & (d_norm > 0)
    return jnp.where(ok, scale * p_norm / jnp.where(ok, d_norm, 1.0), 1.0)


class SGDW(Optimizer):
    """SGD with DECOUPLED weight decay (the AdamW-style split: decay scales
    the parameter directly instead of entering the momentum buffer) — the
    trust-ratio-free baseline LARS is ablated against."""

    def __init__(self, lr: float, momentum: float = 0.9, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params):
        if self.momentum == 0.0:
            return SGDState(momentum=None)
        return SGDState(momentum=tmap(jnp.zeros_like, params))

    def update(self, grads, opt_state, params):
        decay = self.lr * self.weight_decay
        if self.momentum == 0.0:
            new_params = tmap(
                lambda p, g: p - self.lr * g - decay * p, params, grads
            )
            return new_params, opt_state
        buf = tmap(
            lambda b, g: self.momentum * b + g, opt_state.momentum, grads
        )
        new_params = tmap(
            lambda p, b: p - self.lr * b - decay * p, params, buf
        )
        return new_params, SGDState(momentum=buf)


class LARSState(NamedTuple):
    momentum: Any


class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling (You et al., arxiv 1708.03888):
    momentum SGD whose per-layer step is rescaled by
    ``trust_coefficient * ||p|| / (||g|| + weight_decay * ||p|| + eps)`` —
    the large-batch recipe that keeps ResNet-class training converging at
    batch sizes where plain SGD's fixed LR diverges (MLPerf on TPU pods,
    arxiv 1909.09756). Weight decay enters the scaled direction (the
    reference formulation), and layers with a zero parameter or gradient
    norm take the unscaled step."""

    def __init__(
        self,
        lr: float,
        momentum: float = 0.9,
        weight_decay: float = 0.0,
        trust_coefficient: float = 0.001,
        eps: float = 1e-9,
    ):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.trust_coefficient = trust_coefficient
        self.eps = eps

    def init(self, params):
        return LARSState(momentum=tmap(jnp.zeros_like, params))

    def _direction(self, g, p, p_sq, g_sq):
        p_n, g_n = jnp.sqrt(p_sq), jnp.sqrt(g_sq)
        ratio = _safe_ratio(
            p_n, g_n + self.weight_decay * p_n + self.eps,
            self.trust_coefficient,
        )
        return ratio * (g + self.weight_decay * p)

    def update(self, grads, opt_state, params):
        d = tmap(
            lambda g, p: self._direction(
                g, p, jnp.sum(jnp.square(p)), jnp.sum(jnp.square(g))
            ),
            grads, params,
        )
        buf = tmap(lambda b, s: self.momentum * b + s, opt_state.momentum, d)
        new_params = tmap(lambda p, b: p - self.lr * b, params, buf)
        return new_params, LARSState(momentum=buf)

    def update_flat(
        self, grads, opt_state, params, spec, axis_name=None, shard_index=None
    ):
        """The flat-vector update over the spec's leaf boundaries — the
        weight-update-sharding seat (``axis_name``/``shard_index`` set by the
        explicit step) and the managed GSPMD seat (both None: the full
        vector is in hand, segment sums are already global)."""
        n = int(grads.shape[0])
        start = 0 if shard_index is None else shard_index * n
        seg, nseg = _flat_segment_ids(spec, start, n)
        p_sq = _segment_sqsum(params, seg, nseg, axis_name)
        g_sq = _segment_sqsum(grads, seg, nseg, axis_name)
        p_n, g_n = jnp.sqrt(p_sq), jnp.sqrt(g_sq)
        ratio = _safe_ratio(
            p_n, g_n + self.weight_decay * p_n + self.eps,
            self.trust_coefficient,
        )
        d = jnp.take(ratio, seg) * (grads + self.weight_decay * params)
        buf = self.momentum * opt_state.momentum + d
        return params - self.lr * buf, LARSState(momentum=buf)


class LAMB(Optimizer):
    """Layer-wise Adaptive Moments (You et al., arxiv 1904.00962): Adam's
    bias-corrected moment direction plus decoupled weight decay, rescaled
    per layer by ``||p|| / ||m̂/(sqrt(v̂)+eps) + wd*p||`` — the trust ratio
    that made BERT train at 32k batch. Moment math runs in f32; zero-norm
    layers take the unscaled step (the reference's φ = identity)."""

    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.0,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            m=tmap(zeros, params),
            v=tmap(zeros, params),
        )

    def _moments(self, g, m, v):
        f32 = jnp.float32
        new_m = self.b1 * m.astype(f32) + (1 - self.b1) * g.astype(f32)
        new_v = self.b2 * v.astype(f32) + (1 - self.b2) * jnp.square(
            g.astype(f32)
        )
        return new_m, new_v

    def _adam_direction(self, m, v, p, bc1, bc2):
        return (m / bc1) / (jnp.sqrt(v / bc2) + self.eps) + (
            self.weight_decay * p
        )

    def update(self, grads, opt_state, params):
        step = opt_state.step + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(self.b1, t)
        bc2 = 1 - jnp.power(self.b2, t)
        m = tmap(
            lambda m_, g: self.b1 * m_.astype(jnp.float32)
            + (1 - self.b1) * g.astype(jnp.float32),
            opt_state.m, grads,
        )
        v = tmap(
            lambda v_, g: self.b2 * v_.astype(jnp.float32)
            + (1 - self.b2) * jnp.square(g.astype(jnp.float32)),
            opt_state.v, grads,
        )

        def leaf(p, m_, v_):
            r = self._adam_direction(m_, v_, p, bc1, bc2)
            ratio = _safe_ratio(
                jnp.sqrt(jnp.sum(jnp.square(p))),
                jnp.sqrt(jnp.sum(jnp.square(r))),
                1.0,
            )
            return p - (self.lr * ratio * r).astype(p.dtype)

        new_params = tmap(leaf, params, m, v)
        return new_params, AdamState(step=step, m=m, v=v)

    def update_flat(
        self, grads, opt_state, params, spec, axis_name=None, shard_index=None
    ):
        """Flat-vector LAMB over the spec's leaf boundaries (see
        :meth:`LARS.update_flat` for the seats)."""
        step = opt_state.step + 1
        t = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(self.b1, t)
        bc2 = 1 - jnp.power(self.b2, t)
        m, v = self._moments(grads, opt_state.m, opt_state.v)
        r = self._adam_direction(m, v, params, bc1, bc2)
        n = int(grads.shape[0])
        start = 0 if shard_index is None else shard_index * n
        seg, nseg = _flat_segment_ids(spec, start, n)
        p_n = jnp.sqrt(_segment_sqsum(params, seg, nseg, axis_name))
        r_n = jnp.sqrt(_segment_sqsum(r, seg, nseg, axis_name))
        ratio = _safe_ratio(p_n, r_n, 1.0)
        new_params = params - self.lr * jnp.take(ratio, seg) * r
        return new_params, AdamState(step=step, m=m, v=v)


def global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))


def clip_grad_norm_(grads, max_norm: float):
    """Scale grads so their global L2 norm is <= max_norm.
    Returns (clipped_grads, pre-clip norm)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return tmap(lambda g: g * scale, grads), norm
