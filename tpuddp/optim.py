"""Native optimizers as pure pytree transforms.

The reference uses ``optim.Adam(lr=0.001)`` (multi-GPU-training-torch.py:249);
these implementations follow torch's update rules exactly (bias-corrected Adam,
momentum/nesterov SGD, decoupled-from-grads weight decay matching torch's
L2-into-grad convention) so converged behavior is comparable.

API: ``opt.init(params) -> opt_state``;
``opt.update(grads, opt_state, params) -> (new_params, new_opt_state)``.
Both are jit-safe pure functions over pytrees.

``clip_grad_norm_`` implements the clip-before-aggregate guidance the
reference README documents (README.md, gradient clipping note): under DDP it
must run on the *averaged* gradient, identically on every replica — tpuddp's
train step applies it after the pmean.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


class Optimizer:
    def init(self, params) -> Any:
        raise NotImplementedError

    def update(self, grads, opt_state, params) -> Tuple[Any, Any]:
        raise NotImplementedError


class SGDState(NamedTuple):
    momentum: Any


class SGD(Optimizer):
    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
    ):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        if self.momentum == 0.0:
            return SGDState(momentum=None)
        return SGDState(momentum=tmap(jnp.zeros_like, params))

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.momentum == 0.0:
            new_params = tmap(lambda p, g: p - self.lr * g, params, grads)
            return new_params, opt_state
        buf = tmap(lambda b, g: self.momentum * b + g, opt_state.momentum, grads)
        if self.nesterov:
            step = tmap(lambda g, b: g + self.momentum * b, grads, buf)
        else:
            step = buf
        new_params = tmap(lambda p, s: p - self.lr * s, params, step)
        return new_params, SGDState(momentum=buf)


class AdamState(NamedTuple):
    step: jax.Array
    m: Any
    v: Any


class Adam(Optimizer):
    def __init__(
        self,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            m=tmap(jnp.zeros_like, params),
            v=tmap(jnp.zeros_like, params),
        )

    def update(self, grads, opt_state, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        step = opt_state.step + 1
        b1, b2 = self.b1, self.b2
        m = tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, opt_state.m, grads)
        v = tmap(lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), opt_state.v, grads)
        t = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(b1, t)
        bc2 = 1 - jnp.power(b2, t)
        new_params = tmap(
            lambda p, m_, v_: p
            - self.lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.eps),
            params,
            m,
            v,
        )
        return new_params, AdamState(step=step, m=m, v=v)


def global_norm(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))


def clip_grad_norm_(grads, max_norm: float):
    """Scale grads so their global L2 norm is <= max_norm.
    Returns (clipped_grads, pre-clip norm)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return tmap(lambda g: g * scale, grads), norm
