"""Host-side batch loaders.

Two loaders mirror the two dataloading shapes in the reference:

- :class:`DataLoader` — a plain single-stream loader (the accelerate
  entrypoint's unsharded loaders, multi-GPU-training-accelerate.py:22-36, and
  its deliberately-unprepared test loader, :129-131 / quirk Q3);
- :class:`ShardedDataLoader` — the DP loader. The reference gives each of N
  single-GPU processes its own ``DataLoader(sampler=DistributedSampler(...))``
  (multi-GPU-training-torch.py:72-101). On TPU one process drives many chips,
  so this loader runs one :class:`DistributedSampler` per *local replica* and
  assembles their microbatches, in mesh order, into the process-local slice of
  the global batch; ``tpuddp.parallel.mesh.shard_batch`` then places it on the
  mesh (multi-host: every process loads ONLY its shard — the global
  permutation stays consistent because every sampler keys off the same
  seed+epoch).

TPU-first batching: every batch has a static shape. Final partial batches are
padded and carry a 0/1 weight vector ``w`` (consumed by the masked loss /
metric math) instead of producing a ragged last batch that would retrigger XLA
compilation.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional, Sequence, Tuple

import queue
import threading

import jax
import numpy as np

from tpuddp.parallel.sampler import DistributedSampler
from tpuddp.utils import batching

try:
    from tpuddp.data import _native
except ImportError:  # missing native package: numpy path only
    class _native:  # type: ignore[no-redef]
        @staticmethod
        def gather_rows(src, indices, pad_rows=0):
            return None


def _fetch(dataset, indices: np.ndarray):
    """Vectorized batch fetch when the dataset supports it."""
    if hasattr(dataset, "get_batch"):
        return dataset.get_batch(indices)
    xs, ys = zip(*(dataset[int(i)] for i in indices))
    return np.stack(xs), np.asarray(ys)


def _pad_batch(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Pad to the static batch size; w marks real samples. The one padding
    implementation is shared with eval fusion and serving
    (tpuddp/utils/batching.py)."""
    return batching.pad_batch(x, y, batch_size)


def _fetch_padded(dataset, indices: np.ndarray, batch_size: int):
    """Fetch + pad in one step. Datasets exposing contiguous ``.images`` /
    ``.labels`` arrays (CIFAR10, SyntheticClassification) take the native C++
    multi-threaded row-gather fast path (tpuddp/data/_native); everything else
    falls back to numpy with identical results."""
    n = len(indices)
    images = getattr(dataset, "images", None)
    labels = getattr(dataset, "labels", None)
    if images is not None and labels is not None:
        x = _native.gather_rows(images, indices, pad_rows=batch_size)
        if x is not None:
            w = np.ones(batch_size, np.float32)
            w[n:] = 0.0
            y = np.zeros(batch_size, labels.dtype)
            y[:n] = labels[np.asarray(indices)]
            return x, y, w
    x, y = _fetch(dataset, indices)
    return _pad_batch(x, y, batch_size)


def _per_sample_nbytes(dataset):
    """Input bytes of one sample (x only), when the dataset exposes a
    contiguous ``.images`` array (the protocol _fetch_padded relies on);
    None otherwise."""
    images = getattr(dataset, "images", None)
    if images is None or not hasattr(images, "itemsize"):
        return None
    return int(np.prod(images.shape[1:])) * images.itemsize


class DataLoader:
    """Single-stream host loader yielding ``(x, y, w)`` numpy batches.

    ``sampler``: optional index source with the DistributedSampler protocol
    (iter + set_epoch). Without one, iterates sequentially or shuffled
    (``shuffle=True``, reshuffled per epoch via ``set_epoch`` like the
    sampler-based path).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        sampler: Optional[DistributedSampler] = None,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    @property
    def batch_nbytes(self):
        """Input bytes of one host batch (x only) — the epoch driver caps the
        auto scan depth by a staging-memory budget with this (loop.py)."""
        per_sample = _per_sample_nbytes(self.dataset)
        return None if per_sample is None else self.batch_size * per_sample

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _indices(self) -> np.ndarray:
        if self.sampler is not None:
            return np.fromiter(iter(self.sampler), dtype=np.int64)
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.Generator(np.random.PCG64(self.seed + self.epoch))
            return rng.permutation(n)
        return np.arange(n)

    def __len__(self) -> int:
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def make_batch_plan(self):
        """Freeze this epoch's order and return ``(n_batches, fetch)`` where
        ``fetch(s)`` assembles batch ``s`` independently of any other batch —
        the random-access protocol PrefetchLoader's worker pool parallelizes
        over. One plan per epoch; ``__iter__`` is defined in terms of it so
        the two can never drift."""
        indices = self._indices()
        steps = len(self)
        batch_size = self.batch_size
        dataset = self.dataset

        def fetch(s: int):
            chunk = indices[s * batch_size : (s + 1) * batch_size]
            return _fetch_padded(dataset, chunk, batch_size)

        return steps, fetch

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        steps, fetch = self.make_batch_plan()
        for s in range(steps):
            yield fetch(s)


class _EpochMemoizedOrder:
    """Materializes a user sampler's order ONCE per epoch and serves the same
    array to every replica's :class:`DistributedSampler`. Required for
    correctness, not just speed: a non-deterministic sampler (e.g. a weighted
    random sampler that doesn't key off the epoch) iterated independently per
    replica — or drawn independently per PROCESS in a multi-host world —
    would give replicas DIFFERENT base orders and silently break shard
    disjointness. Locally the cache guarantees one materialization; across
    processes, process 0's order is broadcast so every host shards the same
    order. The cache invalidates on ``set_epoch`` (the per-epoch contract
    every tpuddp epoch driver honors)."""

    def __init__(self, sampler):
        self.sampler = sampler
        self._cache: Optional[np.ndarray] = None

    def set_epoch(self, epoch: int) -> None:
        set_ep = getattr(self.sampler, "set_epoch", None)
        if set_ep is not None:
            set_ep(epoch)
        self._cache = None

    def __len__(self) -> int:
        return len(self.sampler)

    def _materialize(self) -> np.ndarray:
        if self._cache is None:
            arr = np.fromiter(iter(self.sampler), dtype=np.int64)
            if jax.process_count() > 1:
                from tpuddp.parallel import collectives as col

                arr = np.asarray(col.broadcast_one_to_all(arr), dtype=np.int64)
            self._cache = arr
        return self._cache

    def __array__(self, dtype=None):
        # DistributedSampler._global_indices takes this fast path: the cached
        # ndarray is handed over directly instead of being re-iterated
        # element-by-element once per local replica
        arr = self._materialize()
        return arr.astype(dtype) if dtype is not None else arr

    def __iter__(self):
        return iter(self._materialize())


class ShardedDataLoader:
    """Global-batch DP loader: one instance per process, one sampler per local
    replica. Yields the process-local ``(x, y, w)`` slice of the global batch
    (concat over local replicas in mesh order); pair with
    ``DistributedDataParallel.shard`` / ``mesh.shard_batch`` for placement.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        mesh,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size  # per replica
        self.mesh = mesh
        self.drop_last = drop_last

        flat_devices = list(mesh.devices.flat)
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        model = int(axis_sizes.get("model", 1))
        if model > 1:
            # 2-D ("data", "model") mesh: the DATA-parallel replica set is
            # the data axis only — every device of one model group consumes
            # the SAME rows (the batch lays out P("data"), replicated over
            # "model"), so one sampler per data index, never per device.
            if jax.process_count() > 1:
                raise ValueError(
                    "ShardedDataLoader on a model-parallel mesh is "
                    "single-controller only (parallel.model > 1 is refused "
                    "multi-process at the DDP wrap too)"
                )
            self.world_size = len(flat_devices) // model
            self.local_ranks = list(range(self.world_size))
        else:
            self.world_size = len(flat_devices)
            proc = jax.process_index()
            # global ranks of this process's replicas, in mesh traversal
            # order — must match how NamedSharding lays the global batch
            # across devices.
            self.local_ranks = [
                rank for rank, d in enumerate(flat_devices)
                if d.process_index == proc
            ]
        # base_sampler: a user-supplied full-dataset order source (iter + len
        # + optional set_epoch). Its order is PRESERVED and sharded around:
        # it feeds the per-replica DistributedSamplers as their order_source,
        # so the pad-by-wrap/stride discipline stays the ONE authoritative
        # implementation (parallel/sampler.py) — HF prepare() semantics: a
        # custom sampler rides inside the sharded sampler, it is not replaced.
        self.base_sampler = sampler
        self._order = _EpochMemoizedOrder(sampler) if sampler is not None else None
        self.samplers = [
            DistributedSampler(
                len(dataset),
                num_replicas=self.world_size,
                rank=rank,
                shuffle=shuffle,
                seed=seed,
                order_source=self._order,
            )
            for rank in self.local_ranks
        ]

    def set_epoch(self, epoch: int) -> None:
        """Fan set_epoch to every local replica's sampler (reference
        multi-GPU-training-torch.py:175-178) — and to the user sampler, via
        the epoch memo, when one was supplied."""
        if self._order is not None:
            self._order.set_epoch(epoch)
        for s in self.samplers:
            s.set_epoch(epoch)

    @property
    def batch_nbytes(self):
        """Input bytes of one process-local host batch (x only, all local
        replicas) — the epoch driver caps the auto scan depth by a
        staging-memory budget with this (loop.py)."""
        per_sample = _per_sample_nbytes(self.dataset)
        if per_sample is None:
            return None
        return self.batch_size * len(self.local_ranks) * per_sample

    @property
    def num_samples_per_replica(self) -> int:
        return self.samplers[0].num_samples

    def __len__(self) -> int:
        n = self.num_samples_per_replica
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def make_batch_plan(self):
        """Freeze this epoch's per-replica orders and return
        ``(n_batches, fetch)`` — the random-access protocol PrefetchLoader's
        worker pool parallelizes over (see :meth:`DataLoader.make_batch_plan`).
        """
        per_replica = [s.local_indices() for s in self.samplers]
        steps = len(self)
        batch_size = self.batch_size
        dataset = self.dataset

        def fetch(s: int):
            xs, ys, ws = [], [], []
            for shard in per_replica:
                chunk = shard[s * batch_size : (s + 1) * batch_size]
                x, y, w = _fetch_padded(dataset, chunk, batch_size)
                xs.append(x)
                ys.append(y)
                ws.append(w)
            return np.concatenate(xs), np.concatenate(ys), np.concatenate(ws)

        return steps, fetch

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        steps, fetch = self.make_batch_plan()
        for s in range(steps):
            yield fetch(s)

    def probe_fingerprint(self, x_local: np.ndarray) -> str:
        """Shard-disjointness probe string: a few raw input values per local
        replica (the reference's manual multi-GPU-training-torch.py:112-115
        probe, adapted to NHWC and any input size)."""
        parts = []
        for i, rank in enumerate(self.local_ranks):
            sample = x_local[i * self.batch_size]
            flat = np.asarray(sample).reshape(-1)
            mid = flat.size // 2
            parts.append(f"replica {rank}: {np.array2string(flat[mid : mid + 4], precision=4)}")
        return "; ".join(parts)


class PrefetchLoader:
    """Background-worker prefetch over any loader (the tpuddp analog of the
    reference's ``num_workers=2`` DataLoader workers,
    multi-GPU-training-torch.py:90-98): batch assembly (sampler slicing,
    native gather, padding) overlaps with device compute through a bounded
    queue. Semantics are unchanged — same batches, same order.

    ``workers > 1`` parallelizes batch *assembly* across a thread pool when
    the inner loader exposes the random-access ``make_batch_plan`` protocol
    (both tpuddp loaders do); batches are re-emitted strictly in order, so
    the stream is bitwise-identical to the serial one. Loaders without the
    protocol fall back to one producer thread.

    Hardening contract (the async-pipeline satellite):

    - a worker exception propagates to the consumer with its ORIGINAL
      traceback attached (the producer frame is visible in the report);
    - every worker is reaped when iteration ends — normally, by an
      exception, or by the consumer abandoning the iterator mid-epoch (a
      preemption drain): the bounded queue can never wedge a producer and
      leak its thread;
    - the queue depth is byte-capped against the shared staging budget
      (``tpuddp/utils/batching.py``) via the loader's ``batch_nbytes``, so
      prefetch depth x batch bytes stays bounded host memory.
    """

    _SENTINEL = object()

    def __init__(self, loader, depth: int = 2, workers: int = 1):
        self.loader = loader
        self.depth = max(1, int(depth))
        self.workers = max(1, int(workers))

    # -- delegation so the epoch driver can't tell the difference --
    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def probe_fingerprint(self, x_local):
        probe = getattr(self.loader, "probe_fingerprint", None)
        return probe(x_local) if probe is not None else ""

    def __getattr__(self, name):
        return getattr(self.loader, name)

    def effective_depth(self) -> int:
        """The byte-capped queue depth: ``depth``, bounded by the staging
        budget over one batch's bytes when they are knowable (the shared
        depth policy, ``tpuddp/utils/batching.py::resolve_fuse``)."""
        return batching.resolve_fuse(
            getattr(self.loader, "batch_nbytes", None), cap=self.depth
        )

    def __iter__(self):
        depth = self.effective_depth()
        if self.workers > 1 and hasattr(self.loader, "make_batch_plan"):
            return self._iter_pool(depth)
        return self._iter_serial(depth)

    def _iter_serial(self, depth: int):
        """One producer thread driving the inner loader's own iterator."""
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        stop = threading.Event()
        err = []

        def _put(item) -> bool:
            # bounded put that can always be cancelled: a consumer that
            # abandoned the iterator must be able to reap this thread even
            # with the queue full
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for batch in self.loader:
                    if not _put(batch):
                        return
            except BaseException as e:  # propagate into the consumer
                err.append(e)
            finally:
                _put(self._SENTINEL)

        thread = threading.Thread(
            target=produce, daemon=True, name="tpuddp-prefetch"
        )
        thread.start()
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    break
                yield item
            if err:
                # the exception object still carries the producer-side
                # traceback; re-raising it surfaces the original frames
                raise err[0]
        finally:
            stop.set()
            try:  # unblock a producer stuck on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            thread.join(timeout=5)

    def _iter_pool(self, depth: int):
        """Worker pool over the inner loader's random-access batch plan;
        batches re-emit strictly in order."""
        steps, fetch = self.loader.make_batch_plan()
        lock = threading.Condition()
        results = {}  # batch index -> assembled batch (bounded by depth)
        cursor = {"claim": 0, "emit": 0}
        stop = threading.Event()
        err = []

        def work():
            while not stop.is_set():
                with lock:
                    # claim the next batch index, but never run more than
                    # `depth` batches ahead of the consumer (bounded memory)
                    while (
                        not stop.is_set()
                        and cursor["claim"] < steps
                        and cursor["claim"] - cursor["emit"] >= depth
                    ):
                        lock.wait(0.05)
                    if stop.is_set() or cursor["claim"] >= steps:
                        return
                    s = cursor["claim"]
                    cursor["claim"] += 1
                try:
                    batch = fetch(s)
                except BaseException as e:
                    with lock:
                        err.append(e)
                        stop.set()
                        lock.notify_all()
                    return
                with lock:
                    results[s] = batch
                    lock.notify_all()

        threads = [
            threading.Thread(
                target=work, daemon=True, name=f"tpuddp-prefetch-{i}"
            )
            for i in range(min(self.workers, max(1, steps)))
        ]
        for t in threads:
            t.start()
        try:
            for s in range(steps):
                with lock:
                    while s not in results and not err:
                        lock.wait(0.05)
                        if err:
                            break
                    if err:
                        raise err[0]
                    batch = results.pop(s)
                    cursor["emit"] = s + 1
                    lock.notify_all()
                yield batch
        finally:
            stop.set()
            with lock:
                lock.notify_all()
            for t in threads:
                t.join(timeout=5)
