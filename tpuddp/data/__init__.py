"""Data layer: datasets, host loaders, and device-side transforms."""

from tpuddp.data.loader import DataLoader, ShardedDataLoader  # noqa: F401
from tpuddp.data.synthetic import SyntheticClassification  # noqa: F401

__all__ = ["DataLoader", "ShardedDataLoader", "SyntheticClassification"]
