"""Data layer: datasets, host loaders, and device-side transforms."""

from tpuddp.data.loader import (  # noqa: F401
    DataLoader,
    PrefetchLoader,
    ShardedDataLoader,
)
from tpuddp.data.synthetic import SyntheticClassification  # noqa: F401

__all__ = [
    "DataLoader",
    "PrefetchLoader",
    "ShardedDataLoader",
    "SyntheticClassification",
]
