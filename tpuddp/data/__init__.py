"""Data layer: datasets, host loaders, and device-side transforms."""

from typing import Any, Dict, Sequence, Tuple

from tpuddp.data.loader import (  # noqa: F401
    DataLoader,
    PrefetchLoader,
    ShardedDataLoader,
)
from tpuddp.data.synthetic import SyntheticClassification  # noqa: F401


def load_datasets_for(training: Dict[str, Any], synthetic_fallback: bool = True):
    """(train, test) datasets for ``training.dataset`` — the dataset-dispatch
    layer both entrypoints share (the reference hardcodes CIFAR-10,
    data_and_toy_model.py:8-38; tpuddp adds ``digits`` — real offline data —
    and ``synthetic`` for CI/benchmarks)."""
    name = str(training.get("dataset") or "cifar10")
    if name == "cifar10":
        from tpuddp.data import cifar10

        kwargs = {}
        if training.get("synthetic_n"):
            kwargs["synthetic_n"] = tuple(training["synthetic_n"])
        return cifar10.load_datasets(
            training.get("data_root", "./data"),
            synthetic_fallback=synthetic_fallback,
            **kwargs,
        )
    if name == "digits":
        from tpuddp.data import digits

        return digits.load_datasets()
    if name == "synthetic":
        from tpuddp.data.synthetic import synthetic_uint8_datasets

        n = tuple(training.get("synthetic_n") or (2048, 512))
        return synthetic_uint8_datasets(n[0], n[1])
    raise ValueError(
        f"unknown training.dataset {name!r}; one of cifar10, digits, synthetic"
    )


def flip_for(training: Dict[str, Any]) -> bool:
    """Horizontal-flip augmentation setting: explicit ``training.flip`` wins;
    the default follows the dataset (CIFAR photos are flip-invariant,
    data_and_toy_model.py:15; handwritten digits are not)."""
    f = training.get("flip")
    if f is not None:
        return bool(f)
    return str(training.get("dataset") or "cifar10") != "digits"


def compute_dtype_for(training: Dict[str, Any]):
    """Activation dtype for the device-side transforms: ``bfloat16`` is the
    TPU mixed-precision mode (f32 master params, bf16 activations on the
    MXU; see BASELINE.md's bf16-vs-f32 analysis)."""
    import jax.numpy as jnp

    name = str(training.get("compute_dtype") or "float32")
    table = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}
    if name not in table:
        raise ValueError(
            f"unknown training.compute_dtype {name!r}; one of float32, bfloat16"
        )
    return table[name]


def norm_stats_for(training: Dict[str, Any]) -> Tuple[Sequence[float], Sequence[float]]:
    """Per-dataset normalization (mean, std) for the device-side transforms
    (the reference bakes CIFAR constants into its torchvision pipeline,
    data_and_toy_model.py:17,25)."""
    name = str(training.get("dataset") or "cifar10")
    if name == "digits":
        from tpuddp.data.digits import DIGITS_MEAN, DIGITS_STD

        return DIGITS_MEAN, DIGITS_STD
    from tpuddp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD

    return CIFAR10_MEAN, CIFAR10_STD


__all__ = [
    "DataLoader",
    "PrefetchLoader",
    "ShardedDataLoader",
    "SyntheticClassification",
    "load_datasets_for",
    "norm_stats_for",
    "flip_for",
    "compute_dtype_for",
]
