// tpuddp native data-path: multi-threaded row gather.
//
// The reference's data path leans on torch's native DataLoader machinery
// (worker processes + pinned-memory copies, multi-GPU-training-torch.py:90-98).
// tpuddp's equivalent hot host op is assembling a batch as a row-gather out of
// the in-memory dataset (images[idx]); this implements it as parallel memcpy
// with an optional tail-pad, callable from the loader via ctypes with a numpy
// fallback when the library isn't built.
//
// Build: g++ -O3 -march=native -shared -fPIC gather.cpp -o libtpuddp_gather.so -lpthread
// (driven by tpuddp/data/_native/__init__.py on first use).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

extern "C" {

// Gather n_idx rows of row_bytes each from src into dst, then pad dst with
// copies of its first gathered row up to pad_rows total rows (the loader's
// static-shape final-batch padding). n_threads <= 0 picks hardware threads.
void tpuddp_gather_rows(const uint8_t* src, int64_t row_bytes,
                        const int64_t* idx, int64_t n_idx, int64_t pad_rows,
                        uint8_t* dst, int n_threads) {
  if (n_idx <= 0) return;
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = hw > 0 ? hw : 4;
  // small batches: threading overhead dominates, copy inline
  const int64_t kMinRowsPerThread = 64;
  int threads = static_cast<int>(
      std::min<int64_t>(n_threads, std::max<int64_t>(1, n_idx / kMinRowsPerThread)));

  auto copy_range = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                  static_cast<size_t>(row_bytes));
    }
  };

  if (threads <= 1) {
    copy_range(0, n_idx);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    int64_t chunk = (n_idx + threads - 1) / threads;
    for (int t = 0; t < threads; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = std::min<int64_t>(n_idx, lo + chunk);
      if (lo >= hi) break;
      pool.emplace_back(copy_range, lo, hi);
    }
    for (auto& th : pool) th.join();
  }

  for (int64_t i = n_idx; i < pad_rows; ++i) {
    std::memcpy(dst + i * row_bytes, dst, static_cast<size_t>(row_bytes));
  }
}

int tpuddp_native_abi_version() { return 1; }

}  // extern "C"
