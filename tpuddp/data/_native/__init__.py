"""ctypes bridge to the native (C++) data-path library, with lazy on-demand
compilation and a clean unavailable -> numpy-fallback story (the loader never
requires the native path)."""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import platform
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("tpuddp")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "gather.cpp")


def _isa_tag() -> str:
    """Host ISA fingerprint for the cached-library filename. The build uses
    ``-march=native``, so on a shared filesystem a .so built on a newer-ISA
    node would SIGILL when dlopen'd on an older one — keying the cache path
    by machine + CPU-flags hash makes each ISA build its own copy."""
    flags = b""
    try:
        with open("/proc/cpuinfo", "rb") as f:
            for line in f:
                if line.startswith((b"flags", b"Features")):
                    flags = b" ".join(sorted(line.split(b":", 1)[1].split()))
                    break
    except OSError:
        pass
    digest = hashlib.sha256(flags).hexdigest()[:8]
    return f"{platform.machine()}-{digest}"


_LIB = os.path.join(_DIR, f"libtpuddp_gather.{_isa_tag()}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a temp path and rename into place: concurrent first-use
    # builders (multi-job shared filesystems) and mid-write kills must never
    # leave a half-written .so for another process to dlopen.
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC",
        _SRC, "-o", tmp, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception as e:
        logger.info("native gather build failed (%s); using numpy fallback", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            fresh = (
                os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)
            )
        except OSError:  # e.g. stale .so present but source missing
            fresh = os.path.exists(_LIB)
        if not fresh and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
            lib.tpuddp_gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int,
            ]
            lib.tpuddp_gather_rows.restype = None
            lib.tpuddp_native_abi_version.restype = ctypes.c_int
            assert lib.tpuddp_native_abi_version() == 1
            _lib = lib
        except Exception as e:  # pragma: no cover - load failure path
            logger.info("native gather load failed (%s); using numpy fallback", e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def gather_rows(src: np.ndarray, indices: np.ndarray, pad_rows: int = 0) -> Optional[np.ndarray]:
    """Gather ``src[indices]`` (rows of an (N, ...) array) with optional
    padding to ``pad_rows`` rows by repeating the first gathered row.
    Returns None when the native path can't serve this input (caller falls
    back to numpy)."""
    lib = load()
    if lib is None or not src.flags["C_CONTIGUOUS"] or len(src) == 0:
        return None
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    n = len(idx)
    if n == 0:
        # the C side has no source row to replicate as padding; let the
        # numpy fallback produce the (deterministic) empty/padded result
        return None
    if int(idx.min()) < 0 or int(idx.max()) >= len(src):
        # out-of-range (incl. negative, which numpy would wrap) -> numpy
        # fallback, which raises a clean IndexError instead of a wild memcpy
        return None
    out_rows = max(n, pad_rows)
    row_bytes = src.strides[0]
    out = np.empty((out_rows,) + src.shape[1:], dtype=src.dtype)
    lib.tpuddp_gather_rows(
        src.ctypes.data, row_bytes,
        idx.ctypes.data, n, out_rows,
        out.ctypes.data, 0,
    )
    return out
