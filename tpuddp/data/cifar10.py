"""CIFAR-10 — torchvision-free loader (reference data_and_toy_model.py:8-38).

Reads either on-disk format (``cifar-10-batches-py`` pickle batches or
``cifar-10-batches-bin`` binaries) from ``root``/``$TPUDDP_DATA``. Images stay
**uint8 NHWC 32x32** in host memory: tpuddp's TPU-first pipeline ships raw
bytes to HBM and does resize/flip/normalize on-chip inside the jitted step
(tpuddp.data.transforms), cutting host->device traffic ~196x vs the
reference's CPU-side resize-to-224 float32 tensors (per sample:
224*224*3*4 B vs 32*32*3 B).

Zero-egress environments: ``download=True`` attempts the canonical URL but a
missing dataset raises a clear error; callers that just need a runnable
tutorial (entrypoints, CI) use ``load_datasets(synthetic_fallback=True)``.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tarfile
from typing import Optional, Tuple

import numpy as np

from tpuddp.data.synthetic import SyntheticClassification

URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
PY_DIR = "cifar-10-batches-py"
BIN_DIR = "cifar-10-batches-bin"
TRAIN_PY = [f"data_batch_{i}" for i in range(1, 6)]
TEST_PY = ["test_batch"]
TRAIN_BIN = [f"data_batch_{i}.bin" for i in range(1, 6)]
TEST_BIN = ["test_batch.bin"]

# Normalization constants the reference bakes in (data_and_toy_model.py:17,25).
CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR10_STD = (0.2023, 0.1994, 0.2010)


def _load_py_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # -> NHWC
    labels = np.asarray(d[b"labels"], dtype=np.int32)
    return np.ascontiguousarray(data), labels


def _load_bin_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, dtype=np.uint8).reshape(-1, 3073)
    labels = raw[:, 0].astype(np.int32)
    data = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(data), labels


def _search_roots(root: Optional[str]):
    roots = []
    if root:
        roots.append(root)
    env = os.environ.get("TPUDDP_DATA")
    if env:
        roots.append(env)
    roots.append("./data")
    return roots


def find_cifar10(root: Optional[str] = None) -> Optional[Tuple[str, str]]:
    """Locate an extracted CIFAR-10 copy. Returns (dir, format) or None."""
    for r in _search_roots(root):
        for sub, fmt in ((PY_DIR, "py"), (BIN_DIR, "bin")):
            d = os.path.join(r, sub)
            if os.path.isdir(d):
                return d, fmt
        # tolerate pointing straight at the batches dir
        if os.path.basename(r) in (PY_DIR, BIN_DIR) and os.path.isdir(r):
            return r, ("py" if os.path.basename(r) == PY_DIR else "bin")
    return None


def _remove_quietly(path: str) -> None:
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


def _maybe_download(root: str) -> None:
    """Fetch + extract the archive with retry/backoff (3 attempts, jittered —
    flaky egress is the normal case on shared clusters). Downloads land in a
    ``.part`` file first and are published by rename; a failed attempt removes
    its partial file, and a corrupt archive (truncated by an earlier kill) is
    deleted before the retry re-downloads — a bad attempt must not poison the
    next run."""
    from tpuddp.resilience.retry import RetryPolicy, retry

    archive = os.path.join(root, "cifar-10-python.tar.gz")
    os.makedirs(root, exist_ok=True)

    def attempt():
        if not os.path.exists(archive):
            import urllib.request

            part = archive + ".part"
            try:
                # urlretrieve has no timeout knob — a stalled connection would
                # block attempt 1 forever and the retry wrapper would never
                # run. Stream through urlopen with a socket timeout instead.
                with urllib.request.urlopen(URL, timeout=60) as resp, open(
                    part, "wb"
                ) as out:
                    shutil.copyfileobj(resp, out)
                os.replace(part, archive)
            except BaseException:
                _remove_quietly(part)
                raise
        try:
            with tarfile.open(archive, "r:gz") as tar:
                tar.extractall(root)
        except (tarfile.TarError, EOFError, OSError):
            _remove_quietly(archive)
            raise

    retry(
        attempt,
        RetryPolicy(max_attempts=3, base_delay=1.0, max_delay=10.0),
        describe=f"CIFAR-10 download from {URL} into {root}",
    )


class CIFAR10:
    """In-memory CIFAR-10 split with the vectorized ``get_batch`` fast path.
    Images: uint8 (N, 32, 32, 3); labels: int32 (N,)."""

    def __init__(self, root: str = "./data", train: bool = True, download: bool = False):
        found = find_cifar10(root)
        if found is None and download:
            try:
                _maybe_download(root)
            except Exception as e:
                raise FileNotFoundError(
                    f"CIFAR-10 not found under {root} and download failed ({e}). "
                    "Place cifar-10-batches-py/ or cifar-10-batches-bin/ under the "
                    "data root or set TPUDDP_DATA."
                ) from e
            found = find_cifar10(root)
        if found is None:
            raise FileNotFoundError(
                f"CIFAR-10 not found (searched {_search_roots(root)}); pass "
                "download=True or stage the dataset."
            )
        d, fmt = found
        names = (TRAIN_PY if train else TEST_PY) if fmt == "py" else (TRAIN_BIN if train else TEST_BIN)
        loader = _load_py_batch if fmt == "py" else _load_bin_batch
        xs, ys = zip(*(loader(os.path.join(d, n)) for n in names))
        self.images = np.concatenate(xs)
        self.labels = np.concatenate(ys)
        self.num_classes = 10

    def __len__(self) -> int:
        return len(self.labels)

    def __getitem__(self, i):
        return self.images[i], self.labels[i]

    def get_batch(self, indices):
        idx = np.asarray(indices)
        return self.images[idx], self.labels[idx]


def load_datasets(
    root: str = "./data",
    download: bool = True,
    synthetic_fallback: bool = False,
    synthetic_n: Tuple[int, int] = (2048, 512),
):
    """(train, test) datasets — parity with the reference's ``load_datasets()``
    (data_and_toy_model.py:8-38), minus host-side transforms (those run
    on-device; see tpuddp.data.transforms). ``synthetic_fallback`` substitutes
    a seeded synthetic uint8 dataset when CIFAR-10 is unavailable, so the
    tutorial entrypoints run in zero-egress/CI environments."""
    try:
        return (
            CIFAR10(root, train=True, download=download),
            CIFAR10(root, train=False, download=download),
        )
    except FileNotFoundError:
        if not synthetic_fallback:
            raise
        import logging

        logging.getLogger("tpuddp").warning(
            "CIFAR-10 unavailable; using synthetic uint8 stand-in datasets"
        )
        from tpuddp.data.synthetic import synthetic_uint8_datasets

        return synthetic_uint8_datasets(synthetic_n[0], synthetic_n[1])
