"""Handwritten-digits dataset (scikit-learn ``load_digits``) — the real-image
workload for zero-egress environments.

The reference's workload is real CIFAR-10 (data_and_toy_model.py:8-38), which
requires a network download; in an egress-free environment the only *real*
(non-synthetic) image-classification data available offline is scikit-learn's
bundled digits set: 1,797 genuine 8x8 handwritten digit scans (a UCI/NIST
derivative). It is small, but it is real — training on it demonstrates actual
generalization (train/test accuracy on human-written data) end to end through
the same entrypoints, loaders, augmentation, and checkpoint paths that the
CIFAR-10 configuration uses.

Format matches the CIFAR10 loader contract (uint8 NHWC images, int32 labels,
vectorized ``get_batch``): pixel intensities 0..16 are rescaled to 0..255 and
the gray channel is replicated to RGB so every device-side transform and model
stem works unchanged.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from tpuddp.data.synthetic import SyntheticClassification

# Per-channel normalization constants for digits (computed once from the full
# set after the 0..16 -> 0..255 rescale; gray replicated to 3 channels).
DIGITS_MEAN = (0.3054, 0.3054, 0.3054)
DIGITS_STD = (0.3757, 0.3757, 0.3757)


def _load_arrays() -> Tuple[np.ndarray, np.ndarray]:
    from sklearn.datasets import load_digits as _sk_load

    bunch = _sk_load()
    # (N, 8, 8) float 0..16 -> uint8 NHWC 0..255, gray -> RGB
    imgs = np.round(bunch.images * (255.0 / 16.0)).astype(np.uint8)
    imgs = np.repeat(imgs[..., None], 3, axis=-1)
    labels = bunch.target.astype(np.int32)
    return np.ascontiguousarray(imgs), labels


def load_datasets(n_test: int = 360, seed: int = 0):
    """(train, test) split of the 1,797 digits with a deterministic seeded
    permutation (load_digits is class-ordered in blocks; an unshuffled split
    would skew the label distribution). Defaults to a 1,437/360 (80/20) split."""
    images, labels = _load_arrays()
    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(labels))
    images, labels = images[perm], labels[perm]
    full = SyntheticClassification.from_arrays(images, labels)
    return full.split(n_test)
