"""Synthetic, learnable classification datasets for CI and benchmarks.

Replaces the reference's always-download-CIFAR assumption
(data_and_toy_model.py:31-36) for test environments: deterministic Gaussian
class clusters, so loss actually decreases and parity tests have signal.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class SyntheticClassification:
    """x = class_mean[y] + noise. Arrays live in host memory; ``get_batch``
    does vectorized fancy-indexing (the fast path loaders prefer)."""

    def __init__(
        self,
        n: int = 1024,
        shape: Tuple[int, ...] = (32, 32, 3),
        num_classes: int = 10,
        noise: float = 0.5,
        seed: int = 0,
        dtype=np.float32,
    ):
        rng = np.random.RandomState(seed)
        self.num_classes = num_classes
        self.labels = rng.randint(0, num_classes, size=n).astype(np.int32)
        means = rng.randn(num_classes, *shape).astype(np.float32)
        self.images = (
            means[self.labels] + noise * rng.randn(n, *shape).astype(np.float32)
        ).astype(dtype)

    def __len__(self) -> int:
        return len(self.labels)

    def __getitem__(self, i):
        return self.images[i], self.labels[i]

    def get_batch(self, indices):
        idx = np.asarray(indices)
        return self.images[idx], self.labels[idx]

    @classmethod
    def from_arrays(cls, images: np.ndarray, labels: np.ndarray):
        ds = cls.__new__(cls)
        ds.images = images
        ds.labels = labels
        ds.num_classes = int(labels.max()) + 1 if len(labels) else 0
        return ds

    def split(self, n_test: int):
        """(train, test) views sharing this dataset's class distribution —
        a real generalization split, unlike two differently-seeded sets."""
        return (
            self.from_arrays(self.images[:-n_test], self.labels[:-n_test]),
            self.from_arrays(self.images[-n_test:], self.labels[-n_test:]),
        )


def synthetic_uint8_datasets(n_train: int = 2048, n_test: int = 512, seed: int = 0):
    """(train, test) uint8 image datasets in the CIFAR loader's format — the
    single source for every synthetic stand-in (the cifar10 fallback and the
    'synthetic' dataset name must draw the same distribution)."""
    full = SyntheticClassification(n=n_train + n_test, shape=(32, 32, 3), seed=seed)
    full.images = np.clip(full.images * 40 + 128, 0, 255).astype(np.uint8)
    return full.split(n_test)
