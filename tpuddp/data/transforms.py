"""Device-side image transforms — the TPU-first replacement for the
reference's CPU-side torchvision pipeline (data_and_toy_model.py:13-29).

The reference resizes every 32x32 CIFAR image to 224x224 float32 on the host
and ships ~588 KB/sample through the dataloader; tpuddp ships the raw 3 KB
uint8 sample to HBM and runs Resize + RandomHorizontalFlip + Normalize
*inside* the jitted train step, where XLA fuses the elementwise work into the
surrounding compute. The augment hook signature matches
``training.step.build_train_step(augment=...)``: ``augment(rng, x) -> x``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpuddp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD


def _to_float(x: jax.Array) -> jax.Array:
    """uint8 [0,255] -> float32 [0,1] (torchvision ToTensor semantics); pass
    floats through unchanged."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(jnp.float32)
    return x.astype(jnp.float32) / 255.0


def resize(x: jax.Array, size: int) -> jax.Array:
    """Bilinear resize of an NHWC batch to (size, size) — Resize(224) analog."""
    n, _, _, c = x.shape
    return jax.image.resize(x, (n, size, size, c), method="bilinear")


def normalize(
    x: jax.Array,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
) -> jax.Array:
    return (x - jnp.asarray(mean, x.dtype)) / jnp.asarray(std, x.dtype)


def random_horizontal_flip(rng: jax.Array, x: jax.Array, p: float = 0.5) -> jax.Array:
    """Per-sample flip (torchvision RandomHorizontalFlip): one Bernoulli per
    image, applied via a select — no dynamic shapes, fully fusible."""
    flip = jax.random.bernoulli(rng, p, (x.shape[0], 1, 1, 1))
    return jnp.where(flip, x[:, :, ::-1, :], x)


def make_train_augment(
    size: Optional[int] = 224,
    flip: bool = True,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    compute_dtype=jnp.float32,
):
    """The reference's transform_train (Resize, RandomHorizontalFlip, ToTensor,
    Normalize — data_and_toy_model.py:13-20), reordered so the cheap ops run on
    the small 32x32 image and the resize output feeds the conv directly."""

    def augment(rng: jax.Array, x: jax.Array) -> jax.Array:
        x = _to_float(x)
        if flip:
            x = random_horizontal_flip(rng, x)
        x = normalize(x, mean, std)
        if size is not None and (x.shape[1] != size or x.shape[2] != size):
            x = resize(x, size)
        return x.astype(compute_dtype)

    return augment


def make_eval_transform(
    size: Optional[int] = 224,
    mean: Sequence[float] = CIFAR10_MEAN,
    std: Sequence[float] = CIFAR10_STD,
    compute_dtype=jnp.float32,
):
    """transform_test analog (no flip, data_and_toy_model.py:22-29)."""

    def transform(x: jax.Array) -> jax.Array:
        x = _to_float(x)
        x = normalize(x, mean, std)
        if size is not None and (x.shape[1] != size or x.shape[2] != size):
            x = resize(x, size)
        return x.astype(compute_dtype)

    return transform
