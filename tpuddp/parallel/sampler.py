"""DistributedSampler — exact-semantics, torch-free reimplementation.

Owns the contract the reference delegates to
``torch.utils.data.DistributedSampler`` (SURVEY.md §2b #12), exercised at
multi-GPU-training-torch.py:80-83,175-178:

- per-epoch deterministic permutation keyed by ``seed + epoch`` via
  :meth:`set_epoch` — without it, every epoch replays the same order (the
  pitfall documented at reference README.md:82-84);
- pads the index list by wrapping (repeating head samples) until its length is
  divisible by ``num_replicas`` (or drops the tail with ``drop_last``);
- each rank takes the strided slice ``indices[rank::num_replicas]`` — shards
  are disjoint and equal-sized.

The permutation source is numpy PCG64 rather than torch's Philox, so the
*semantics* (deterministic, epoch-keyed, identical across ranks) match while
the concrete ordering differs — which the reference never depends on.
"""

from __future__ import annotations

import math
from typing import Iterator, Optional, Sized, Union

import numpy as np


class DistributedSampler:
    """Shards dataset indices across the data-parallel world.

    Parameters mirror torch's: ``dataset`` (anything with ``len``, or an int
    length), ``num_replicas``, ``rank``, ``shuffle``, ``seed``, ``drop_last``.
    """

    def __init__(
        self,
        dataset: Union[Sized, int],
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        order_source=None,
    ):
        """``order_source``: optional externally-supplied base order (an
        iterable of dataset indices with ``len``) that REPLACES the seeded
        permutation while keeping this class's pad/drop_last/stride discipline
        authoritative — the mechanism behind preserving a user sampler's order
        in ``Accelerator.prepare`` (HF semantics: the custom sampler rides
        inside the sharded sampler). ``shuffle`` is ignored when set."""
        if num_replicas is None or rank is None:
            raise ValueError("num_replicas and rank are required")
        if not (0 <= rank < num_replicas):
            raise ValueError(f"rank {rank} not in [0, {num_replicas})")
        self.dataset_len = dataset if isinstance(dataset, int) else len(dataset)
        self.num_replicas = int(num_replicas)
        self.rank = int(rank)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.drop_last = bool(drop_last)
        self.order_source = order_source
        self.epoch = 0

        # sizes derive from the order's length when one is supplied (it may
        # be a subset of the dataset), else from the dataset length
        base_len = self.dataset_len if order_source is None else len(order_source)
        self._base_len = base_len
        if self.drop_last and base_len % self.num_replicas != 0:
            self.num_samples = base_len // self.num_replicas
        else:
            self.num_samples = math.ceil(base_len / self.num_replicas)
        self.total_size = self.num_samples * self.num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Re-key the shuffle for a new epoch (reference usage at
        multi-GPU-training-torch.py:175-178). Must be called before iterating
        each epoch, on every rank, with the same value."""
        self.epoch = int(epoch)

    def _global_indices(self) -> np.ndarray:
        if self.order_source is not None:
            src = self.order_source
            if hasattr(src, "__array__"):
                # array-backed source (e.g. the loader's epoch memo): take
                # the ndarray directly, no per-element re-iteration
                indices = np.asarray(src, dtype=np.int64)
            else:
                indices = np.fromiter(iter(src), dtype=np.int64)
            if len(indices) != self._base_len:
                raise ValueError(
                    f"order_source produced {len(indices)} indices but "
                    f"declared len {self._base_len}; shard sizes were computed "
                    "from the declared length"
                )
        elif self.shuffle:
            rng = np.random.Generator(np.random.PCG64(self.seed + self.epoch))
            indices = rng.permutation(self.dataset_len)
        else:
            indices = np.arange(self.dataset_len)

        if not self.drop_last:
            padding = self.total_size - len(indices)
            if padding > 0:
                if padding <= len(indices):
                    indices = np.concatenate([indices, indices[:padding]])
                else:
                    reps = math.ceil(padding / len(indices))
                    indices = np.concatenate(
                        [indices, np.tile(indices, reps)[:padding]]
                    )
        else:
            indices = indices[: self.total_size]
        assert len(indices) == self.total_size
        return indices

    def local_indices(self) -> np.ndarray:
        """This rank's disjoint strided shard of the epoch permutation."""
        shard = self._global_indices()[self.rank : self.total_size : self.num_replicas]
        assert len(shard) == self.num_samples
        return shard

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples
