"""XLA collectives over ICI/DCN — the tpuddp communication backend.

This module owns the contracts the reference delegates to torch.distributed /
NCCL (SURVEY.md §2b #11):

- ``all_reduce``    ~ ``dist.all_reduce`` (default SUM), used x5 per epoch for
                     metric aggregation (multi-GPU-training-torch.py:198-204)
- ``pmean``         ~ DDP's gradient averaging (the implicit allreduce inside
                     ``loss.backward()``, multi-GPU-training-torch.py:125)
- ``barrier``       ~ ``dist.barrier()`` (multi-GPU-training-torch.py:194,223)
- ``broadcast_one_to_all`` ~ DDP's rank-0 parameter broadcast at wrap time
                     (multi-GPU-training-torch.py:245)

The in-jit functions (psum/pmean/all_gather/...) are thin, named wrappers over
``jax.lax`` collectives: on TPU these compile to XLA collective ops scheduled
on ICI (intra-slice) or DCN (inter-slice) — there is no NCCL-style runtime to
manage. They must be called inside ``shard_map``/``pmap`` with a live axis name
(tpuddp uses ``"data"``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import multihost_utils

from tpuddp.parallel.mesh import DATA_AXIS

# ---------------------------------------------------------------------------
# In-jit collectives (require an active named axis, e.g. inside shard_map).
# ---------------------------------------------------------------------------

_REDUCE_OPS = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax, "min": lax.pmin}


def all_reduce(x, op: str = "sum", axis_name: str = DATA_AXIS):
    """All-reduce a value (or pytree) across the named axis. Default op=sum,
    matching ``dist.all_reduce``'s default ReduceOp.SUM."""
    try:
        fn = _REDUCE_OPS[op]
    except KeyError:
        raise ValueError(f"unknown reduce op {op!r}; one of {sorted(_REDUCE_OPS)}")
    return jax.tree_util.tree_map(partial(fn, axis_name=axis_name), x)


def psum(x, axis_name: str = DATA_AXIS):
    return all_reduce(x, "sum", axis_name)


def pmean(x, axis_name: str = DATA_AXIS):
    """Cross-replica mean — the DDP gradient-averaging contract."""
    return all_reduce(x, "mean", axis_name)


def pmax(x, axis_name: str = DATA_AXIS):
    return all_reduce(x, "max", axis_name)


def pmin(x, axis_name: str = DATA_AXIS):
    return all_reduce(x, "min", axis_name)


def all_gather(x, axis_name: str = DATA_AXIS, axis: int = 0, tiled: bool = False):
    return jax.tree_util.tree_map(
        lambda v: lax.all_gather(v, axis_name, axis=axis, tiled=tiled), x
    )


def reduce_scatter(x, axis_name: str = DATA_AXIS, scatter_dimension: int = 0):
    return jax.tree_util.tree_map(
        lambda v: lax.psum_scatter(
            v, axis_name, scatter_dimension=scatter_dimension, tiled=True
        ),
        x,
    )


def bucketed_psum(vec, buckets, wire_dtype, axis_name: Optional[str] = DATA_AXIS):
    """Bucketed compressed psum over a flat f32 vector (the gradient-comm
    hook's reduce primitive, parallel/comm.py): each contiguous ``(start,
    end)`` bucket is cast to ``wire_dtype``, summed across the axis — the
    collective's operand IS the wire dtype, so bf16 halves the interconnect
    payload — and decompressed back to f32. ``axis_name=None`` skips the
    collective (auto mode: XLA's partitioner already inserted the reduction)
    and only round-trips the quantization. Returns the reassembled f32
    vector (SUM, not mean — callers divide by world)."""
    parts = []
    for s, e in buckets:
        b = lax.slice(vec, (s,), (e,)).astype(wire_dtype)
        if axis_name is not None:
            b = lax.psum(b, axis_name)
        parts.append(b.astype(jnp.float32))
    return jnp.concatenate(parts)


def allgather_dequant_sum(q, scale, axis_name):
    """Cross-replica SUM of per-replica int8-quantized payloads (the int8_ef
    exchange, parallel/comm.py): every replica's ``q`` (int8 values) and
    ``scale`` (its f32 max-abs scale) are all-gathered — the collective's
    operands ARE the compressed payload, the wire carries int8 + one scalar
    per replica — and each replica dequantizes and sums locally. Per-replica
    scales make a direct psum meaningless (summing int8 codes across
    different scales is not a sum of gradients), which is why torch's
    ``quantization_pertensor_hook`` takes the same all-gather shape."""
    ag_q = lax.all_gather(q, axis_name)  # (world, n) int8
    ag_s = lax.all_gather(scale, axis_name)  # (world,) f32
    return jnp.sum(
        ag_q.astype(jnp.float32) * ag_s[:, None].astype(jnp.float32), axis=0
    )


def allgather_topk_sum(idx, q, scale, n: int, axis_name):
    """Cross-replica SUM of per-replica top-k sparse payloads (the topk_ef
    exchange): all-gather the int32 indices + int8 values + f32 scale, then
    scatter-add every replica's dequantized contribution into a dense (n,)
    f32 vector — as ONE flattened scatter-add (duplicate indices across
    replicas accumulate by scatter-add semantics), so the program stays
    O(1) ops regardless of world size."""
    ag_i = lax.all_gather(idx, axis_name)  # (world, k) int32
    ag_q = lax.all_gather(q, axis_name)  # (world, k) int8
    ag_s = lax.all_gather(scale, axis_name)  # (world,) f32
    vals = ag_q.astype(jnp.float32) * ag_s[:, None].astype(jnp.float32)
    return jnp.zeros((n,), jnp.float32).at[ag_i.reshape(-1)].add(
        vals.reshape(-1)
    )


def psum_scatter_compressed(vec, wire_dtype, axis_name: str = DATA_AXIS):
    """Compressed reduce-scatter of a flat vector (the comm hooks' weight-
    update-sharding composition): the whole vector is cast to ``wire_dtype``
    and ``psum_scatter``'d tiled — each replica receives the summed
    contiguous 1/N shard with the wire carrying the compressed dtype — then
    decompressed to f32. Returns ``(f32_sum_shard, compressed_send)``; the
    send is handed back so error-feedback callers can form ``sent -
    kept``."""
    comp = vec.astype(wire_dtype)
    shard = lax.psum_scatter(
        comp, axis_name, scatter_dimension=0, tiled=True
    ).astype(jnp.float32)
    return shard, comp


def ppermute(x, perm, axis_name: str = DATA_AXIS):
    """Point-to-point ring permutation (building block for ring algorithms)."""
    return jax.tree_util.tree_map(
        lambda v: lax.ppermute(v, axis_name, perm=perm), x
    )


def axis_index(axis_name: str = DATA_AXIS):
    """This replica's index along the axis — the in-jit notion of "rank"."""
    return lax.axis_index(axis_name)


def broadcast(x, root: int = 0, axis_name: str = DATA_AXIS):
    """In-jit broadcast from ``root``: every replica gets root's value.

    Implements DDP's rank-0 parameter/buffer broadcast semantics. Uses a
    select+psum so it stays a single fused collective.
    """

    def _bcast(v):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == root, v, jnp.zeros_like(v))
        return lax.psum(masked, axis_name)

    return jax.tree_util.tree_map(_bcast, x)


# ---------------------------------------------------------------------------
# Host-level operations (called from the training loop, not inside jit).
# ---------------------------------------------------------------------------


def barrier(tag: str = "tpuddp_barrier", wait_for=None) -> None:
    """Synchronize. Analog of ``dist.barrier()`` (multi-GPU-training-torch.py:194,223).

    On a single host, device work is ordered by XLA's async dispatch stream, so
    the barrier reduces to (optionally) blocking on in-flight values. Across
    hosts it is a real global rendezvous over DCN.

    Resilience: entry is a ``$TPUDDP_FAULT`` injection site (``hang@barrier``
    is the chaos suite's dead-peer scenario, detected by the heartbeat
    watchdog). The rendezvous itself deliberately fails FAST: one host
    retrying ``sync_global_devices`` alone after its peers already completed
    the round would re-enter a rendezvous nobody else revisits — hanging
    forever or pairing with the peers' *next* barrier and tripping the tag
    assertion pod-wide. Transient-blip retry belongs where all hosts fail
    together, i.e. the ``jax.distributed.initialize`` rendezvous in
    ``backend.init_process_group``.
    """
    from tpuddp.resilience import faults

    faults.maybe_fire("barrier")
    if wait_for is not None:
        jax.block_until_ready(wait_for)
    if jax.process_count() > 1:
        try:
            multihost_utils.sync_global_devices(tag)
        except Exception as exc:
            raise RuntimeError(
                f"barrier {tag!r} failed on process {jax.process_index()}: "
                f"{exc}. A mid-training barrier cannot be retried unilaterally "
                "(peers have moved on); restart the run — auto_resume will "
                "continue from the last checkpoint."
            ) from exc


def broadcast_one_to_all(pytree, is_source: Optional[bool] = None):
    """Host-level broadcast of a pytree from process 0 to all processes —
    the multi-host analog of DDP's construction-time parameter broadcast.
    Single-process: identity (params are already one copy shared by all chips).
    Typed PRNG-key leaves are transported as their raw key data (the broadcast
    goes through numpy, which cannot hold key dtypes).
    """
    if jax.process_count() == 1:
        return pytree
    leaves, treedef = jax.tree_util.tree_flatten(pytree)
    is_key = [
        hasattr(l, "dtype") and jax.dtypes.issubdtype(l.dtype, jax.dtypes.prng_key)
        for l in leaves
    ]
    prepped = [
        jax.random.key_data(l) if k else l for l, k in zip(leaves, is_key)
    ]
    out = multihost_utils.broadcast_one_to_all(prepped, is_source=is_source)
    restored = [
        jax.random.wrap_key_data(o) if k else o for o, k in zip(out, is_key)
    ]
    return jax.tree_util.tree_unflatten(treedef, restored)


