"""2-D ``("data", "model")`` device mesh — the axis layer tensor parallelism
runs over (ROADMAP open item 1).

The flat DDP mesh (:func:`tpuddp.parallel.mesh.data_mesh`) and the factored
hierarchical mesh are both *1-D data-parallel*: every device holds a full
parameter copy and the only cross-device exchange is the gradient reduction.
:func:`mesh2d` generalizes that world into a ``data x model`` grid:

- the **data** axis keeps DDP's contract — the batch splits over it, gradient
  collectives reduce over it, replicas along it are supposed to agree bitwise;
- the **model** axis is new — parameters *shard* over it following a model's
  partition rules (tpuddp/parallel/tensor.py applies
  ``tpuddp.models.transformer.partition_spec``'s table), activations exchange
  over it inside the forward/backward, and shards along it are *supposed to
  differ* (the desync auditor compares across ``data`` only).

``mesh2d(data, 1)`` is definitionally today's DDP world:
:func:`squeeze_model` collapses it back to the exact 1-D data mesh so the
``model=1`` configuration lowers through the UNCHANGED existing code path
(HLO byte-identity is asserted in tests/test_mesh2d.py).

Axis registry (:data:`AXIS_ROLES`): the closed set of mesh axis names tpuddp
builds, with the role each one plays. The config surface cannot express an
unknown axis (the ``parallel`` block's key refusal covers it); programmatic
callers minting axis names check them against the registry with
:func:`validate_axis`.

Device order: ``model`` is the MINOR axis, so the devices of one tensor-
parallel group are adjacent in the flat device order — on a real slice that
keeps the latency-critical per-block activation psums on the closest ICI
hops, with the less frequent data-axis gradient reduction striding further.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from tpuddp.parallel.mesh import (
    DATA_AXIS,
    HOST_AXIS,
    LOCAL_AXIS,
    local_mesh_devices,
    make_mesh,
)

MODEL_AXIS = "model"

# The closed registry of mesh axis names and their roles. Everything tpuddp
# builds is one of: the flat data axis, its ("host", "local") factoring, or
# the 2-D (data, model) grid. An axis outside this set has no collectives,
# no sharding rules, and no checkpoint story. The YAML surface cannot name
# one (the parallel block refuses unknown keys, and mesh_from only ever
# mints registered axes); code-level callers inventing an axis validate it
# here via validate_axis instead of silently growing a fifth axis kind.
AXIS_ROLES: Mapping[str, str] = {
    DATA_AXIS: "batch sharding + gradient reduction (replicas agree bitwise)",
    MODEL_AXIS: "tensor-parallel parameter sharding (shards legitimately differ)",
    HOST_AXIS: "inter-host hop of the factored data axis (comm_topology=hierarchical)",
    LOCAL_AXIS: "intra-host hop of the factored data axis (comm_topology=hierarchical)",
}


def validate_axis(name: str) -> str:
    if name not in AXIS_ROLES:
        raise ValueError(
            f"unknown mesh axis {name!r}; the registry knows "
            f"{sorted(AXIS_ROLES)} (tpuddp/parallel/mesh2d.AXIS_ROLES)"
        )
    return name


def mesh2d(
    data: int,
    model: int,
    devices: Optional[Sequence[jax.Device]] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """The ``("data", "model")`` mesh: ``data * model`` devices reshaped into
    a grid with ``model`` minor (tensor-parallel groups on adjacent devices).

    ``model=1`` still builds the 2-D mesh (axes ``("data", "model")``,
    trailing size 1); callers that want the byte-identical legacy DDP program
    collapse it with :func:`squeeze_model` — DistributedDataParallel does
    this automatically, so ``mesh2d(N, 1)`` IS the flat mesh end to end."""
    data, model = int(data), int(model)
    if data < 1 or model < 1:
        raise ValueError(f"mesh2d axis sizes must be >= 1, got data={data}, model={model}")
    if devices is None:
        devices = local_mesh_devices(data * model, backend)
    if len(devices) != data * model:
        raise ValueError(
            f"mesh2d(data={data}, model={model}) needs exactly "
            f"{data * model} devices, got {len(devices)}"
        )
    return make_mesh(devices, axes={DATA_AXIS: data, MODEL_AXIS: model})


def axis_sizes(mesh: Mesh) -> Mapping[str, int]:
    """``{axis name: size}`` of a mesh."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def model_size(mesh: Optional[Mesh]) -> int:
    """The tensor-parallel width of a mesh: the ``model`` axis size, or 1
    for every 1-D data mesh (flat or hierarchical) — DDP is the ``model=1``
    special case by definition."""
    if mesh is None:
        return 1
    return int(axis_sizes(mesh).get(MODEL_AXIS, 1))


def data_size(mesh: Mesh) -> int:
    """The data-parallel width: every axis that is not ``model`` (the flat
    ``data`` axis, or the ``host * local`` product on the factored mesh)."""
    sizes = axis_sizes(mesh)
    return int(np.prod([s for a, s in sizes.items() if a != MODEL_AXIS], dtype=int))


def is_tensor_parallel(mesh: Optional[Mesh]) -> bool:
    return model_size(mesh) > 1


def squeeze_model(mesh: Mesh) -> Mesh:
    """Collapse a ``model=1`` 2-D mesh to the exact flat data mesh over the
    same devices (same order), so downstream step construction takes the
    UNCHANGED 1-D code path and lowers to byte-identical HLO. A mesh whose
    ``model`` axis is wider than 1 cannot be squeezed and raises."""
    if MODEL_AXIS not in mesh.axis_names:
        return mesh
    if model_size(mesh) != 1:
        raise ValueError(
            f"cannot squeeze a model={model_size(mesh)} mesh to 1-D; only "
            "the model=1 special case collapses to the flat DDP mesh"
        )
    return make_mesh(list(mesh.devices.flat))


def describe(mesh: Optional[Mesh]) -> Optional[dict]:
    """The run_meta ``mesh`` block's axis sizes: ``{"data": D, "model": M}``
    (None for no mesh). The data width folds the hierarchical factoring, so
    a reader never needs the axis registry to know the replica count."""
    if mesh is None:
        return None
    return {"data": data_size(mesh), "model": model_size(mesh)}
