"""Distributed runtime bootstrap — the TPU-native process-group layer.

This is the tpuddp equivalent of the reference's process-group setup
(`multi-GPU-training-torch.py:29-51`):

- reference ``setup(rank, world_size)`` does a TCP rendezvous on
  ``MASTER_ADDR/MASTER_PORT`` and picks a backend with a NCCL -> Gloo -> error
  ladder, then pins the process to ``cuda:rank``;
- here, rendezvous is ``jax.distributed.initialize`` (only needed multi-host —
  on a TPU pod slice each host runs ONE process that owns all of its local
  chips, so there is no per-device process spawn), and the backend ladder is
  **TPU -> CPU -> error**.  The CPU rung uses XLA's host-platform devices
  (``--xla_force_host_platform_device_count=N``) and replaces the reference's
  Gloo fallback as the no-accelerator development/test path.

Device "binding" (reference ``torch.cuda.set_device(rank)``,
multi-GPU-training-torch.py:44) has no TPU analog: XLA owns all local chips and
placement is expressed through shardings on the mesh, not a per-process device.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np

logger = logging.getLogger("tpuddp")

# Environment override for the backend ladder, e.g. TPUDDP_BACKEND=cpu in CI.
_BACKEND_ENV = "TPUDDP_BACKEND"

# Module-level runtime state (the "process group").
_state = {
    "initialized": False,
    "backend": None,
    "world_size": None,
    "multihost": False,
}


class BackendUnavailableError(RuntimeError):
    """No usable accelerator backend. Mirrors the reference's terminal error
    (`multi-GPU-training-torch.py:38-42`) raised when neither NCCL nor Gloo is
    available."""


def _try_devices(backend: str):
    try:
        devs = jax.devices(backend)
        return devs if devs else None
    except RuntimeError:
        return None


def available_backends() -> list:
    """List usable backends in ladder order (TPU first, CPU fallback)."""
    out = []
    for name in ("tpu", "cpu"):
        if _try_devices(name):
            out.append(name)
    return out


def detect_backend(prefer: Optional[str] = None) -> str:
    """Backend selection ladder: ``prefer`` (or $TPUDDP_BACKEND) -> tpu -> cpu -> error.

    Mirrors the NCCL -> Gloo -> raise ladder at multi-GPU-training-torch.py:34-42.
    """
    ladder = []
    prefer = prefer or os.environ.get(_BACKEND_ENV)
    if prefer:
        ladder.append(prefer)
    ladder += ["tpu", "cpu"]
    for backend in ladder:
        if _try_devices(backend):
            return backend
    raise BackendUnavailableError(
        "Both backends tpu and cpu not available for multi-chip training with "
        "distributed data parallel. No XLA devices found."
    )


def setup(
    world_size: Optional[int] = None,
    backend: Optional[str] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> str:
    """Initialize the distributed runtime and return the selected backend name.

    Single-host: selects a backend via :func:`detect_backend` and records the
    world size (defaults to all local devices of that backend).

    Multi-host (TPU pod): pass ``coordinator_address`` (the analog of the
    reference's ``MASTER_ADDR:MASTER_PORT``, multi-GPU-training-torch.py:30-31)
    or set the standard TPU pod env so ``jax.distributed.initialize`` can
    auto-discover peers.
    """
    if _state["initialized"]:
        logger.warning("tpuddp.setup() called twice; ignoring second call")
        return _state["backend"]

    multihost = coordinator_address is not None or (
        num_processes is not None and num_processes > 1
    )
    if multihost:
        # import the submodule directly: the package __init__ re-exports the
        # retry FUNCTION under the same name, so `from tpuddp.resilience
        # import retry` binds the callable, not the module
        from tpuddp.resilience.retry import RetryPolicy as _RetryPolicy
        from tpuddp.resilience.retry import retry as _retry

        # The rendezvous is the classic transient failure: N hosts race to
        # come up and the coordinator may not be listening yet. Jittered
        # backoff (3 attempts) decorrelates the herd; the terminal RetryError
        # names the coordinator so the failure is actionable.
        _retry(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            ),
            _RetryPolicy(max_attempts=3, base_delay=2.0, max_delay=15.0),
            describe=(
                f"jax.distributed.initialize (coordinator "
                f"{coordinator_address or 'auto-discovered'})"
            ),
        )

    chosen = detect_backend(backend)
    devices = jax.devices(chosen)
    if world_size is None:
        world_size = len(devices)
    if world_size > len(devices) and jax.process_count() == 1:
        raise ValueError(
            f"world_size={world_size} exceeds the {len(devices)} available "
            f"{chosen} devices on this host. For a CPU development world, set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N before importing jax."
        )

    _state.update(
        initialized=True,
        backend=chosen,
        world_size=world_size,
        multihost=multihost or jax.process_count() > 1,
    )
    # Parity with the reference's post-init banner (multi-GPU-training-torch.py:46-47).
    logger.info(
        "Process group initialized with backend %s, process %d, world size %d.",
        chosen,
        jax.process_index(),
        world_size,
    )
    return chosen


def cleanup() -> None:
    """Tear down the runtime. Analog of ``dist.destroy_process_group()``
    (multi-GPU-training-torch.py:50-51)."""
    if _state.get("multihost") and jax.process_count() > 1:
        try:
            jax.distributed.shutdown()
        except Exception:  # pragma: no cover - shutdown is best-effort
            logger.exception("jax.distributed.shutdown failed")
    _state.update(initialized=False, backend=None, world_size=None, multihost=False)


def is_initialized() -> bool:
    return bool(_state["initialized"])


def get_backend() -> Optional[str]:
    """Analog of ``dist.get_backend()``."""
    return _state["backend"]


def get_rank() -> int:
    """Analog of ``dist.get_rank()`` — on TPU the unit is the *process* (host),
    each of which drives all of its local chips."""
    return jax.process_index()


def get_world_size() -> int:
    """Analog of ``dist.get_world_size()`` — the number of devices in the data
    axis (per-chip granularity, unlike get_rank's per-host granularity)."""
    if _state["world_size"] is not None:
        return _state["world_size"]
    return jax.device_count()


def resolve_devices(
    world_size: Optional[int] = None, backend: Optional[str] = None
) -> Sequence[jax.Device]:
    """Pick the devices that form the data-parallel world.

    Multi-process: always the full global device list (every process must agree
    on mesh devices). Single-process: the first ``world_size`` local devices of
    the detected backend.
    """
    chosen = backend or _state["backend"] or detect_backend()
    devices = jax.devices(chosen)
    if jax.process_count() > 1:
        return devices
    if world_size is None:
        world_size = _state["world_size"] or len(devices)
    if world_size > len(devices):
        raise ValueError(
            f"world_size={world_size} > available {chosen} devices ({len(devices)})"
        )
    return devices[:world_size]
