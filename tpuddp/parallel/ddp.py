"""DistributedDataParallel — the explicit DP wrapper.

Owns the contract of ``torch.nn.parallel.DistributedDataParallel`` (SURVEY.md
§2b #13), reimagined functionally: instead of hooking autograd, it *builds*
the compiled train/eval step in which gradient pmean, buffer broadcast, and
metric partial-sums are explicit. Wrapping = ``ddp = DistributedDataParallel(
model, optimizer, criterion, mesh)`` + ``state = ddp.init_state(key, sample)``;
the construction-time rank-0 parameter broadcast of torch DDP
(multi-GPU-training-torch.py:245) is performed in ``init_state`` via
``broadcast_one_to_all``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from tpuddp.nn.core import Context
from tpuddp.nn.loss import CrossEntropyLoss
from tpuddp.parallel import collectives as col
from tpuddp.parallel import comm as comm_lib
from tpuddp.parallel.mesh import (
    HOST_AXIS,
    LOCAL_AXIS,
    data_axes,
    data_mesh,
    hierarchical_mesh,
    replicate,
    shard_batch,
)
from tpuddp.parallel.mesh2d import (
    MODEL_AXIS as _MODEL_AXIS,
    data_size as _mesh_data_size,
    model_size as _mesh_model_size,
    squeeze_model as _squeeze_model,
)
from tpuddp.resilience import guard as guard_lib
from tpuddp.training import step as step_lib
from tpuddp.training.train_state import TrainState, create_train_state


def _normalize_overlap(value):
    """Normalize the ``comm_overlap`` knob to True/False/"auto" (YAML hands
    us bools, CLI overrides hand us strings)."""
    if value is True or value is False:
        return value
    if value is None:
        return "auto"
    if isinstance(value, str):
        v = value.strip().lower()
        if v == "auto":
            return "auto"
        if v in ("true", "1", "on", "yes"):
            return True
        if v in ("false", "0", "off", "no"):
            return False
    raise ValueError(
        f"comm_overlap must be true, false, or 'auto'; got {value!r}"
    )


class DistributedDataParallel:
    """Builds and caches the compiled DP steps for (model, optimizer, criterion).

    mode="shard_map" is the explicit-DDP analog (visible lax.pmean); mode="auto"
    is the managed analog used by the Accelerator facade. Both run on the same
    mesh/collectives backend.
    """

    def __init__(
        self,
        model,
        optimizer,
        criterion: Optional[Callable] = None,
        mesh=None,
        mode: str = "shard_map",
        sync_buffers: str = "broadcast",
        clip_grad_norm: Optional[float] = None,
        augment: Optional[Callable] = None,
        eval_transform: Optional[Callable] = None,
        remat: bool = False,
        weight_update_sharding: bool = False,
        grad_accumulation: int = 1,
        comm_hook: str = "none",
        bucket_cap_mb: float = comm_lib.DEFAULT_BUCKET_CAP_MB,
        comm_topology: str = "flat",
        topk_density: float = comm_lib.DEFAULT_TOPK_DENSITY,
        guard=None,
        comm_overlap="auto",
    ):
        """``weight_update_sharding``: shard the optimizer update + moments
        across the data axis (reduce-scatter grads, update a 1/N parameter
        shard per replica, all-gather new params — the cross-replica
        weight-update sharding of arxiv.org/abs/2004.13336 / ZeRO-1).
        N-fold less optimizer memory and update HBM traffic per chip; same
        interconnect bytes as the plain allreduce. shard_map mode only.

        ``grad_accumulation=A > 1``: ONE optimizer update per A consecutive
        micro-batches (native effective-batch control, the explicit-API analog
        of ``Accelerator(gradient_accumulation_steps=A)``). Training then runs
        through :meth:`train_step_many` in whole cycles of A — the epoch
        driver pads ragged tails with all-padding micro-batches; the
        per-batch :meth:`train_step` is refused (a full-scale update per
        micro-batch would be a silent A× LR bug).

        ``comm_hook``: the gradient-communication hook (torch DDP's comm-hook
        analog, parallel/comm.py): ``"none"`` keeps today's full-precision
        pmean; ``"bf16"`` runs the bucketed bf16-compressed allreduce (half
        the gradient interconnect bytes); ``"bf16_ef"`` adds the per-replica
        error-feedback residual (carried in ``TrainState.comm_state``,
        checkpointed with the rest of the state) so compression error does
        not bias convergence. In ``mode="shard_map"`` the collective
        genuinely runs in bf16 on the wire; in ``mode="auto"`` the hook
        quantizes the aggregated gradient (same numerics contract, byte
        savings are a shard_map-mode property). Composes with
        ``weight_update_sharding`` (the compressed payload is
        reduce-scattered) and ``grad_accumulation`` (compression happens
        once per cycle, on the averaged gradient).

        ``"int8_ef"`` runs per-bucket max-abs symmetric int8 quantization
        (values + per-bucket f32 scales on the wire, ~75% fewer gradient
        bytes) and ``"topk_ef"`` keeps only the top ``topk_density`` of each
        bucket by magnitude (int8 values + int32 indices + scale, ~87.5%
        fewer bytes at density 0.1); both carry the same persistent
        error-feedback residual as bf16_ef (quantization error AND unsent
        elements re-enter the next send).

        ``bucket_cap_mb``: bucket size cap for the compressed hooks (torch's
        ``bucket_cap_mb`` knob, default 25): small tensors coalesce into one
        collective per bucket; boundaries fall on whole-leaf edges.

        ``comm_topology``: ``"flat"`` (one collective over the whole data
        axis — today's behavior) or ``"hierarchical"`` (parallel/comm.py
        ``reduce_hierarchical``): intra-host f32 reduce-scatter over the
        factored mesh's ``"local"`` axis, compressed inter-host exchange
        over ``"host"``, then all-gather — only the compressed shard crosses
        the slow inter-host link. Needs ``mode="shard_map"`` and a factored
        ``("host", "local")`` mesh (``mesh=None`` builds one via
        :func:`~tpuddp.parallel.mesh.hierarchical_mesh`); mutually exclusive
        with ``weight_update_sharding`` (the scatter already factors the
        exchange). ``grad_comm_bytes_inter_host`` /
        ``grad_comm_bytes_intra_host`` account the two hops separately.

        ``topk_density``: the fraction of each bucket topk_ef keeps
        (default 0.1); ignored by the other hooks.

        ``comm_overlap``: segmented-backward execution (``true``/``false``/
        ``"auto"``, training/step.py): stage the backward pass as per-segment
        VJP closures whose boundaries align with ``bucket_cap_mb`` buckets
        and issue each segment's gradient collective the moment its buckets
        materialize — torch DDP's ready-bucket overlap, bitwise-identical
        loss trajectory to the barrier step. ``"auto"`` (default) enables it
        only where it genuinely segments (``mode="shard_map"``, flat
        topology, Sequential model, no WUS/remat/TP, and >= 2 bucket-aligned
        segments) and quietly keeps the barrier step elsewhere; ``true``
        refuses ineligible combos loudly at :meth:`init_state`; ``false``
        pins the barrier step. :attr:`comm_overlap_meta` records the
        resolution for run_meta provenance.

        ``guard``: the ``training.guard`` block (None/False/True/dict or a
        :class:`~tpuddp.resilience.guard.GuardConfig`). When enabled, the
        compiled step gates every optimizer update behind a non-finite
        gradient firewall (a poisoned step becomes a bitwise no-op counted
        in ``TrainState.skipped_steps``) and :meth:`init_state` runs the
        cross-replica desync auditor — the torch
        ``_verify_params_across_processes`` moment. Off by default; the
        disabled path lowers to the identical step program."""
        self.model = model
        self.optimizer = optimizer
        self.criterion = criterion if criterion is not None else CrossEntropyLoss()
        self.comm_topology = comm_lib.validate_topology(comm_topology)
        if mesh is not None:
            self.mesh = mesh
        elif self.comm_topology == "hierarchical":
            self.mesh = hierarchical_mesh()
        else:
            self.mesh = data_mesh()
        self.mode = mode
        # 2-D ("data", "model") mesh (parallel/mesh2d.py): model=1 collapses
        # to the EXACT flat data mesh, so the legacy DDP construction below
        # runs unchanged and lowers to byte-identical HLO; model>1 arms the
        # tensor-parallel path (parallel/tensor.py) with its own refusal
        # surface — a combo the TP step has no semantics for must fail at
        # wrap time, not mistrain.
        self.model_size = _mesh_model_size(self.mesh)
        if _MODEL_AXIS in self.mesh.axis_names and self.model_size == 1:
            self.mesh = _squeeze_model(self.mesh)
        self.data_size = _mesh_data_size(self.mesh)
        self._tp_specs = None  # P-tree of the TP param shards (model>1 only)
        self._tp_opt_specs = None
        if self.model_size > 1:
            self._validate_tp(
                mode, weight_update_sharding, grad_accumulation,
                clip_grad_norm, augment, eval_transform, remat, optimizer,
            )
        if self.comm_topology == "hierarchical":
            if mode != "shard_map":
                raise ValueError(
                    "comm_topology='hierarchical' needs the explicit "
                    "per-replica step (mode='shard_map'): the multi-hop "
                    "reduction is expressed over the factored mesh's named "
                    "axes (mode='auto' lets XLA place the collective)"
                )
            if weight_update_sharding:
                raise ValueError(
                    "comm_topology='hierarchical' and weight_update_sharding "
                    "are mutually exclusive: the reduce-scatter/all-gather "
                    "exchange already factors the reduction; pick one"
                )
            names = set(self.mesh.axis_names)
            if names != {HOST_AXIS, LOCAL_AXIS}:
                raise ValueError(
                    "comm_topology='hierarchical' needs a factored "
                    f"('{HOST_AXIS}', '{LOCAL_AXIS}') mesh (got axes "
                    f"{tuple(self.mesh.axis_names)}); build one with "
                    "tpuddp.parallel.mesh.hierarchical_mesh"
                )
        # fail at wrap time, not first step (a bad value would silently skip
        # buffer sync and publish divergent buffers as replicated)
        step_lib._validate_sync_buffers(
            model, step_lib.DATA_AXIS if mode == "shard_map" else None, sync_buffers
        )
        if weight_update_sharding and mode != "shard_map":
            raise ValueError(
                "weight_update_sharding requires mode='shard_map' (the "
                "reduce-scatter/all-gather exchange is expressed over the "
                "explicit per-replica step's named axis)"
            )
        self.grad_accumulation = int(grad_accumulation)
        if self.grad_accumulation < 1:
            raise ValueError(
                f"grad_accumulation must be >= 1, got {grad_accumulation!r}"
            )
        self.sync_buffers = sync_buffers
        self.clip_grad_norm = clip_grad_norm
        self.augment = augment
        self.eval_transform = eval_transform
        self.remat = remat
        self.weight_update_sharding = bool(weight_update_sharding)
        self.comm_hook = comm_lib.validate_hook(comm_hook)
        self.bucket_cap_mb = float(bucket_cap_mb)
        if self.bucket_cap_mb <= 0:
            raise ValueError(f"bucket_cap_mb must be > 0, got {bucket_cap_mb!r}")
        self.topk_density = float(topk_density)
        comm_lib.bucket_topk(1, self.topk_density)  # range-validate eagerly
        self.guard = guard_lib.resolve_guard(guard)
        self.comm_overlap = _normalize_overlap(comm_overlap)
        self._segments = None
        self._overlap_meta = None
        self._comm = None
        self._grad_comm_bytes = None
        self._grad_comm_bytes_f32 = None
        self._grad_comm_breakdown = None
        self._wus_spec = None
        self._state_spec = None
        self._train_step = None
        self._eval_step = None
        self._scan_step = None
        self._eval_scan_step = None

    def _validate_tp(
        self, mode, weight_update_sharding, grad_accumulation,
        clip_grad_norm, augment, eval_transform, remat, optimizer,
    ):
        """Wrap-time refusal surface for the tensor-parallel path: every
        combination the TP step has no semantics for fails HERE, loudly —
        the alternative is a silently different training run."""
        from tpuddp.parallel import tensor as tp_lib

        tp_lib.validate_tp_geometry(self.model, self.model_size)
        if mode != "shard_map":
            raise ValueError(
                "parallel.model > 1 needs the explicit per-replica step "
                "(mode='shard_map'): the model-axis exchanges are written "
                "over named mesh axes"
            )
        if self.comm_topology != "flat":
            raise ValueError(
                "parallel.model > 1 with comm_topology='hierarchical' is "
                "refused: the factored ('host','local') data axis and the "
                "model axis would need a 3-D mesh the comm hooks do not "
                "express yet — pick one"
            )
        if weight_update_sharding:
            raise ValueError(
                "parallel.model > 1 with weight_update_sharding is refused: "
                "the WUS flat layout spans the whole replicated parameter "
                "vector, which a model-sharded state no longer has (the "
                "ZeRO composition is ROADMAP item 2)"
            )
        if int(grad_accumulation) != 1:
            raise ValueError(
                "parallel.model > 1 with grad_accumulation > 1 is deferred; "
                "scale the per-replica batch instead"
            )
        if clip_grad_norm is not None:
            raise ValueError(
                "parallel.model > 1 with clip_grad_norm is deferred: the "
                "global norm of a model-sharded gradient needs a model-axis "
                "reduction the clip path does not express yet"
            )
        if augment is not None or eval_transform is not None:
            raise ValueError(
                "parallel.model > 1 is a token-model path; image "
                "augment/eval_transform hooks do not apply"
            )
        if remat:
            raise ValueError("parallel.model > 1 with remat is deferred")
        if type(optimizer).__name__ in ("LARS", "LAMB"):
            raise ValueError(
                "parallel.model > 1 with LARS/LAMB is deferred: per-layer "
                "trust ratios over model-sharded leaves need model-axis "
                "norm reductions; use adam/sgd/sgdw"
            )
        if jax.process_count() > 1:
            raise ValueError(
                "parallel.model > 1 is single-controller only for now "
                "(every shard must be addressable for placement and "
                "checkpoint gather)"
            )

    # -- world introspection (dist.get_world_size analog) -------------------
    @property
    def world_size(self) -> int:
        return self.mesh.devices.size

    @property
    def tp_rules_hash(self):
        """Short hash of the tensor-parallel rule table this wrap applies
        (run_meta ``mesh.tp_rules_hash``); None on pure-DP wraps."""
        if self.model_size <= 1:
            return None
        from tpuddp.parallel import tensor as tp_lib

        return tp_lib.tp_rules_hash()

    @property
    def tp_param_specs(self):
        """The PartitionSpec tree of the TP parameter shards (None on pure
        DP) — the desync auditor needs it to fingerprint each device's OWN
        shard and compare across data replicas only."""
        return self._tp_specs

    def _init_state_tp(self, key, sample_input, params, model_state) -> TrainState:
        """The tensor-parallel init: full host init + broadcast (the DDP
        construction contract, unchanged), then the QKV layout reshape, the
        rule-table placement of params/moments over the model axis, the
        LOCAL-shard gradient comm plan (data-axis exchange only), and the
        per-(data, model)-device error-feedback residual."""
        import numpy as np
        from jax.sharding import NamedSharding

        from tpuddp.parallel import tensor as tp_lib
        from tpuddp.parallel.mesh import DATA_AXIS
        from tpuddp.parallel.mesh2d import MODEL_AXIS

        if (params is None) != (model_state is None):
            raise ValueError(
                "init_state needs params and model_state together: pretrained "
                "params with freshly-initialized buffers would silently "
                "mis-normalize"
            )
        if params is not None:
            _, run_key = jax.random.split(key)
            state = TrainState(
                params=params,
                model_state=model_state,
                opt_state=None,
                step=jnp.zeros((), jnp.int32),
                rng=run_key,
            )
        else:
            state = create_train_state(self.model, self.optimizer, key, sample_input)
        state = col.broadcast_one_to_all(state)
        host_params = jax.tree_util.tree_map(np.asarray, state.params)
        tp_params = tp_lib.to_tp_tree(host_params)
        self._tp_specs = tp_lib.tp_param_specs(self.model, tp_params)
        # optimizer state over the TP-layout tree: moments inherit each
        # parameter's spec by tree path, so each chip materializes only its
        # shard's moments — the per-chip HBM cut covers m/v too
        opt_state = self.optimizer.init(tp_params)
        self._tp_opt_specs = tp_lib.opt_state_specs(
            opt_state, tp_params, self._tp_specs
        )
        # gradient comm plan over the LOCAL shard template: hooks bucket the
        # shard's flat vector and exchange it across DATA replicas only —
        # the model axis never sees a gradient collective
        local_tpl = tp_lib.local_param_template(
            tp_params, self._tp_specs, self.model_size
        )
        self._comm = comm_lib.make_grad_comm(
            local_tpl, self.data_size, self.comm_hook, self.bucket_cap_mb,
            density=self.topk_density,
        )
        self._grad_comm_bytes = comm_lib.comm_bytes_for_hook(
            local_tpl, self.data_size, self.comm_hook, wire=True,
            bucket_cap_mb=self.bucket_cap_mb, density=self.topk_density,
        )
        self._grad_comm_bytes_f32 = comm_lib.comm_bytes_for_hook(
            local_tpl, self.data_size, "none", wire=True,
        )
        self._grad_comm_breakdown = {
            "total": self._grad_comm_bytes,
            "inter_host": self._grad_comm_bytes,
            "intra_host": 0,
        }
        self._resolve_overlap(None)  # TP is overlap-ineligible; record why
        self._state_spec = tp_lib.tp_state_spec(
            self._tp_specs, self._tp_opt_specs, comm=self._comm
        )
        placed_params = tp_lib.place_tree(self.mesh, tp_params, self._tp_specs)
        placed_opt = tp_lib.place_tree(self.mesh, opt_state, self._tp_opt_specs)
        comm_state = None
        if self._comm is not None and self._comm.needs_residual:
            # one residual slice per (data_index, model_index) device,
            # created device-side already sharded — P(("data", "model"))
            # splits the flat vector data-major, model-minor, exactly the
            # mesh's device order
            n = self._comm.spec.total * self.world_size
            comm_state = jax.jit(
                lambda: jnp.zeros((n,), jnp.float32),
                out_shardings=NamedSharding(
                    self.mesh, step_lib.P((DATA_AXIS, MODEL_AXIS))
                ),
            )()
        skipped = (
            replicate(self.mesh, guard_lib.init_skip_counters())
            if self.guard.enabled
            else None
        )
        return self._audit_at_wrap(TrainState(
            params=placed_params,
            model_state=replicate(self.mesh, state.model_state),
            opt_state=placed_opt,
            step=replicate(self.mesh, state.step),
            rng=replicate(self.mesh, state.rng),
            comm_state=comm_state,
            skipped_steps=skipped,
        ))

    def init_state(self, key, sample_input, params=None, model_state=None) -> TrainState:
        """Create replicated train state. Parameters are broadcast from
        process 0 (multi-host) and placed replicated on every mesh device —
        the DDP construction contract.

        ``params``/``model_state`` override the fresh initialization with
        caller-supplied values (the pretrained fine-tune path,
        data_and_toy_model.py:41-45); optimizer state is re-derived from the
        supplied params."""
        if self.model_size > 1:
            return self._init_state_tp(key, sample_input, params, model_state)
        if (params is None) != (model_state is None):
            raise ValueError(
                "init_state needs params and model_state together: pretrained "
                "params with freshly-initialized buffers (e.g. BatchNorm "
                "running stats) would silently mis-normalize"
            )
        if params is not None:
            # caller already owns the variables; skip the (large) fresh init
            _, run_key = jax.random.split(key)
            state = TrainState(
                params=params,
                model_state=model_state,
                opt_state=self.optimizer.init(params),
                step=jnp.zeros((), jnp.int32),
                rng=run_key,
            )
        else:
            state = create_train_state(self.model, self.optimizer, key, sample_input)
        if self.weight_update_sharding:
            # re-derive the optimizer state over the FLAT padded parameter
            # vector: moments become (total,) arrays laid out sharded over
            # the data axis (each replica materializes only its 1/N slice)
            self._wus_spec = step_lib.make_flat_param_spec(
                state.params, self.world_size
            )
            opt_state = self.optimizer.init(
                jnp.zeros((self._wus_spec.total,), jnp.float32)
            )
            state = TrainState(
                params=state.params,
                model_state=state.model_state,
                opt_state=opt_state,
                step=state.step,
                rng=state.rng,
            )
        # Gradient-comm plan (parallel/comm.py): under weight-update sharding
        # the hook reuses the WUS flat spec so the error-feedback residual
        # aligns with the scattered vector element for element. Hierarchical
        # topology forces a plan even for hook "none" (its multi-hop
        # exchange needs the flat spec regardless of compression).
        self._comm = comm_lib.make_grad_comm(
            state.params, self.world_size, self.comm_hook, self.bucket_cap_mb,
            flat_spec=self._wus_spec, density=self.topk_density,
            force=(self.comm_topology == "hierarchical"),
        )
        wire = self.mode == "shard_map"
        if self.weight_update_sharding:
            # auto mode: XLA inserts the psum over f32 values and the hook
            # only emulates the quantization — account the wire honestly
            self._grad_comm_bytes = comm_lib.comm_bytes_for_hook(
                state.params, self.world_size, self.comm_hook, wus=True,
                wire=wire, bucket_cap_mb=self.bucket_cap_mb,
                density=self.topk_density,
            )
            self._grad_comm_breakdown = {
                "total": self._grad_comm_bytes,
                "inter_host": self._grad_comm_bytes,
                "intra_host": 0,
            }
        else:
            # flat vs hierarchical intra/inter-host split (comm.py
            # accounting model); "total" is the headline counter either way
            local = (
                dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get(
                    LOCAL_AXIS
                )
                if self.comm_topology == "hierarchical"
                else None
            )
            self._grad_comm_breakdown = comm_lib.comm_bytes_breakdown(
                state.params, self.world_size, self.comm_hook,
                topology=self.comm_topology, local_size=local, wire=wire,
                bucket_cap_mb=self.bucket_cap_mb, density=self.topk_density,
            )
            self._grad_comm_bytes = self._grad_comm_breakdown["total"]
        # the uncompressed reference payload for the same layout: run_meta
        # records both, so a history file alone can state the byte savings
        # a compressed hook achieved (tools/tpuddp_inspect.py)
        self._grad_comm_bytes_f32 = comm_lib.comm_bytes_for_hook(
            state.params, self.world_size, "none",
            wus=self.weight_update_sharding,
            wire=wire,
        )
        self._resolve_overlap(state.params)
        sharded_residual = (
            self._comm is not None
            and self._comm.needs_residual
            and self.mode == "shard_map"
        )
        if self._comm is not None and self._comm.needs_residual and not sharded_residual:
            # auto mode: a replicated (total,)-sized residual — O(params),
            # carried through the broadcast like any other leaf. The
            # per-replica shard_map residual is built directly under its
            # target sharding below instead: materializing a
            # (world * total,) host vector of zeros and broadcasting it
            # would cost O(world x params) host memory for nothing.
            state = TrainState(
                params=state.params,
                model_state=state.model_state,
                opt_state=state.opt_state,
                step=state.step,
                rng=state.rng,
                comm_state=jnp.asarray(
                    self._comm.init_residual(per_replica=False)
                ),
            )
        axis = data_axes(self.mesh)
        if self.weight_update_sharding:
            self._state_spec = step_lib.sharded_state_spec(
                state.opt_state, self._wus_spec, comm=self._comm, axis=axis
            )
        elif sharded_residual:
            self._state_spec = step_lib.comm_state_spec(axis=axis)
        if self.guard.enabled:
            # the firewall's skip counters ride in the state (replicated,
            # checkpointed); added after every structural rebuild above so no
            # reconstruction can drop them
            import dataclasses

            state = dataclasses.replace(
                state, skipped_steps=guard_lib.init_skip_counters()
            )
        state = col.broadcast_one_to_all(state)
        if not self.weight_update_sharding and not sharded_residual:
            return self._audit_at_wrap(replicate(self.mesh, state))
        # placement follows the state spec's judgment leaf by leaf (ONE
        # predicate for what shards): optimizer vectors / the per-replica
        # comm residual land sharded over the data axis, everything else
        # replicated
        from jax.sharding import NamedSharding

        def place(leaf, spec):
            if spec == step_lib.P(axis):
                import numpy as np

                host = np.asarray(leaf)
                return jax.make_array_from_callback(
                    host.shape,
                    NamedSharding(self.mesh, spec),
                    lambda idx: host[idx],
                )
            return replicate(self.mesh, leaf)

        comm_state = None
        if sharded_residual:
            # definitionally zeros: create the (world * total,) residual
            # device-side, already sharded over the data axis — no host-size
            # copy, no cross-host broadcast of zeros
            n = self._comm.spec.total * self.world_size
            comm_state = jax.jit(
                lambda: jnp.zeros((n,), jnp.float32),
                out_shardings=NamedSharding(self.mesh, step_lib.P(axis)),
            )()
        return self._audit_at_wrap(TrainState(
            params=replicate(self.mesh, state.params),
            model_state=replicate(self.mesh, state.model_state),
            opt_state=jax.tree_util.tree_map(
                lambda l, s: place(l, s),
                state.opt_state,
                self._state_spec.opt_state,
            )
            if self.weight_update_sharding
            else replicate(self.mesh, state.opt_state),
            step=replicate(self.mesh, state.step),
            rng=replicate(self.mesh, state.rng),
            comm_state=comm_state,
            skipped_steps=replicate(self.mesh, state.skipped_steps),
        ))

    def _resolve_overlap(self, params):
        """Resolve the ``comm_overlap`` knob against the eligibility matrix,
        deriving the bucket-aligned backward segments
        (:func:`~tpuddp.parallel.comm.make_segments`) where the segmented
        step genuinely applies. Runs inside :meth:`init_state` — segments
        need the realized parameter layout. ``"auto"`` falls back to the
        barrier step with a recorded reason; ``True`` refuses loudly."""
        from tpuddp.nn.core import Sequential

        want = self.comm_overlap
        if want is False:
            self._overlap_meta = {
                "enabled": False, "segments": None, "reason": "disabled",
            }
            return
        reason = None
        if self.mode != "shard_map":
            reason = (
                "mode='auto' has no explicit collective to issue per "
                "segment (XLA places the psum itself)"
            )
        elif self.comm_topology != "flat":
            reason = (
                "comm_topology='hierarchical': a per-segment scatter would "
                "move the error-feedback residual's owner placement"
            )
        elif self.weight_update_sharding:
            reason = (
                "weight_update_sharding: per-segment reduce-scatter pieces "
                "do not reassemble into the replica's canonical full-vector "
                "shard"
            )
        elif self.remat:
            reason = (
                "remat wraps the whole forward in one jax.checkpoint body; "
                "per-segment VJP staging would recompute outside it"
            )
        elif self.model_size > 1:
            reason = "tensor parallelism (parallel.model > 1)"
        elif not isinstance(self.model, Sequential):
            reason = (
                "segment boundaries are derived from Sequential children; "
                f"{type(self.model).__name__} has no child decomposition"
            )
        segments = None
        if reason is None:
            import numpy as np

            try:
                if self._comm is not None:
                    spec, buckets = self._comm.spec, self._comm.buckets
                else:
                    spec = step_lib.make_flat_param_spec(
                        params, self.world_size
                    )
                    buckets = comm_lib.make_buckets(
                        spec.sizes, spec.total, self.bucket_cap_mb
                    )
                layer_sizes = tuple(
                    sum(
                        int(np.prod(np.shape(l)))
                        for l in jax.tree_util.tree_leaves(sub)
                    )
                    for sub in params
                )
                segments = comm_lib.make_segments(
                    layer_sizes, buckets, spec.total
                )
            except ValueError as e:
                reason, segments = f"segment derivation failed: {e}", None
        if reason is None and want == "auto" and len(segments) < 2:
            reason = (
                "single bucket-aligned segment at bucket_cap_mb="
                f"{self.bucket_cap_mb:g} — segmentation would be the barrier "
                "step with extra staging"
            )
            segments = None
        if reason is not None:
            if want is True:
                raise ValueError(
                    f"comm_overlap=true refused: {reason}. Use "
                    "comm_overlap='auto' to fall back to the barrier step "
                    "where segmentation does not apply."
                )
            self._overlap_meta = {
                "enabled": False, "segments": None, "reason": reason,
            }
            return
        self._segments = segments
        self._overlap_meta = {
            "enabled": True, "segments": len(segments), "reason": None,
        }

    @property
    def comm_overlap_meta(self):
        """Overlap-resolution provenance for run_meta (schema v10
        ``comm.overlap``): ``{"enabled", "segments", "reason"}`` after
        :meth:`init_state`, None before."""
        return self._overlap_meta

    def _audit_at_wrap(self, state: TrainState) -> TrainState:
        """torch DDP's ``_verify_params_across_processes`` moment: under
        ``guard``, fingerprint every replica's parameter copy before the
        first step — a construction-time divergence (bad broadcast, corrupt
        host) surfaces as :class:`~tpuddp.resilience.guard.ReplicaDesync`
        (exit 77) instead of a silently forked trajectory. On a 2-D mesh the
        fingerprints cover each device's OWN model shard and compare across
        DATA replicas only — a tensor-parallel shard is *supposed* to differ
        from its model-axis neighbor and must never be convicted for it."""
        if self.guard.enabled:
            guard_lib.audit_or_raise(
                self.mesh, state.params, where="ddp-wrap", specs=self._tp_specs
            )
        return state

    def shard(self, batch):
        """Place a host batch onto the mesh, split over the data axis."""
        return shard_batch(self.mesh, batch)

    def shard_stacked(self, stacked_batch):
        """Place a (K, batch, ...) super-batch for the scan step: axis 1 is the
        data axis, axis 0 the step axis."""
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = data_axes(self.mesh)

        def _put(x):
            spec = P(None, axis, *([None] * (x.ndim - 2)))
            sharding = NamedSharding(self.mesh, spec)
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sharding, np.asarray(x))
            return jax.device_put(x, sharding)

        return jax.tree_util.tree_map(_put, stacked_batch)

    def _check_wus_ready(self):
        if self.weight_update_sharding and self._wus_spec is None:
            raise RuntimeError(
                "weight_update_sharding derives its flat layout from the "
                "initialized parameters; call init_state before the first step"
            )
        if self.comm_hook != "none" and self._comm is None:
            raise RuntimeError(
                f"comm_hook={self.comm_hook!r} derives its bucket plan from "
                "the initialized parameters; call init_state before the "
                "first step"
            )

    @property
    def grad_comm_bytes_per_step(self) -> Optional[int]:
        """Per-replica wire bytes of ONE gradient reduction (the comm-bytes
        counter, parallel/comm.py accounting model): known after
        :meth:`init_state`; None before. The epoch driver and bench multiply
        by optimizer updates to report measured comm volume."""
        return self._grad_comm_bytes

    @property
    def grad_comm_bytes_per_step_f32(self) -> Optional[int]:
        """What one gradient reduction WOULD cost uncompressed (hook="none",
        same layout) — the denominator of a compressed hook's byte-savings
        claim, recorded in the run_meta header so the history file is
        self-contained evidence."""
        return self._grad_comm_bytes_f32

    @property
    def grad_comm_bytes_inter_host(self) -> Optional[int]:
        """The inter-host share of one gradient reduction's wire bytes: the
        compressed shard exchange under ``comm_topology="hierarchical"``;
        the whole payload under ``"flat"`` (the conservative reading — a
        flat collective's bytes all cross the slowest link)."""
        bd = self._grad_comm_breakdown
        return None if bd is None else bd["inter_host"]

    @property
    def grad_comm_bytes_intra_host(self) -> Optional[int]:
        """The intra-host (ICI) share: the f32 reduce-scatter + all-gather
        operands under the hierarchical topology, 0 under flat."""
        bd = self._grad_comm_breakdown
        return None if bd is None else bd["intra_host"]

    @property
    def _hier(self):
        """The (inner, outer) axis pair of the hierarchical exchange, or
        None under the flat topology."""
        if self.comm_topology != "hierarchical":
            return None
        return (LOCAL_AXIS, HOST_AXIS)

    def train_step_many(self, state: TrainState, stacked_batch):
        """K fused train steps per dispatch (lax.scan; see
        training.step.build_train_scan_step)."""
        if self._scan_step is None:
            self._check_wus_ready()
            if self.model_size > 1:
                from tpuddp.parallel import tensor as tp_lib

                self._scan_step = tp_lib.build_tp_train_scan_step(
                    self.model, self.criterion, self.optimizer, self.mesh,
                    self._state_spec, comm=self._comm,
                    guard=self.guard.enabled,
                )
                return self._scan_step(state, stacked_batch)
            self._scan_step = step_lib.build_train_scan_step(
                self.model,
                self.criterion,
                self.optimizer,
                self.mesh,
                mode=self.mode,
                sync_buffers=self.sync_buffers,
                clip_grad_norm=self.clip_grad_norm,
                augment=self.augment,
                remat=self.remat,
                wus_spec=self._wus_spec,
                state_spec=self._state_spec,
                grad_accumulation=self.grad_accumulation,
                comm=self._comm,
                guard=self.guard.enabled,
                hier=self._hier,
                segments=self._segments,
            )
        return self._scan_step(state, stacked_batch)

    def train_step(self, state: TrainState, batch):
        if self.grad_accumulation > 1:
            raise RuntimeError(
                "per-batch train_step is undefined under grad_accumulation "
                f"(= {self.grad_accumulation}): it would apply one full-scale "
                "update per micro-batch. Use train_step_many with chunks that "
                "are whole multiples of the accumulation cycle (the epoch "
                "driver does this automatically)."
            )
        if self._train_step is None:
            self._check_wus_ready()
            if self.model_size > 1:
                from tpuddp.parallel import tensor as tp_lib

                self._train_step = tp_lib.build_tp_train_step(
                    self.model, self.criterion, self.optimizer, self.mesh,
                    self._state_spec, comm=self._comm,
                    guard=self.guard.enabled,
                )
                return self._train_step(state, batch)
            self._train_step = step_lib.build_train_step(
                self.model,
                self.criterion,
                self.optimizer,
                self.mesh,
                mode=self.mode,
                sync_buffers=self.sync_buffers,
                clip_grad_norm=self.clip_grad_norm,
                augment=self.augment,
                remat=self.remat,
                wus_spec=self._wus_spec,
                state_spec=self._state_spec,
                comm=self._comm,
                guard=self.guard.enabled,
                hier=self._hier,
                segments=self._segments,
            )
        return self._train_step(state, batch)

    def eval_step_many(self, state: TrainState, stacked_batch):
        """K fused eval batches per dispatch (lax.scan; see
        training.step.build_eval_scan_step)."""
        if self._eval_scan_step is None:
            self._check_wus_ready()
            if self.model_size > 1:
                from tpuddp.parallel import tensor as tp_lib

                self._eval_scan_step = tp_lib.build_tp_eval_scan_step(
                    self.model, self.criterion, self.mesh, self._state_spec
                )
                return self._eval_scan_step(state, stacked_batch)
            self._eval_scan_step = step_lib.build_eval_scan_step(
                self.model,
                self.criterion,
                self.mesh,
                mode=self.mode,
                transform=self.eval_transform,
                state_spec=self._state_spec,
            )
        return self._eval_scan_step(state, stacked_batch)

    def eval_step(self, state: TrainState, batch):
        if self._eval_step is None:
            self._check_wus_ready()
            if self.model_size > 1:
                from tpuddp.parallel import tensor as tp_lib

                self._eval_step = tp_lib.build_tp_eval_step(
                    self.model, self.criterion, self.mesh, self._state_spec
                )
                return self._eval_step(state, batch)
            self._eval_step = step_lib.build_eval_step(
                self.model,
                self.criterion,
                self.mesh,
                mode=self.mode,
                transform=self.eval_transform,
                state_spec=self._state_spec,
            )
        return self._eval_step(state, batch)

    def forward(self, state: TrainState, x):
        """Inference forward (replicated params, sharded batch). On a
        tensor-parallel wrap the shards are gathered to the canonical host
        layout first — a debugging convenience, not a serving path."""
        params, model_state = state.params, state.model_state
        if self.model_size > 1:
            from tpuddp.parallel import tensor as tp_lib

            params = tp_lib.gather_params(params)
        logits, _ = self.model.apply(
            params, model_state, x, Context(train=False)
        )
        return logits
