"""Device mesh + sharding helpers.

The reference's notion of a "world" is N single-GPU processes joined by NCCL
(multi-GPU-training-torch.py:269-279). The TPU-native notion is a
``jax.sharding.Mesh`` over all chips with a named ``"data"`` axis; data
parallelism = batch sharded over that axis, parameters replicated. The axis is
*named* so that later tensor/pipeline axes can be added to the same mesh
without redesign (SURVEY.md §2c build consequence).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuddp.parallel import backend as _backend

DATA_AXIS = "data"

# The factored data mesh (comm_topology="hierarchical", parallel/comm.py):
# the SAME replica set, with the axis split ("host", "local") so collectives
# can address the intra-host (ICI) and inter-host (DCN) hops separately —
# outer axis first, so consecutive local devices stay adjacent in the mesh.
HOST_AXIS = "host"
LOCAL_AXIS = "local"


def data_axes(mesh: "Mesh"):
    """The axis name(s) forming ``mesh``'s data-parallel dimension: the flat
    ``"data"`` axis when present, else the full factored axis tuple (the
    hierarchical ``("host", "local")`` split). Every mesh tpuddp builds is
    data-parallel over ALL its axes, so the tuple is the whole name list;
    ``jax.lax`` collectives, ``PartitionSpec`` entries, and ``axis_index``
    all accept the tuple wherever the flat name went."""
    names = tuple(mesh.axis_names)
    if DATA_AXIS in names:
        return DATA_AXIS
    return names if len(names) > 1 else names[0]


def local_mesh_devices(
    world_size: Optional[int] = None, backend: Optional[str] = None
) -> Sequence[jax.Device]:
    """Devices forming the data-parallel world (see backend.resolve_devices)."""
    return _backend.resolve_devices(world_size, backend)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[Mapping[str, int]] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """Create a mesh. Default: 1-D mesh over all resolved devices, axis "data".

    ``axes`` maps axis names to sizes, e.g. ``{"data": 4, "model": 2}``; sizes
    must multiply to the device count. Data parallelism only needs the default,
    but the mesh abstraction is N-D from day one.
    """
    if devices is None:
        devices = local_mesh_devices(backend=backend)
    devices = np.asarray(devices, dtype=object)
    if axes is None:
        axes = {DATA_AXIS: devices.size}
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    if int(np.prod(sizes)) != devices.size:
        raise ValueError(f"mesh axes {dict(axes)} do not tile {devices.size} devices")
    return Mesh(devices.reshape(sizes), names)


def data_mesh(world_size: Optional[int] = None, backend: Optional[str] = None) -> Mesh:
    """1-D data-parallel mesh — the DP world the reference builds with mp.spawn."""
    return make_mesh(local_mesh_devices(world_size, backend))


def hierarchical_mesh(
    world_size: Optional[int] = None,
    hosts: Optional[int] = None,
    backend: Optional[str] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """The factored ``("host", "local")`` data mesh for
    ``comm_topology="hierarchical"``: the same replica set as
    :func:`data_mesh`, with the axis split so the comm hooks can run the
    intra-host f32 reduce-scatter / compressed inter-host exchange /
    all-gather pipeline (parallel/comm.py ``reduce_hierarchical``).

    ``hosts`` (the outer-axis size) defaults to ``jax.process_count()`` on a
    real pod; on a single process (the CPU test rung, or one multi-chip
    host) it defaults to 2 — a SIMULATED host split that keeps the factored
    collectives and the intra/inter byte accounting testable without DCN.
    The world must factor: ``hosts`` has to divide it."""
    if devices is None:
        devices = local_mesh_devices(world_size, backend)
    world = len(devices)
    if hosts is None:
        hosts = jax.process_count() if jax.process_count() > 1 else 2
    hosts = int(hosts)
    if hosts < 2 or world % hosts:
        raise ValueError(
            f"comm_topology='hierarchical' needs a factorable world: "
            f"{hosts} host group(s) do not tile {world} device(s); pick a "
            "world size divisible by the host count (or >= 2 devices on the "
            "simulated single-host split)"
        )
    return make_mesh(devices, axes={HOST_AXIS: hosts, LOCAL_AXIS: world // hosts})


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for parameters/optimizer state: replicated on every device
    (the DDP contract: replica-identical params, multi-GPU-training-torch.py:245)."""
    return NamedSharding(mesh, P())


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device. Works multi-process
    (where a plain device_put cannot target non-addressable devices): a jitted
    identity with replicated out_shardings lets each process contribute its
    (identical — broadcast first!) local copy to the global array."""
    return jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))(tree)


def data_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Sharding for a batch: leading axis split over the data mesh axis
    (the factored axis tuple on a hierarchical mesh)."""
    axis = data_axes(mesh)
    spec = P(axis, *([None] * (ndim - 1))) if ndim > 1 else P(axis)
    return NamedSharding(mesh, spec)


def shard_batch(mesh: Mesh, batch):
    """Place a host batch onto the mesh, split over the data axis.

    Single-process: a plain ``device_put`` with a data-sharded NamedSharding.
    Multi-process: each process passes its *local* shard (what its sampler
    loaded) and the global array is assembled across hosts — the TPU-native
    replacement for N dataloaders feeding N processes.
    """
    axis = data_axes(mesh)

    def _put(x):
        sharding = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        if (
            isinstance(x, jax.Array)
            and x.sharding.is_equivalent_to(sharding, x.ndim)
        ):
            return x  # already laid out correctly: no copy, no dispatch
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, np.asarray(x))
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(_put, batch)
