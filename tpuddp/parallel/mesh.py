"""Device mesh + sharding helpers.

The reference's notion of a "world" is N single-GPU processes joined by NCCL
(multi-GPU-training-torch.py:269-279). The TPU-native notion is a
``jax.sharding.Mesh`` over all chips with a named ``"data"`` axis; data
parallelism = batch sharded over that axis, parameters replicated. The axis is
*named* so that later tensor/pipeline axes can be added to the same mesh
without redesign (SURVEY.md §2c build consequence).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuddp.parallel import backend as _backend

DATA_AXIS = "data"


def local_mesh_devices(
    world_size: Optional[int] = None, backend: Optional[str] = None
) -> Sequence[jax.Device]:
    """Devices forming the data-parallel world (see backend.resolve_devices)."""
    return _backend.resolve_devices(world_size, backend)


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[Mapping[str, int]] = None,
    backend: Optional[str] = None,
) -> Mesh:
    """Create a mesh. Default: 1-D mesh over all resolved devices, axis "data".

    ``axes`` maps axis names to sizes, e.g. ``{"data": 4, "model": 2}``; sizes
    must multiply to the device count. Data parallelism only needs the default,
    but the mesh abstraction is N-D from day one.
    """
    if devices is None:
        devices = local_mesh_devices(backend=backend)
    devices = np.asarray(devices, dtype=object)
    if axes is None:
        axes = {DATA_AXIS: devices.size}
    names = tuple(axes.keys())
    sizes = tuple(axes.values())
    if int(np.prod(sizes)) != devices.size:
        raise ValueError(f"mesh axes {dict(axes)} do not tile {devices.size} devices")
    return Mesh(devices.reshape(sizes), names)


def data_mesh(world_size: Optional[int] = None, backend: Optional[str] = None) -> Mesh:
    """1-D data-parallel mesh — the DP world the reference builds with mp.spawn."""
    return make_mesh(local_mesh_devices(world_size, backend))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for parameters/optimizer state: replicated on every device
    (the DDP contract: replica-identical params, multi-GPU-training-torch.py:245)."""
    return NamedSharding(mesh, P())


def replicate(mesh: Mesh, tree):
    """Place a pytree replicated on every mesh device. Works multi-process
    (where a plain device_put cannot target non-addressable devices): a jitted
    identity with replicated out_shardings lets each process contribute its
    (identical — broadcast first!) local copy to the global array."""
    return jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))(tree)


def data_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Sharding for a batch: leading axis split over the "data" mesh axis."""
    spec = P(DATA_AXIS, *([None] * (ndim - 1))) if ndim > 1 else P(DATA_AXIS)
    return NamedSharding(mesh, spec)


def shard_batch(mesh: Mesh, batch):
    """Place a host batch onto the mesh, split over the data axis.

    Single-process: a plain ``device_put`` with a data-sharded NamedSharding.
    Multi-process: each process passes its *local* shard (what its sampler
    loaded) and the global array is assembled across hosts — the TPU-native
    replacement for N dataloaders feeding N processes.
    """
    def _put(x):
        sharding = NamedSharding(mesh, P(DATA_AXIS, *([None] * (x.ndim - 1))))
        if (
            isinstance(x, jax.Array)
            and x.sharding.is_equivalent_to(sharding, x.ndim)
        ):
            return x  # already laid out correctly: no copy, no dispatch
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, np.asarray(x))
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(_put, batch)
