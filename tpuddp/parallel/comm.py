"""Gradient-communication hooks — the tpuddp rebuild of torch DDP's bucketed
allreduce + comm-hook machinery (SURVEY.md §2b: DDP's ``bf16_compress_hook``
et al., the one reference capability tpuddp had not reimplemented natively).

torch DDP flattens gradients into size-capped buckets and lets a registered
comm hook transform each bucket's allreduce (``default_hooks.bf16_compress_hook``
casts the bucket to bf16, allreduces half the bytes, and decompresses).
tpuddp expresses the same pipeline *inside the compiled step*:

1. the gradient pytree is flattened into ONE padded f32 vector with the
   existing :class:`~tpuddp.training.step.FlatParamSpec` vectorizer;
2. the vector is split into size-capped contiguous **buckets**
   (``bucket_cap_mb``, torch's knob/default): whole leaves are packed
   greedily in deterministic ``tree_flatten`` order, so many small tensors
   coalesce into one collective instead of paying per-tensor latency, while
   an oversized leaf gets a bucket of its own;
3. each bucket runs the configured **hook**:

   - ``"none"``  — today's full-precision ``lax.pmean`` (the default; the
     bucketed flat path is bypassed entirely, zero behavior change);
   - ``"bf16"``  — cast the bucket to bf16, ``lax.psum`` it (HALF the
     interconnect bytes), decompress to f32, divide by world;
   - ``"bf16_ef"`` — ``bf16`` plus **error feedback**: each replica keeps a
     persistent local residual of what compression discarded and adds it
     back into the next step's send, so quantization error accumulates into
     later updates instead of biasing the trajectory (1-bit-Adam/DynamiQ
     lineage; arxiv.org/abs/2602.08923). The residual is carried in
     ``TrainState.comm_state`` and checkpoints with the rest of the state.

Under ``weight_update_sharding`` the compressed payload is **reduce-
scattered** instead: the bf16 vector is ``psum_scatter``'d whole (the scatter
hands every replica a contiguous 1/N shard aligned with its optimizer-moment
shard, so the bucket partition would scramble shard ownership — buckets
degenerate to the full vector there and remain an accounting construct).
Gradient wire bytes still halve; the f32 parameter all-gather is unchanged.

Modes and honesty:

- ``mode="shard_map"`` (explicit): the emitted program requests the
  collective in the wire dtype — the lowered step carries a bf16
  all-reduce/reduce-scatter (asserted in tests/test_comm.py; TPU ICI runs
  bf16 collectives natively, while backends without them — the CPU test
  world — legalize to f32 at compile time, preserving the quantization
  numerics). :func:`comm_bytes_for_hook` is the measured-artifact counter
  for the reduction.
- ``mode="auto"`` / the managed Accelerator: XLA inserts the cross-replica
  psum inside backward where a dtype cast cannot be interposed, so the hook
  quantizes the *aggregated* gradient with the same error-feedback residual
  — the convergence contract (what the numerics tests pin) is preserved,
  but the byte reduction is a property of the explicit path only, and the
  counter accounts for it honestly (``comm_bytes_for_hook(wire=False)``
  reports the f32 payload those paths actually reduce).
  :func:`local_quantize` is that tree-level emulation.

Per-replica residual layout (shard_map): a flat ``(world * total,)`` f32
vector sharded ``P("data")`` over the mesh — inside ``shard_map`` each
replica sees its own ``(total,)`` slice, exactly like the weight-update-
sharded optimizer moments. Checkpointing gathers it cross-host like any
other sharded leaf (training/checkpoint.py).

Numerical-guard composition (``training.guard``, resilience/guard.py): the
non-finite firewall checks the f32 gradient payload — post-allreduce on the
explicit path (bf16 keeps f32's exponent range, so quantization cannot mask
a non-finite payload from the decompressed check), pre-quantization on the
auto/managed path where the aggregate already exists — and a skipped step
hands back the PRE-step residual, so a poisoned ``send`` (gradient +
residual) never contaminates the error-feedback state (training/step.py's
``gate``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

COMM_HOOKS = ("none", "bf16", "bf16_ef")

# torch DDP's bucket_cap_mb default. Small enough that many buckets exist on
# real models (XLA can pipeline the collectives), large enough that small
# tensors coalesce instead of paying per-tensor collective latency.
DEFAULT_BUCKET_CAP_MB = 25

_WIRE_DTYPES = {"bf16": jnp.bfloat16, "bf16_ef": jnp.bfloat16}
_F32_BYTES = 4


def wire_dtype(hook: str):
    """The on-the-wire dtype of a hook's gradient collective (f32 for none)."""
    return _WIRE_DTYPES.get(hook, jnp.float32)


def wire_itemsize(hook: str) -> int:
    return jnp.dtype(wire_dtype(hook)).itemsize


def validate_hook(hook: str) -> str:
    if hook not in COMM_HOOKS:
        raise ValueError(f"unknown comm_hook {hook!r}; one of {COMM_HOOKS}")
    return hook


def make_buckets(
    sizes: Tuple[int, ...], total: int, bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB
) -> Tuple[Tuple[int, int], ...]:
    """Partition ``[0, total)`` into contiguous ``(start, end)`` buckets.

    ``sizes`` are the flat-vector leaf sizes in ``tree_flatten`` order (the
    deterministic order :func:`~tpuddp.training.step._tree_to_vec`
    concatenates in), so bucket boundaries land on whole-leaf boundaries:
    leaves are packed greedily until the next leaf would push the bucket past
    ``bucket_cap_mb`` of f32 payload; a single leaf larger than the cap gets
    its own bucket (torch DDP's rule — tensors are never split). The final
    bucket absorbs the spec's world-multiple zero padding (``total`` minus
    the raw leaf sum), so the buckets always cover the padded vector exactly.
    """
    if bucket_cap_mb <= 0:
        raise ValueError(f"bucket_cap_mb must be > 0, got {bucket_cap_mb!r}")
    cap_elems = max(1, int(bucket_cap_mb * 1024 * 1024) // _F32_BYTES)
    buckets = []
    start = 0
    cursor = 0
    filled = 0
    for size in sizes:
        if filled and filled + size > cap_elems:
            buckets.append((start, cursor))
            start, filled = cursor, 0
        cursor += size
        filled += size
    # the tail bucket: remaining leaves plus the zero padding up to `total`
    if cursor < total or filled or start < total:
        buckets.append((start, total))
    assert buckets and buckets[0][0] == 0 and buckets[-1][1] == total
    return tuple(buckets)


class GradComm(NamedTuple):
    """Static comm plan for one (model, world, hook) triple: the flat spec the
    gradients vectorize through, the bucket partition, and the hook."""

    spec: "FlatParamSpec"  # noqa: F821 - tpuddp.training.step.FlatParamSpec
    buckets: Tuple[Tuple[int, int], ...]
    hook: str
    world: int

    # -- properties ---------------------------------------------------------
    @property
    def compressed(self) -> bool:
        return self.hook in ("bf16", "bf16_ef")

    @property
    def needs_residual(self) -> bool:
        return self.hook == "bf16_ef"

    # -- residual lifecycle -------------------------------------------------
    def init_residual(self, per_replica: bool) -> Optional[np.ndarray]:
        """Host zeros for ``TrainState.comm_state``: ``(world * total,)`` when
        the residual is per-replica (shard_map — placed ``P("data")`` so each
        replica owns its slice) or ``(total,)`` replicated (auto mode, where
        the hook quantizes the already-aggregated gradient)."""
        if not self.needs_residual:
            return None
        n = self.spec.total * (self.world if per_replica else 1)
        return np.zeros((n,), np.float32)

    # -- in-jit hook pipeline ----------------------------------------------
    def reduce(self, grads, residual, axis_name: Optional[str]):
        """The bucketed hook pipeline: grads tree in, cross-replica MEAN
        grads tree out, plus the new residual. ``axis_name=None`` is the
        auto-mode emulation (no collective; XLA already reduced)."""
        from tpuddp.parallel.collectives import bucketed_psum
        from tpuddp.training.step import _tree_to_vec, _vec_to_tree

        g_vec = _tree_to_vec(grads, self.spec)
        send = g_vec if residual is None else g_vec + residual
        reduced = bucketed_psum(
            send, self.buckets, wire_dtype(self.hook), axis_name
        )
        if axis_name is not None:
            reduced = reduced / self.world
        new_residual = residual
        if self.needs_residual:
            # what the wire kept is elementwise, so the whole-vector round
            # trip equals the per-bucket casts that were actually sent
            new_residual = send - send.astype(wire_dtype(self.hook)).astype(
                jnp.float32
            )
        return _vec_to_tree(reduced, self.spec), new_residual

    def reduce_scatter(self, g_vec, residual, axis_name: str):
        """The weight-update-sharding composition: compress the whole padded
        vector and ``psum_scatter`` the bf16 payload — each replica receives
        the f32-decompressed MEAN gradient for its contiguous 1/N shard
        (aligned with its optimizer-moment shard). Returns
        ``(g_shard_mean_f32, new_residual)``; the residual stays full-length
        and local (it is this replica's compression error over the whole
        vector, not its shard's)."""
        from tpuddp.parallel.collectives import psum_scatter_compressed

        send = g_vec if residual is None else g_vec + residual
        shard, comp = psum_scatter_compressed(
            send, wire_dtype(self.hook), axis_name
        )
        shard = shard / self.world
        new_residual = residual
        if self.needs_residual:
            new_residual = send - comp.astype(jnp.float32)
        return shard, new_residual

def make_grad_comm(
    params,
    world: int,
    comm_hook: str = "none",
    bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB,
    flat_spec=None,
) -> Optional[GradComm]:
    """Build the comm plan for ``params`` (None for hook "none" — the legacy
    pmean path needs no plan; accounting for it comes from a bf16 plan's
    sibling via :func:`comm_bytes_for_hook`). ``flat_spec`` reuses an
    existing :class:`FlatParamSpec` (the weight-update-sharding one) so the
    residual aligns with the scattered vector."""
    validate_hook(comm_hook)
    if comm_hook == "none":
        return None
    from tpuddp.training.step import make_flat_param_spec

    spec = flat_spec if flat_spec is not None else make_flat_param_spec(params, world)
    buckets = make_buckets(spec.sizes, spec.total, bucket_cap_mb)
    return GradComm(spec=spec, buckets=buckets, hook=comm_hook, world=world)


def comm_bytes_for_hook(
    params, world: int, comm_hook: str, wus: bool = False, wire: bool = True
) -> int:
    """Analytic per-replica wire payload of ONE gradient reduction (bytes) —
    the counter the dryrun/bench compare across hooks: the operand bytes
    entering the gradient collective, in its wire dtype. Ring-transfer
    multipliers (2(N-1)/N for allreduce, (N-1)/N for reduce-scatter) are
    topology constants that cancel in any same-shape comparison, so the
    counter reports the payload itself — the quantity the hook changes.
    ``wus`` counts the gradient reduce-scatter only (the f32 parameter
    all-gather is a separate, hook-independent exchange). ``wire=False``
    (``mode="auto"`` / the managed Accelerator, where XLA inserts the psum
    and the hook only emulates the quantization) accounts the collective at
    f32 regardless of hook — the counter must never record a byte cut that
    did not reach the wire."""
    validate_hook(comm_hook)
    from tpuddp.training.step import make_flat_param_spec

    spec = make_flat_param_spec(params, world)
    if not wire:
        comm_hook = "none"
    if comm_hook == "none" and not wus:
        # the tree-level pmean reduces exactly the raw (unpadded) leaf
        # elements; flat-vector paths carry the world-multiple padding
        return sum(spec.sizes) * _F32_BYTES
    return spec.total * wire_itemsize(comm_hook)


def local_quantize(grads, residual, hook: str):
    """Tree-level hook emulation for the managed/auto path: quantize the
    (already globally-aggregated) gradient through the wire dtype, with the
    same error-feedback residual semantics as the explicit path. ``residual``
    is a pytree like ``grads`` (or None for hook "bf16"). Returns
    ``(quantized_grads, new_residual)``."""
    validate_hook(hook)
    if hook == "none":
        return grads, residual
    dt = wire_dtype(hook)
    if hook == "bf16":
        return (
            jax.tree_util.tree_map(
                lambda g: g.astype(dt).astype(jnp.float32), grads
            ),
            residual,
        )
    send = jax.tree_util.tree_map(lambda g, r: g + r, grads, residual)
    quant = jax.tree_util.tree_map(
        lambda s: s.astype(dt).astype(jnp.float32), send
    )
    new_residual = jax.tree_util.tree_map(lambda s, q: s - q, send, quant)
    return quant, new_residual


def init_residual_tree(params):
    """Zeros-like residual pytree for :func:`local_quantize`'s bf16_ef."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(np.shape(p), jnp.float32), params
    )


def redistribute_residual(mat: np.ndarray, new_world: int) -> Tuple[np.ndarray, str]:
    """Re-map per-replica error-feedback residuals onto a new world size —
    the elastic-resume rule for ``TrainState.comm_state`` (DynamiQ's
    dynamic-world-size compression-state motivation, arxiv.org/abs/2602.08923).

    ``mat`` is the residual viewed as ``(old_world, per)``: row ``r`` is
    replica ``r``'s accumulated compression error over the whole flat
    gradient vector. What steers the trajectory is the SUM over replicas —
    each replica adds its residual into its next send and the sends are
    ``psum``'d — so any re-mapping that preserves the per-element sum over
    the replica axis preserves the aggregate un-sent error budget:

    - shrink, ``new_world`` divides ``old_world``: each new replica takes the
      elementwise f32 sum of one group of ``old/new`` consecutive old rows
      (``reshape(new, k, per).sum(axis=1)`` — exactly reproducible, so tests
      can assert the redistribution bitwise);
    - grow, ``old_world`` divides ``new_world``: old row ``r`` moves verbatim
      to new row ``r * (new/old)``; the other rows start at zero (pure
      placement — bitwise sum-preserving);
    - no divisor relation (``M∤N`` both ways): there is no sum-preserving
      alignment of whole rows, so the residual RESETS to zero — the
      documented fallback. The un-sent error (bounded by one step's bf16
      rounding per element) is dropped once; callers record a typed
      ``comm_state_reset`` event row so the discontinuity is auditable.

    Returns ``(new_mat, action)`` with ``action`` one of ``"unchanged"`` /
    ``"redistributed"`` / ``"reset"``."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        raise ValueError(f"expected a (world, per) residual view, got {mat.shape}")
    old_world, per = mat.shape
    if new_world < 1:
        raise ValueError(f"new_world must be >= 1, got {new_world}")
    if new_world == old_world:
        return mat, "unchanged"
    if old_world % new_world == 0:
        k = old_world // new_world
        return mat.reshape(new_world, k, per).sum(axis=1), "redistributed"
    if new_world % old_world == 0:
        k = new_world // old_world
        out = np.zeros((new_world, per), mat.dtype)
        out[::k] = mat
        return out, "redistributed"
    return np.zeros((new_world, per), mat.dtype), "reset"
