"""Gradient-communication hooks — the tpuddp rebuild of torch DDP's bucketed
allreduce + comm-hook machinery (SURVEY.md §2b: DDP's ``bf16_compress_hook``
et al., the one reference capability tpuddp had not reimplemented natively).

torch DDP flattens gradients into size-capped buckets and lets a registered
comm hook transform each bucket's allreduce (``default_hooks.bf16_compress_hook``
casts the bucket to bf16, allreduces half the bytes, and decompresses).
tpuddp expresses the same pipeline *inside the compiled step*:

1. the gradient pytree is flattened into ONE padded f32 vector with the
   existing :class:`~tpuddp.training.step.FlatParamSpec` vectorizer;
2. the vector is split into size-capped contiguous **buckets**
   (``bucket_cap_mb``, torch's knob/default): whole leaves are packed
   greedily in deterministic ``tree_flatten`` order, so many small tensors
   coalesce into one collective instead of paying per-tensor latency, while
   an oversized leaf gets a bucket of its own;
3. each bucket runs the configured **hook**:

   - ``"none"``  — today's full-precision ``lax.pmean`` (the default; the
     bucketed flat path is bypassed entirely, zero behavior change);
   - ``"bf16"``  — cast the bucket to bf16, ``lax.psum`` it (HALF the
     interconnect bytes), decompress to f32, divide by world;
   - ``"bf16_ef"`` — ``bf16`` plus **error feedback**: each replica keeps a
     persistent local residual of what compression discarded and adds it
     back into the next step's send, so quantization error accumulates into
     later updates instead of biasing the trajectory (1-bit-Adam/DynamiQ
     lineage; arxiv.org/abs/2602.08923). The residual is carried in
     ``TrainState.comm_state`` and checkpoints with the rest of the state;
   - ``"int8_ef"`` — per-bucket max-abs symmetric **int8** quantization
     (~75% fewer wire bytes): int8 codes + one f32 scale per bucket are
     all-gathered and dequant-summed locally (per-replica scales make a
     direct psum meaningless — torch's ``quantization_pertensor_hook``
     takes the same shape), with bf16_ef's error-feedback residual;
   - ``"topk_ef"`` — per-bucket **top-k by magnitude** (``topk_density``,
     default 0.1 => ~87.5% fewer wire bytes): int8-quantized values + int32
     indices + the bucket scale on the wire; the unsent complement AND the
     quantization error fold into the same residual.

Topology (``comm_topology``): ``"flat"`` runs one collective over the whole
data axis; ``"hierarchical"`` (:meth:`GradComm.reduce_hierarchical`, over
the factored ``("host", "local")`` mesh — mesh.hierarchical_mesh) runs
intra-host f32 reduce-scatter, a compressed inter-host exchange of each
1/L shard, then all-gather — only the compressed shard crosses the slow
inter-host link, and :func:`comm_bytes_breakdown` accounts the two hops
separately.

Under ``weight_update_sharding`` the compressed payload is **reduce-
scattered** instead: the bf16 vector is ``psum_scatter``'d whole (the scatter
hands every replica a contiguous 1/N shard aligned with its optimizer-moment
shard, so the bucket partition would scramble shard ownership — buckets
degenerate to the full vector there and remain an accounting construct).
Gradient wire bytes still halve; the f32 parameter all-gather is unchanged.

Modes and honesty:

- ``mode="shard_map"`` (explicit): the emitted program requests the
  collective in the wire dtype — the lowered step carries a bf16
  all-reduce/reduce-scatter (asserted in tests/test_comm.py; TPU ICI runs
  bf16 collectives natively, while backends without them — the CPU test
  world — legalize to f32 at compile time, preserving the quantization
  numerics). :func:`comm_bytes_for_hook` is the measured-artifact counter
  for the reduction.
- ``mode="auto"`` / the managed Accelerator: XLA inserts the cross-replica
  psum inside backward where a dtype cast cannot be interposed, so the hook
  quantizes the *aggregated* gradient with the same error-feedback residual
  — the convergence contract (what the numerics tests pin) is preserved,
  but the byte reduction is a property of the explicit path only, and the
  counter accounts for it honestly (``comm_bytes_for_hook(wire=False)``
  reports the f32 payload those paths actually reduce).
  :func:`local_quantize` is that tree-level emulation.

Per-replica residual layout (shard_map): a flat ``(world * total,)`` f32
vector sharded ``P("data")`` over the mesh — inside ``shard_map`` each
replica sees its own ``(total,)`` slice, exactly like the weight-update-
sharded optimizer moments. Checkpointing gathers it cross-host like any
other sharded leaf (training/checkpoint.py).

Numerical-guard composition (``training.guard``, resilience/guard.py): the
non-finite firewall checks the f32 gradient payload — post-allreduce on the
explicit path (bf16 keeps f32's exponent range, so quantization cannot mask
a non-finite payload from the decompressed check), pre-quantization on the
auto/managed path where the aggregate already exists — and a skipped step
hands back the PRE-step residual, so a poisoned ``send`` (gradient +
residual) never contaminates the error-feedback state (training/step.py's
``gate``).
"""

from __future__ import annotations

import re
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

COMM_HOOKS = ("none", "bf16", "bf16_ef", "int8_ef", "topk_ef")

# Hooks that carry the persistent error-feedback residual in
# TrainState.comm_state (the DynamiQ lineage, arxiv.org/abs/2602.08923):
# whatever a step's compression dropped — quantization rounding for
# bf16_ef/int8_ef, the whole unsent complement for topk_ef — re-enters the
# next step's send, so compression error accumulates into later updates
# instead of biasing the trajectory.
EF_HOOKS = ("bf16_ef", "int8_ef", "topk_ef")

# torch DDP's bucket_cap_mb default. Small enough that many buckets exist on
# real models (XLA can pipeline the collectives), large enough that small
# tensors coalesce instead of paying per-tensor collective latency.
DEFAULT_BUCKET_CAP_MB = 25

# topk_ef's density knob default: keep the top 10% of each bucket by
# magnitude (values int8-quantized + int32 indices + one f32 scale per
# bucket => ~87.5% fewer gradient wire bytes than f32 at this density).
DEFAULT_TOPK_DENSITY = 0.1

_WIRE_DTYPES = {"bf16": jnp.bfloat16, "bf16_ef": jnp.bfloat16}
_F32_BYTES = 4
_INT8_BYTES = 1
_IDX_BYTES = 4  # top-k indices travel as int32
_SCALE_BYTES = 4  # one f32 max-abs scale per bucket rides the wire

COMM_TOPOLOGIES = ("flat", "hierarchical")


def wire_dtype(hook: str):
    """The on-the-wire dtype of a hook's gradient collective (f32 for none).
    Only meaningful for the dense cast hooks (bf16/bf16_ef); the int8/top-k
    hooks carry a structured payload (int8 values [+ int32 indices] + f32
    scales) whose bytes :func:`comm_bytes_for_hook` accounts per part."""
    return _WIRE_DTYPES.get(hook, jnp.float32)


def wire_itemsize(hook: str) -> int:
    return jnp.dtype(wire_dtype(hook)).itemsize


def validate_hook(hook: str) -> str:
    if hook not in COMM_HOOKS:
        raise ValueError(f"unknown comm_hook {hook!r}; one of {COMM_HOOKS}")
    return hook


def validate_topology(topology: str) -> str:
    if topology not in COMM_TOPOLOGIES:
        raise ValueError(
            f"unknown comm_topology {topology!r}; one of {COMM_TOPOLOGIES}"
        )
    return topology


def loss_parity_tol(hook: str, base_loss: float) -> float:
    """The documented loss-trajectory parity bound of each hook vs the
    uncompressed run — what the dryrun, the full gate's compression-matrix
    leg, and the bench assert. Dense hooks (bf16*/int8_ef) track the f32
    trajectory step for step: ``max(0.05, 0.02 |base|)`` (the bf16_ef bound
    PR 2 shipped). ``topk_ef`` provably converges to the same optimum but
    with an error-feedback WARMUP LAG of O(1/density) steps (until every
    coordinate has been sent at least once, ~90% of the gradient rides the
    residual at density 0.1), so short-horizon comparisons get the looser
    ``max(0.35, 0.25 |base|)``; past the warmup (>= ~2/density updates) the
    trajectories re-converge and the dense bound empirically holds again
    (tests/test_comm.py pins both regimes)."""
    validate_hook(hook)
    if hook == "topk_ef":
        return max(0.35, 0.25 * abs(base_loss))
    return max(0.05, 0.02 * abs(base_loss))


def bucket_topk(size: int, density: float) -> int:
    """Elements topk_ef keeps of a ``size``-element bucket: ``density`` of it,
    floored, never below 1 (an empty send would stall the layer forever)."""
    if not (0.0 < density <= 1.0):
        raise ValueError(f"topk density must be in (0, 1], got {density!r}")
    return max(1, int(size * density))


# ------------------------------------------------- int8 / top-k primitives --


def quantize_int8(b, scale):
    """Symmetric max-abs int8 quantization of a bucket against ``scale``
    (= max|b| / 127). The divide guards the all-zero bucket (scale 0 -> send
    zeros); a NON-FINITE scale (any NaN/Inf in the bucket) is deliberately
    NOT guarded — dequantization multiplies by the raw scale, so a poisoned
    bucket decompresses to NaN everywhere and the numerical-guard firewall
    sees it (int8's range, unlike bf16's exponent-preserving cast, could
    otherwise mask a non-finite payload)."""
    denom = jnp.where(scale > 0, scale, 1.0)
    return jnp.clip(jnp.round(b / denom), -127, 127).astype(jnp.int8)


def int8_scale(b):
    """Per-bucket max-abs scale (f32 scalar); NaN/Inf in the bucket poisons
    it, which is the guard-visibility contract (see quantize_int8)."""
    return (jnp.max(jnp.abs(b)) / 127.0).astype(jnp.float32)


def make_buckets(
    sizes: Tuple[int, ...], total: int, bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB
) -> Tuple[Tuple[int, int], ...]:
    """Partition ``[0, total)`` into contiguous ``(start, end)`` buckets.

    ``sizes`` are the flat-vector leaf sizes in ``tree_flatten`` order (the
    deterministic order :func:`~tpuddp.training.step._tree_to_vec`
    concatenates in), so bucket boundaries land on whole-leaf boundaries:
    leaves are packed greedily until the next leaf would push the bucket past
    ``bucket_cap_mb`` of f32 payload; a single leaf larger than the cap gets
    its own bucket (torch DDP's rule — tensors are never split). The final
    bucket absorbs the spec's world-multiple zero padding (``total`` minus
    the raw leaf sum), so the buckets always cover the padded vector exactly.
    """
    if bucket_cap_mb <= 0:
        raise ValueError(f"bucket_cap_mb must be > 0, got {bucket_cap_mb!r}")
    cap_elems = max(1, int(bucket_cap_mb * 1024 * 1024) // _F32_BYTES)
    buckets = []
    start = 0
    cursor = 0
    filled = 0
    for size in sizes:
        if filled and filled + size > cap_elems:
            buckets.append((start, cursor))
            start, filled = cursor, 0
        cursor += size
        filled += size
    # the tail bucket: remaining leaves plus the zero padding up to `total`
    if cursor < total or filled or start < total:
        buckets.append((start, total))
    assert buckets and buckets[0][0] == 0 and buckets[-1][1] == total
    return tuple(buckets)


class CommSegment(NamedTuple):
    """One backward segment of the segmented-overlap step (``comm_overlap``,
    training/step.py): a contiguous run of model children whose flat-vector
    span is exactly a union of whole buckets, so the segment's collective can
    be issued the moment its backward VJP materializes — without ever
    splitting a bucket (the byte accounting stays per-bucket and identical
    to barrier mode by construction)."""

    layers: Tuple[int, int]  # [start, end) child indices of the Sequential
    flat: Tuple[int, int]  # [start, end) offsets into the padded flat vector
    buckets: Tuple[Tuple[int, int], ...]  # absolute (start, end) bucket slices


def make_segments(
    layer_sizes: Tuple[int, ...],
    buckets: Tuple[Tuple[int, int], ...],
    total: int,
) -> Tuple[CommSegment, ...]:
    """Derive the backward segments from the existing bucket assembly.

    ``layer_sizes`` are the per-child flat element counts of a Sequential
    model in ``tree_flatten`` order (child i's parameters occupy the
    contiguous flat span ``[sum(sizes[:i]), sum(sizes[:i+1]))`` because the
    params pytree is a tuple over children). A segment boundary is every
    layer boundary that coincides with a bucket edge — buckets are never
    split, and a bucket that straddles a layer boundary simply fuses those
    layers into one segment (torch DDP's rule in flat-vector form). The
    final segment extends to ``total`` so the spec's world-multiple padding
    rides the tail bucket exactly as in barrier mode. Parameter-free
    children (ReLU, Flatten) produce zero-width spans and attach to the
    segment of the parameterized layer they follow."""
    offsets = [0]
    for n in layer_sizes:
        offsets.append(offsets[-1] + int(n))
    if offsets[-1] > total:
        raise ValueError(
            f"layer sizes sum to {offsets[-1]} > padded total {total}"
        )
    offsets[-1] = total  # padding rides the last layer's segment
    edges = {s for s, _ in buckets} | {e for _, e in buckets}
    bounds = [0]
    for i, off in enumerate(offsets[1:-1], start=1):
        # a boundary must advance the flat cursor (skip zero-param runs) and
        # land on a bucket edge (never split a bucket)
        if off > bounds[-1] and off in edges:
            bounds.append(off)
    if total > bounds[-1]:
        bounds.append(total)
    elif bounds == [0]:  # zero-parameter model: one degenerate segment
        bounds.append(total)
    segs = []
    layer_cursor = 0
    n_layers = len(layer_sizes)
    for lo, hi in zip(bounds, bounds[1:]):
        first = layer_cursor
        while layer_cursor < n_layers and offsets[layer_cursor + 1] <= hi:
            layer_cursor += 1
        segs.append(CommSegment(
            layers=(first, layer_cursor),
            flat=(lo, hi),
            buckets=tuple(b for b in buckets if lo <= b[0] and b[1] <= hi),
        ))
    if segs:
        # trailing parameter-free children attach to the last segment
        segs[-1] = segs[-1]._replace(layers=(segs[-1].layers[0], n_layers))
    assert sum(len(s.buckets) for s in segs) == len(buckets)
    return tuple(segs)


class GradComm(NamedTuple):
    """Static comm plan for one (model, world, hook) triple: the flat spec the
    gradients vectorize through, the bucket partition, the hook, and the
    top-k density (ignored by the dense hooks)."""

    spec: "FlatParamSpec"  # noqa: F821 - tpuddp.training.step.FlatParamSpec
    buckets: Tuple[Tuple[int, int], ...]
    hook: str
    world: int
    density: float = DEFAULT_TOPK_DENSITY

    # -- properties ---------------------------------------------------------
    @property
    def compressed(self) -> bool:
        return self.hook != "none"

    @property
    def needs_residual(self) -> bool:
        return self.hook in EF_HOOKS

    # -- residual lifecycle -------------------------------------------------
    def init_residual(self, per_replica: bool) -> Optional[np.ndarray]:
        """Host zeros for ``TrainState.comm_state``: ``(world * total,)`` when
        the residual is per-replica (shard_map — placed ``P("data")`` so each
        replica owns its slice) or ``(total,)`` replicated (auto mode, where
        the hook quantizes the already-aggregated gradient)."""
        if not self.needs_residual:
            return None
        n = self.spec.total * (self.world if per_replica else 1)
        return np.zeros((n,), np.float32)

    # -- per-bucket compress/exchange (SUM over replicas + own kept part) ---
    def _exchange_bucket(self, b, axis_name):
        """One bucket through the hook's wire format: returns
        ``(summed_f32, kept_f32)`` where ``summed`` is the cross-replica SUM
        of every replica's decompressed payload (this replica's own payload
        when ``axis_name=None`` — the auto-mode emulation) and ``kept`` is
        what THIS replica's send survived the round trip as (the
        error-feedback subtrahend)."""
        from tpuddp.parallel import collectives as col

        if self.hook in ("bf16", "bf16_ef"):
            comp = b.astype(wire_dtype(self.hook))
            kept = comp.astype(jnp.float32)
            if axis_name is None:
                return kept, kept
            from jax import lax

            return lax.psum(comp, axis_name).astype(jnp.float32), kept
        if self.hook == "int8_ef":
            scale = int8_scale(b)
            q = quantize_int8(b, scale)
            kept = q.astype(jnp.float32) * scale
            if axis_name is None:
                return kept, kept
            return col.allgather_dequant_sum(q, scale, axis_name), kept
        if self.hook == "topk_ef":
            k = bucket_topk(int(b.shape[0]), self.density)
            from jax import lax

            _, idx = lax.top_k(jnp.abs(b), k)
            vals = jnp.take(b, idx)
            # whole-bucket scale, not top-k-only: max|vals| == max|b| on
            # finite buckets (top-k selects the max), and a NaN anywhere in
            # the bucket poisons the scale even if top_k's NaN ordering
            # happened not to select it — the guard-visibility contract
            scale = int8_scale(b)
            q = quantize_int8(vals, scale)
            kept = jnp.zeros_like(b).at[idx].set(q.astype(jnp.float32) * scale)
            if axis_name is None:
                return kept, kept
            return (
                col.allgather_topk_sum(idx, q, scale, int(b.shape[0]), axis_name),
                kept,
            )
        raise AssertionError(f"hook {self.hook!r} has no exchange")

    def _compressed_sum(self, send, axis_name):
        """The whole padded vector through the bucketed exchange: per-bucket
        compress + collective-SUM + decompress, reassembled, plus the kept
        (round-tripped) view of this replica's send."""
        from jax import lax

        sums, keeps = [], []
        for s, e in self.buckets:
            b = lax.slice(send, (s,), (e,))
            summed, kept = self._exchange_bucket(b, axis_name)
            sums.append(summed)
            keeps.append(kept)
        return jnp.concatenate(sums), jnp.concatenate(keeps)

    def exchange_segment(self, send, seg: "CommSegment", axis_name):
        """One backward segment's slice of the bucketed exchange
        (``comm_overlap``): ``send`` is the segment's local send vector
        (gradient slice + residual slice, ``seg.flat`` elements long).
        Returns ``(summed_f32, kept_f32)`` concatenated over the segment's
        buckets — element for element the ``seg.flat`` slice of what
        :meth:`_compressed_sum` computes over the full vector, because every
        bucket lies whole inside exactly one segment (the
        :func:`make_segments` invariant). Issued from inside the backward
        walk, this is the collective that overlaps the next segment's VJP."""
        from jax import lax

        lo = seg.flat[0]
        sums, keeps = [], []
        for s, e in seg.buckets:
            b = lax.slice(send, (s - lo,), (e - lo,))
            summed, kept = self._exchange_bucket(b, axis_name)
            sums.append(summed)
            keeps.append(kept)
        return jnp.concatenate(sums), jnp.concatenate(keeps)

    # -- in-jit hook pipeline ----------------------------------------------
    def reduce(self, grads, residual, axis_name):
        """The bucketed hook pipeline: grads tree in, cross-replica MEAN
        grads tree out, plus the new residual. ``axis_name=None`` is the
        auto-mode emulation (no collective; XLA already reduced);
        ``axis_name`` may be a tuple of mesh axis names (the factored
        ("host", "local") data mesh under a flat topology)."""
        from tpuddp.training.step import _tree_to_vec, _vec_to_tree

        g_vec = _tree_to_vec(grads, self.spec)
        send = g_vec if residual is None else g_vec + residual
        reduced, kept = self._compressed_sum(send, axis_name)
        if axis_name is not None:
            reduced = reduced / self.world
        new_residual = residual
        if self.needs_residual:
            new_residual = send - kept
        return _vec_to_tree(reduced, self.spec), new_residual

    def reduce_hierarchical(self, grads, residual, inner: str, outer: str):
        """The multi-hop reduction (``comm_topology="hierarchical"``) over a
        factored ``(outer, inner)`` = ``("host", "local")`` data mesh:

        1. **intra-host f32 reduce-scatter** over ``inner``: each local
           device ends with the host-sum of one contiguous 1/L shard of the
           send — full precision, the cheap ICI hop;
        2. **compressed inter-host exchange** over ``outer``: the shard
           (ONE bucket — the scatter already partitioned the vector) goes
           through the hook's wire format, so only the compressed payload
           crosses the slow inter-host link;
        3. **all-gather** over ``inner`` reassembles the full reduced vector
           on every device.

        Error feedback: the only lossy hop is (2), and its error is owned by
        exactly one (host, local) pair per shard — this replica's new
        residual is its shard's compression error placed at the shard's
        offset (zeros elsewhere), so the replica-axis SUM of residuals still
        equals the total un-sent error and the elastic
        :func:`redistribute_residual` rules apply unchanged. The residual
        re-enters step (1) next time at full f32 precision."""
        from jax import lax

        from tpuddp.training.step import _tree_to_vec, _vec_to_tree

        g_vec = _tree_to_vec(grads, self.spec)
        send = g_vec if residual is None else g_vec + residual
        shard = lax.psum_scatter(send, inner, scatter_dimension=0, tiled=True)
        if self.hook == "none":
            shard_sum, kept = lax.psum(shard, outer), shard
        else:
            single = self._replace(buckets=((0, int(shard.shape[0])),))
            shard_sum, kept = single._exchange_bucket(shard, outer)
        reduced = lax.all_gather(shard_sum, inner, tiled=True) / self.world
        new_residual = residual
        if self.needs_residual:
            shard_n = int(shard.shape[0])
            offset = lax.axis_index(inner) * shard_n
            new_residual = lax.dynamic_update_slice(
                jnp.zeros_like(send), shard - kept, (offset,)
            )
        return _vec_to_tree(reduced, self.spec), new_residual

    def reduce_scatter(self, g_vec, residual, axis_name):
        """The weight-update-sharding composition: compress the whole padded
        vector and exchange it so each replica receives the f32-decompressed
        MEAN gradient for its contiguous 1/N shard (aligned with its
        optimizer-moment shard) — ``psum_scatter`` in the wire dtype for the
        bf16 hooks; the structured int8/top-k payloads are exchanged whole
        (one bucket — the scatter would scramble index ownership) and the
        own shard sliced from the decompressed sum. Returns
        ``(g_shard_mean_f32, new_residual)``; the residual stays full-length
        and local (it is this replica's compression error over the whole
        vector, not its shard's)."""
        from jax import lax

        from tpuddp.parallel.collectives import psum_scatter_compressed

        send = g_vec if residual is None else g_vec + residual
        if self.hook in ("bf16", "bf16_ef"):
            shard, comp = psum_scatter_compressed(
                send, wire_dtype(self.hook), axis_name
            )
            kept = comp.astype(jnp.float32)
        else:
            single = self._replace(buckets=((0, self.spec.total),))
            summed, kept = single._exchange_bucket(send, axis_name)
            shard_n = self.spec.total // self.world
            shard = lax.dynamic_slice(
                summed, (lax.axis_index(axis_name) * shard_n,), (shard_n,)
            )
        shard = shard / self.world
        new_residual = residual
        if self.needs_residual:
            new_residual = send - kept
        return shard, new_residual

def make_grad_comm(
    params,
    world: int,
    comm_hook: str = "none",
    bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB,
    flat_spec=None,
    density: float = DEFAULT_TOPK_DENSITY,
    force: bool = False,
) -> Optional[GradComm]:
    """Build the comm plan for ``params`` (None for hook "none" — the legacy
    pmean path needs no plan; accounting for it comes from a bf16 plan's
    sibling via :func:`comm_bytes_for_hook` — unless ``force=True``, which
    the hierarchical topology uses: its multi-hop exchange needs the flat
    spec even uncompressed). ``flat_spec`` reuses an existing
    :class:`FlatParamSpec` (the weight-update-sharding one) so the residual
    aligns with the scattered vector."""
    validate_hook(comm_hook)
    if comm_hook == "none" and not force:
        return None
    if comm_hook == "topk_ef":
        bucket_topk(1, density)  # validate the density range eagerly
    from tpuddp.training.step import make_flat_param_spec

    spec = flat_spec if flat_spec is not None else make_flat_param_spec(params, world)
    buckets = make_buckets(spec.sizes, spec.total, bucket_cap_mb)
    return GradComm(
        spec=spec, buckets=buckets, hook=comm_hook, world=world,
        density=float(density),
    )


def _bucket_payload_bytes(hook: str, size: int, density: float) -> int:
    """Wire bytes of ONE ``size``-element bucket's payload under ``hook`` —
    the per-hook byte formula the accounting tests pin:

    - ``none``:    size x 4            (f32 values)
    - ``bf16``/``bf16_ef``: size x 2   (bf16 values)
    - ``int8_ef``: size x 1 + 4        (int8 values + one f32 scale)
    - ``topk_ef``: k x (1 + 4) + 4     (k int8 values + k int32 indices +
                                        one f32 scale), k = max(1,
                                        floor(size x density))
    """
    if hook == "int8_ef":
        return size * _INT8_BYTES + _SCALE_BYTES
    if hook == "topk_ef":
        k = bucket_topk(size, density)
        return k * (_INT8_BYTES + _IDX_BYTES) + _SCALE_BYTES
    return size * wire_itemsize(hook)


def comm_bytes_for_hook(
    params,
    world: int,
    comm_hook: str,
    wus: bool = False,
    wire: bool = True,
    bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB,
    density: float = DEFAULT_TOPK_DENSITY,
) -> int:
    """Analytic per-replica wire payload of ONE gradient reduction (bytes) —
    the counter the dryrun/bench compare across hooks: the payload bytes
    entering the gradient collective, in its wire format (values in the wire
    dtype, PLUS int32 indices for the sparse hook and the per-bucket f32
    scale scalars for the quantized hooks — side-channel bytes are wire
    bytes too). Ring-transfer multipliers (2(N-1)/N for allreduce, (N-1)/N
    for reduce-scatter/all-gather) are topology constants that cancel in any
    same-shape comparison, so the counter reports the payload itself — the
    quantity the hook changes. ``wus`` counts the gradient exchange as ONE
    whole-vector bucket (the scatter degenerates the bucket partition; the
    f32 parameter all-gather is a separate, hook-independent exchange).
    ``wire=False`` (``mode="auto"`` / the managed Accelerator, where XLA
    inserts the psum and the hook only emulates the quantization) accounts
    the collective at f32 regardless of hook — the counter must never record
    a byte cut that did not reach the wire."""
    validate_hook(comm_hook)
    from tpuddp.training.step import make_flat_param_spec

    spec = make_flat_param_spec(params, world)
    if not wire:
        comm_hook = "none"
    if comm_hook == "none" and not wus:
        # the tree-level pmean reduces exactly the raw (unpadded) leaf
        # elements; flat-vector paths carry the world-multiple padding
        return sum(spec.sizes) * _F32_BYTES
    if comm_hook == "none":
        return spec.total * _F32_BYTES
    if wus:
        return _bucket_payload_bytes(comm_hook, spec.total, density)
    buckets = make_buckets(spec.sizes, spec.total, bucket_cap_mb)
    return sum(
        _bucket_payload_bytes(comm_hook, e - s, density) for s, e in buckets
    )


def comm_bytes_breakdown(
    params,
    world: int,
    comm_hook: str,
    topology: str = "flat",
    local_size: Optional[int] = None,
    wire: bool = True,
    bucket_cap_mb: float = DEFAULT_BUCKET_CAP_MB,
    density: float = DEFAULT_TOPK_DENSITY,
) -> dict:
    """Per-replica wire bytes of ONE gradient reduction, split intra- vs
    inter-host — the accounting the hierarchical topology exists to move:

    - ``flat``: the whole payload is one collective over the undifferentiated
      data axis; accounted as inter-host (the conservative reading — on a
      multi-host pod every byte of a flat collective crosses the slowest
      link at least logically; on one host the column reads as ICI traffic).
    - ``hierarchical``: intra-host = the f32 reduce-scatter operand
      (``total`` x 4) plus the f32 all-gather operand (the ``total/L``
      shard x 4); inter-host = the hook's compressed payload of the
      ``total/L`` shard (ONE bucket — the scatter already partitioned).

    ``wire=False`` (auto/managed) reports the f32 flat payload, exactly like
    :func:`comm_bytes_for_hook`."""
    validate_hook(comm_hook)
    validate_topology(topology)
    from tpuddp.training.step import make_flat_param_spec

    total_flat = comm_bytes_for_hook(
        params, world, comm_hook, wire=wire,
        bucket_cap_mb=bucket_cap_mb, density=density,
    )
    if topology == "flat" or not wire:
        return {
            "total": total_flat, "inter_host": total_flat, "intra_host": 0,
        }
    if not local_size or world % local_size:
        raise ValueError(
            f"hierarchical accounting needs the inner-axis size (got "
            f"local_size={local_size!r} for world {world})"
        )
    spec = make_flat_param_spec(params, world)
    shard_n = spec.total // local_size
    intra = spec.total * _F32_BYTES + shard_n * _F32_BYTES
    inter = (
        shard_n * _F32_BYTES
        if comm_hook == "none"
        else _bucket_payload_bytes(comm_hook, shard_n, density)
    )
    return {"total": intra + inter, "inter_host": inter, "intra_host": intra}


def _leaf_roundtrip(s, hook: str, density: float):
    """One leaf through the hook's wire format and back (the auto-mode
    emulation: the leaf IS the bucket). Shape-preserving."""
    if hook in ("bf16", "bf16_ef"):
        return s.astype(wire_dtype(hook)).astype(jnp.float32)
    flat = jnp.ravel(s)
    scale = int8_scale(flat)
    if hook == "int8_ef":
        return (quantize_int8(flat, scale).astype(jnp.float32) * scale).reshape(
            s.shape
        )
    # topk_ef: keep density of the leaf, int8-quantized like the wire payload
    from jax import lax

    k = bucket_topk(int(flat.shape[0]), density)
    _, idx = lax.top_k(jnp.abs(flat), k)
    q = quantize_int8(jnp.take(flat, idx), scale)
    dense = jnp.zeros_like(flat).at[idx].set(q.astype(jnp.float32) * scale)
    return dense.reshape(s.shape)


def local_quantize(grads, residual, hook: str, density: float = DEFAULT_TOPK_DENSITY):
    """Tree-level hook emulation for the managed/auto path: round-trip the
    (already globally-aggregated) gradient through the hook's wire format,
    with the same error-feedback residual semantics as the explicit path
    (each leaf is its own bucket: per-leaf int8 scale / per-leaf top-k).
    ``residual`` is a pytree like ``grads`` (or None for hook "bf16").
    Returns ``(quantized_grads, new_residual)``."""
    validate_hook(hook)
    if hook == "none":
        return grads, residual
    if hook == "bf16":
        return (
            jax.tree_util.tree_map(
                lambda g: _leaf_roundtrip(g, hook, density), grads
            ),
            residual,
        )
    send = jax.tree_util.tree_map(lambda g, r: g + r, grads, residual)
    quant = jax.tree_util.tree_map(
        lambda s: _leaf_roundtrip(s, hook, density), send
    )
    new_residual = jax.tree_util.tree_map(lambda s, q: s - q, send, quant)
    return quant, new_residual


def init_residual_tree(params):
    """Zeros-like residual pytree for :func:`local_quantize`'s bf16_ef."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(np.shape(p), jnp.float32), params
    )


_HLO_COLLECTIVE_RE = re.compile(
    r"\ball[-_]reduce\b|\ball[-_]gather\b|\breduce[-_]scatter\b"
    r"|\bcollective[-_]permute\b"
)
_HLO_COMPUTE_RE = re.compile(r"\bdot_general\b|\bdot\(|\bconvolution\b|\bconv\(")


def hlo_overlap_evidence(hlo_text: str) -> dict:
    """Positional evidence of backward/collective interleaving in a lowered
    step's HLO/StableHLO text (the ``comm_overlap`` proof obligation, and
    what the real-TPU latency-hiding scheduler exploits): line indices of
    collective ops and of matmul/conv compute, plus the compute lines that
    fall strictly BETWEEN the first and last collective. In barrier mode the
    collectives form one trailing block (``interleaved_compute == []``); the
    segmented step puts each earlier segment's backward compute after a later
    segment's collective. Pure text analysis — jax-free, so bench rows and
    the full gate can both record it."""
    lines = hlo_text.splitlines()
    collectives = [
        i for i, l in enumerate(lines) if _HLO_COLLECTIVE_RE.search(l)
    ]
    compute = [i for i, l in enumerate(lines) if _HLO_COMPUTE_RE.search(l)]
    inter = (
        [i for i in compute if collectives[0] < i < collectives[-1]]
        if collectives
        else []
    )
    return {
        "collective_lines": collectives,
        "compute_lines": compute,
        "interleaved_compute": inter,
        "interleaved": bool(inter),
    }


def redistribute_residual(mat: np.ndarray, new_world: int) -> Tuple[np.ndarray, str]:
    """Re-map per-replica error-feedback residuals onto a new world size —
    the elastic-resume rule for ``TrainState.comm_state`` (DynamiQ's
    dynamic-world-size compression-state motivation, arxiv.org/abs/2602.08923).

    ``mat`` is the residual viewed as ``(old_world, per)``: row ``r`` is
    replica ``r``'s accumulated compression error over the whole flat
    gradient vector. What steers the trajectory is the SUM over replicas —
    each replica adds its residual into its next send and the sends are
    ``psum``'d — so any re-mapping that preserves the per-element sum over
    the replica axis preserves the aggregate un-sent error budget:

    - shrink, ``new_world`` divides ``old_world``: each new replica takes the
      elementwise f32 sum of one group of ``old/new`` consecutive old rows
      (``reshape(new, k, per).sum(axis=1)`` — exactly reproducible, so tests
      can assert the redistribution bitwise);
    - grow, ``old_world`` divides ``new_world``: old row ``r`` moves verbatim
      to new row ``r * (new/old)``; the other rows start at zero (pure
      placement — bitwise sum-preserving);
    - no divisor relation (``M∤N`` both ways): there is no sum-preserving
      alignment of whole rows, so the residual RESETS to zero — the
      documented fallback. The un-sent error (bounded by one step's bf16
      rounding per element) is dropped once; callers record a typed
      ``comm_state_reset`` event row so the discontinuity is auditable.

    Returns ``(new_mat, action)`` with ``action`` one of ``"unchanged"`` /
    ``"redistributed"`` / ``"reset"``."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        raise ValueError(f"expected a (world, per) residual view, got {mat.shape}")
    old_world, per = mat.shape
    if new_world < 1:
        raise ValueError(f"new_world must be >= 1, got {new_world}")
    if new_world == old_world:
        return mat, "unchanged"
    if old_world % new_world == 0:
        k = old_world // new_world
        return mat.reshape(new_world, k, per).sum(axis=1), "redistributed"
    if new_world % old_world == 0:
        k = new_world // old_world
        out = np.zeros((new_world, per), mat.dtype)
        out[::k] = mat
        return out, "redistributed"
    return np.zeros((new_world, per), mat.dtype), "reset"
