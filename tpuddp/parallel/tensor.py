"""Tensor-parallel execution over the 2-D ``("data", "model")`` mesh.

This module turns the transformer family's *declared* partition metadata
(``tpuddp.models.transformer.param_logical_axes`` / ``partition_spec`` —
SNIPPETS.md [2]'s rule table, unconsumed since the family landed) into a
running training step:

- **column-split** ``wqkv`` / ``mlp w1`` (each model shard owns ``H/M`` heads
  / ``F/M`` hidden units; the input activation is replicated, no exchange on
  the way in);
- **row-split** ``attn wo`` / ``mlp w2`` (each shard contracts its own slice
  and the partial outputs ``psum`` over ``"model"`` — one activation psum per
  row-split projection, two per block, Megatron's f/g pattern);
- **vocab-split** embedding + tied LM head: the lookup is a masked local
  gather whose cross-shard ``psum`` is *exact* (every token's row lives on
  exactly one shard; the others contribute literal zeros), and the logit
  **gather** concatenates local vocab columns over ``"model"`` — exact by
  construction, no reduction touches a logit value.

The model-axis exchanges are expressed through ``jax.custom_vjp`` collectives
(:func:`copy_to_tp` / :func:`reduce_from_tp` / :func:`gather_from_tp`) so the
backward pass is *explicit* — the conjugate psum of a column-split input and
the cotangent slice of the gather are written here, not left to shard_map's
transpose machinery (which is exactly the part ``check_vma=False`` opts out
of validating).

Everything data-parallel composes unchanged and reduces over the **data**
axis only: the batch splits ``P("data")``, gradient comm hooks
(none/bf16_ef/int8_ef/topk_ef) bucket the *local shard* gradient and
exchange it across data replicas (each ``(data_index, model_index)`` device
keeps its own error-feedback residual — the comm_state lays out
``P(("data", "model"))``), and the guard firewall agrees its verdict with one
scalar pmin over ``"model"`` (shards hold different gradient slices, so their
local verdicts can legitimately differ).

Layout note (the one reshape): the canonical joined-QKV weight packs its
columns ``[3, H, Dh]`` with the q/k/v factor OUTERMOST, so a contiguous
column split is not head-aligned. The TP state stores it as ``(E, 3, H*Dh)``
(and ``bqkv`` as ``(3, H*Dh)``) — sharding the last axis is then exactly a
head split, and flattening the gathered ``(E, 3, H*Dh)`` back to
``(E, 3*H*Dh)`` reproduces the canonical layout bit for bit
(:func:`to_tp_tree` / :func:`from_tp_tree`).
"""

from __future__ import annotations

import functools
import hashlib
import json
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp.parallel import collectives as col
from tpuddp.parallel.mesh import DATA_AXIS
from tpuddp.parallel.mesh2d import MODEL_AXIS
from tpuddp.resilience import guard as guard_lib
from tpuddp.training.train_state import TrainState
from tpuddp.utils.compat import shard_map

# The tensor-parallel rule set: SNIPPETS.md [2]'s table (heads/mlp/joined_kv
# -> "model") EXTENDED with the vocab split — the embedding and the tied LM
# head shard their vocabulary rows so the largest single matrix also cuts
# 1/M per chip. The base table keeps vocab unsharded because generic rules
# cannot promise an exact lookup; this layer can (masked gather + zero psum),
# so the TP rule set claims it. run_meta records tp_rules_hash so a history
# states exactly which rule set trained it.
def tp_rules() -> dict:
    from tpuddp.models import transformer as tf_lib

    rules = dict(tf_lib.PARTITION_RULES)
    rules["vocab"] = MODEL_AXIS
    return rules


def tp_rules_hash(rules: Optional[dict] = None) -> str:
    """Stable short hash of the TP rule table (the run_meta ``mesh`` block's
    ``tp_rules_hash`` field): two histories sharded under different rule sets
    must not read as the same configuration."""
    rules = tp_rules() if rules is None else rules
    canon = json.dumps({k: rules[k] for k in sorted(rules)}, sort_keys=True)
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def supports_tp(model) -> bool:
    """Does this model declare the partition metadata the TP layer consumes?
    (The transformer family does; CNNs don't — their TP story is deferred.)"""
    from tpuddp.models.transformer import TransformerLM

    return isinstance(model, TransformerLM)


def validate_tp_geometry(model, model_width: int) -> None:
    """Refuse a TP width the model cannot tile: heads, MLP hidden units, and
    vocabulary rows all split evenly or the shard shapes would be ragged."""
    if not supports_tp(model):
        raise ValueError(
            f"model {type(model).__name__} declares no partition metadata "
            "(param_logical_axes); tensor parallelism supports the "
            "transformer family — run other models at parallel.model=1"
        )
    for name, dim in (
        ("n_heads", model.n_heads),
        ("d_mlp", model.d_mlp),
        ("vocab_size", model.vocab_size),
    ):
        if dim % model_width:
            raise ValueError(
                f"parallel.model={model_width} does not tile the model's "
                f"{name}={dim}; every sharded dimension must split evenly"
            )


# ------------------------------------------------------ layout conversion --


def to_tp_tree(params):
    """Canonical param tree -> the TP layout: ``wqkv (E, 3HD) -> (E, 3, HD)``
    and ``bqkv (3HD,) -> (3, HD)`` so a last-axis shard is head-aligned.
    Every other leaf passes through untouched."""

    def conv(block):
        attn = dict(block["attn"])
        w = attn["wqkv"]
        attn["wqkv"] = w.reshape(w.shape[0], 3, w.shape[1] // 3)
        attn["bqkv"] = attn["bqkv"].reshape(3, -1)
        out = dict(block)
        out["attn"] = attn
        return out

    out = dict(params)
    out["blocks"] = tuple(conv(b) for b in params["blocks"])
    return out


def from_tp_tree(tp_params):
    """Inverse of :func:`to_tp_tree`: the gathered ``(E, 3, H*Dh)`` flattens
    back to the canonical ``(E, 3*H*Dh)`` packing exactly."""

    def conv(block):
        attn = dict(block["attn"])
        w = attn["wqkv"]
        attn["wqkv"] = w.reshape(w.shape[0], w.shape[1] * w.shape[2])
        attn["bqkv"] = attn["bqkv"].reshape(-1)
        out = dict(block)
        out["attn"] = attn
        return out

    out = dict(tp_params)
    out["blocks"] = tuple(conv(b) for b in tp_params["blocks"])
    return out


def tp_param_specs(model, tp_params) -> dict:
    """PartitionSpec pytree (congruent with the TP-layout tree) applying the
    TP rule set: the model's declared ``partition_spec`` mapped leaf-by-leaf,
    with the two reshaped QKV leaves re-spelled for their 3-D/2-D layout."""
    from tpuddp.models import transformer as tf_lib

    mesh_axes = tf_lib.partition_spec(model, tp_params, rules=tp_rules())

    def to_P(t):
        return P(*t)

    spec = jax.tree_util.tree_map(
        to_P, mesh_axes,
        is_leaf=lambda leaf: isinstance(leaf, tuple) and not isinstance(leaf, P)
        and all(n is None or isinstance(n, str) for n in leaf),
    )
    blocks = []
    for b in spec["blocks"]:
        attn = dict(b["attn"])
        attn["wqkv"] = P(None, None, MODEL_AXIS)  # (E, 3, H*Dh): head split
        attn["bqkv"] = P(None, MODEL_AXIS)
        nb = dict(b)
        nb["attn"] = attn
        blocks.append(nb)
    out = dict(spec)
    out["blocks"] = tuple(blocks)
    return out


def _local_shape(shape, spec, model_width: int):
    out = list(shape)
    for d, axis in enumerate(tuple(spec)):
        if axis == MODEL_AXIS:
            out[d] = out[d] // model_width
    return tuple(out)


def local_param_template(tp_params, specs, model_width: int):
    """One model shard's view of the TP tree as host zeros — the template the
    gradient comm plan (bucket layout, byte accounting) is built from: comm
    hooks exchange the LOCAL shard gradient over the data axis only."""
    return jax.tree_util.tree_map(
        lambda leaf, spec: np.zeros(
            _local_shape(np.shape(leaf), spec, model_width), np.float32
        ),
        tp_params, specs,
    )


def per_chip_param_bytes(tp_params, specs, model_width: int) -> int:
    """Parameter bytes ONE chip holds under this sharding — the number the
    MULTICHIP bench row reports against the replicated (model=1) footprint."""
    total = 0
    for leaf, spec in zip(
        jax.tree_util.tree_leaves(tp_params),
        jax.tree_util.tree_leaves(specs),
    ):
        shape = _local_shape(np.shape(leaf), spec, model_width)
        itemsize = np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        total += int(np.prod(shape, dtype=np.int64)) * itemsize
    return total


def opt_state_specs(opt_state, tp_params, param_specs):
    """PartitionSpec pytree for an optimizer state over TP params: every
    state leaf congruent with a parameter (Adam m/v, SGD momentum — their
    tree paths end with the parameter's path) inherits that parameter's
    spec; scalars and anything unrecognized replicate. Shape matching would
    be ambiguous (``embed`` and ``pos`` can share a shape with different
    specs), so the PATH is the key."""
    param_spec_by_path = {
        jax.tree_util.keystr(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(param_specs)[0]
    }
    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    leaves = []
    for path, _leaf in flat:
        key = jax.tree_util.keystr(path)
        spec = P()
        for ppath, pspec in param_spec_by_path.items():
            if key.endswith(ppath):
                spec = pspec
                break
        leaves.append(spec)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def place_tree(mesh, host_tree, specs):
    """Place a host pytree onto the mesh leaf by leaf under ``specs``
    (single-process: every device is addressable, a plain device_put
    shards/replicates as the spec says)."""
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        host_tree, specs,
    )


def tp_state_spec(param_specs, opt_specs, comm=None) -> TrainState:
    """The shard_map PartitionSpec TrainState for the TP step: params and
    optimizer moments carry their model-axis shards, the per-device
    error-feedback residual (when an EF comm hook is armed) lays out
    ``P(("data", "model"))`` — one slice per ``(data_index, model_index)``
    device — and everything else replicates."""
    return TrainState(
        params=param_specs,
        model_state=P(),
        opt_state=opt_specs,
        step=P(),
        rng=P(),
        comm_state=(
            P((DATA_AXIS, MODEL_AXIS))
            if comm is not None and comm.needs_residual
            else P()
        ),
        skipped_steps=P(),
    )


# ------------------------------------- model-axis collectives (explicit AD) --


@jax.custom_vjp
def copy_to_tp(x):
    """Megatron's ``f``: identity forward at a column-split layer's input,
    psum over ``"model"`` backward — each shard backpropagates only its own
    branch, so the input's true cotangent is the cross-shard sum."""
    return x


copy_to_tp.defvjp(
    lambda x: (x, None),
    lambda _, ct: (lax.psum(ct, MODEL_AXIS),),
)


@jax.custom_vjp
def reduce_from_tp(x):
    """Megatron's ``g``: psum over ``"model"`` forward at a row-split layer's
    output (the partial contractions sum to the full one), identity backward
    (the summed output's cotangent already is every shard's cotangent)."""
    return lax.psum(x, MODEL_AXIS)


reduce_from_tp.defvjp(
    lambda x: (lax.psum(x, MODEL_AXIS), None),
    lambda _, ct: (ct,),
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _gather_last(width: int, x):
    return lax.all_gather(x, MODEL_AXIS, axis=x.ndim - 1, tiled=True)


def _gather_last_fwd(width, x):
    return _gather_last(width, x), None


def _gather_last_bwd(width, _, ct):
    idx = lax.axis_index(MODEL_AXIS)
    return (lax.dynamic_slice_in_dim(ct, idx * width, width, axis=ct.ndim - 1),)


_gather_last.defvjp(_gather_last_fwd, _gather_last_bwd)


def gather_from_tp(x):
    """Exact last-axis concatenation over ``"model"`` (the vocab-split logit
    gather): forward is a pure all-gather — no value is reduced, so every
    logit column equals its unsharded self — and backward slices this
    shard's own columns out of the cotangent."""
    return _gather_last(int(x.shape[-1]), x)


# ----------------------------------------------------------- TP forward --


def tp_forward(model, p, tokens):
    """The tensor-parallel causal forward, per-device view inside shard_map:
    ``p`` is this shard's slice of the TP-layout tree, ``tokens`` this data
    replica's ``(B, T)`` int batch (replicated across the model axis).
    Returns full ``(B, T, V)`` logits (vocab columns gathered exactly).
    Matches ``TransformerLM.apply`` up to the row-split contractions'
    summation order (each is one psum of M partials)."""
    import math

    from tpuddp.models.transformer import _NEG_INF

    tokens = jnp.asarray(tokens).astype(jnp.int32)
    B, T = tokens.shape
    embed = p["embed"]["weight"]  # (V/M, E) — this shard's vocab rows
    v_local = embed.shape[0]
    offset = lax.axis_index(MODEL_AXIS) * v_local
    local_ids = tokens - offset
    mine = (local_ids >= 0) & (local_ids < v_local)
    safe = jnp.clip(local_ids, 0, v_local - 1)
    # masked local lookup + zero psum: exactly one shard contributes each
    # token's row, the rest add literal 0.0 — the lookup stays bitwise-exact
    partial_emb = jnp.where(mine[..., None], jnp.take(embed, safe, axis=0), 0.0)
    h = reduce_from_tp(partial_emb) + p["pos"]["weight"][:T]
    mask = jnp.tril(jnp.ones((T, T), bool))
    scale = 1.0 / math.sqrt(model.head_dim)
    for bp in p["blocks"]:
        # -- attention: column-split QKV (local heads), row-split output
        a = copy_to_tp(model._norm(bp["ln1"], h))
        qkv = jnp.einsum("bte,eck->btck", a, bp["attn"]["wqkv"]) + bp["attn"]["bqkv"]
        qkv = qkv.reshape(B, T, 3, -1, model.head_dim)  # (B, T, 3, H/M, Dh)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(h.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        part = o.reshape(B, T, -1) @ bp["attn"]["wo"]  # local head rows
        h = h + reduce_from_tp(part) + bp["attn"]["bo"]
        # -- MLP: column-split in, row-split out
        b = copy_to_tp(model._norm(bp["ln2"], h))
        m = jax.nn.gelu(
            b @ bp["mlp"]["w1"] + bp["mlp"]["b1"], approximate=False
        ) @ bp["mlp"]["w2"]
        h = h + reduce_from_tp(m) + bp["mlp"]["b2"]
    h = copy_to_tp(model._norm(p["ln_f"], h))
    return gather_from_tp(h @ embed.T)  # tied head: local vocab columns


# ------------------------------------------------------------ step builders --


def _make_tp_train_core(model, criterion, optimizer, comm, guard: bool):
    def core(state: TrainState, x, y, w):
        def loss_fn(params):
            logits = tp_forward(model, params, x)
            return criterion(logits, y, w)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        n = jnp.sum(w)
        # THE data-parallel exchange: gradients (local-shard trees) reduce
        # over the DATA axis only — a model shard's gradient belongs to that
        # shard alone. Comm hooks bucket the local flat vector; each
        # (data, model) device carries its own EF residual slice.
        if comm is not None and comm.compressed:
            agg, new_comm = comm.reduce(grads, state.comm_state, DATA_AXIS)
        else:
            agg, new_comm = col.pmean(grads, DATA_AXIS), state.comm_state
        skipped = state.skipped_steps
        if guard:
            # model shards hold DIFFERENT gradient slices, so the local
            # finiteness verdicts can differ — one scalar pmin over "model"
            # makes every device take the same lax.cond branch (the data
            # axis already agrees: the psum propagated any replica's NaN)
            ok = (
                col.pmin(
                    guard_lib.tree_all_finite(agg).astype(jnp.int32),
                    MODEL_AXIS,
                )
                == 1
            )

            def _apply():
                new_p, new_o = optimizer.update(agg, state.opt_state, state.params)
                return new_p, new_o, new_comm, guard_lib.reset_consecutive(skipped)

            def _skip():
                return (
                    state.params, state.opt_state, state.comm_state,
                    guard_lib.bump_skip_counters(skipped),
                )

            new_params, new_opt_state, out_comm, new_skipped = jax.lax.cond(
                ok, _apply, _skip
            )
        else:
            new_params, new_opt_state = optimizer.update(
                agg, state.opt_state, state.params
            )
            out_comm, new_skipped = new_comm, skipped
        metrics = {"loss_sum": (loss * n)[None], "n": n[None]}
        new_state = TrainState(
            params=new_params,
            model_state=state.model_state,
            opt_state=new_opt_state,
            step=state.step + 1,
            rng=state.rng,
            comm_state=out_comm,
            skipped_steps=new_skipped,
        )
        return new_state, metrics

    return core


def _make_tp_eval_core(model, criterion):
    def core(state: TrainState, x, y, w):
        logits = tp_forward(model, state.params, x)
        loss = criterion(logits, y, w)
        n = jnp.sum(w)
        predicted = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((predicted == y) * w)
        return {
            "loss_sum": (loss * n)[None],
            "correct": correct[None],
            "n": n[None],
        }

    return core


def build_tp_train_step(model, criterion, optimizer, mesh, state_spec,
                        comm=None, guard: bool = False):
    """Compile the TP train step over the 2-D mesh. Same calling contract as
    :func:`tpuddp.training.step.build_train_step`: ``step(state, (x, y, w))
    -> (new_state, metrics)`` with donated state; metrics are per-data-
    replica partial sums (identical across the model axis by construction)."""
    core = _make_tp_train_core(model, criterion, optimizer, comm, guard)
    metric_spec = {"loss_sum": P(DATA_AXIS), "n": P(DATA_AXIS)}
    fn = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_spec, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(state_spec, metric_spec),
        check_vma=False,
    )
    jitted = jax.jit(fn, donate_argnums=0)

    def step(state, batch):
        x, y, w = batch
        return jitted(state, x, y, w)

    return step


def build_tp_train_scan_step(model, criterion, optimizer, mesh, state_spec,
                             comm=None, guard: bool = False):
    """K fused TP train steps per dispatch (lax.scan over the single-step
    core, the ``train_step_many`` contract)."""
    core = _make_tp_train_core(model, criterion, optimizer, comm, guard)

    def multi(state: TrainState, xs, ys, ws):
        def body(st, batch):
            return core(st, *batch)

        state, stacked = jax.lax.scan(body, state, (xs, ys, ws))
        return state, jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), stacked)

    in_batch = P(None, DATA_AXIS)
    metric_spec = {"loss_sum": P(DATA_AXIS), "n": P(DATA_AXIS)}
    fn = shard_map(
        multi,
        mesh=mesh,
        in_specs=(state_spec, in_batch, in_batch, in_batch),
        out_specs=(state_spec, metric_spec),
        check_vma=False,
    )
    jitted = jax.jit(fn, donate_argnums=0)

    def step(state, stacked_batch):
        xs, ys, ws = stacked_batch
        return jitted(state, xs, ys, ws)

    return step


def build_tp_eval_step(model, criterion, mesh, state_spec):
    core = _make_tp_eval_core(model, criterion)
    metric_spec = {
        "loss_sum": P(DATA_AXIS), "correct": P(DATA_AXIS), "n": P(DATA_AXIS),
    }
    fn = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_spec, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=metric_spec,
        check_vma=False,
    )
    jitted = jax.jit(fn)

    def step(state, batch):
        x, y, w = batch
        return jitted(state, x, y, w)

    return step


def build_tp_eval_scan_step(model, criterion, mesh, state_spec):
    core = _make_tp_eval_core(model, criterion)

    def multi(state: TrainState, xs, ys, ws):
        def body(carry, batch):
            return carry, core(state, *batch)

        _, stacked = jax.lax.scan(body, 0, (xs, ys, ws))
        return jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), stacked)

    in_batch = P(None, DATA_AXIS)
    metric_spec = {
        "loss_sum": P(DATA_AXIS), "correct": P(DATA_AXIS), "n": P(DATA_AXIS),
    }
    fn = shard_map(
        multi,
        mesh=mesh,
        in_specs=(state_spec, in_batch, in_batch, in_batch),
        out_specs=metric_spec,
        check_vma=False,
    )
    jitted = jax.jit(fn)

    def step(state, stacked_batch):
        xs, ys, ws = stacked_batch
        return jitted(state, xs, ys, ws)

    return step


def gather_params(state_or_params):
    """Host canonical-layout parameter tree from a TP state (or TP param
    tree): fetch the (fully addressable) global arrays and undo the QKV
    layout reshape — the reference view parity tests compare against."""
    params = getattr(state_or_params, "params", state_or_params)
    host = jax.tree_util.tree_map(np.asarray, params)
    return from_tp_tree(host)
