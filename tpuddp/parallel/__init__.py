"""Distributed runtime for tpuddp: backends, meshes, collectives, sampling, DDP."""

from tpuddp.parallel.backend import (  # noqa: F401
    BackendUnavailableError,
    available_backends,
    cleanup,
    detect_backend,
    get_backend,
    get_rank,
    get_world_size,
    is_initialized,
    setup,
)
from tpuddp.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    data_sharded,
    data_mesh,
    local_mesh_devices,
    make_mesh,
    replicated,
)
from tpuddp.parallel.mesh2d import (  # noqa: F401
    AXIS_ROLES,
    MODEL_AXIS,
    data_size,
    mesh2d,
    model_size,
    squeeze_model,
)
from tpuddp.parallel import collectives  # noqa: F401
from tpuddp.parallel.sampler import DistributedSampler  # noqa: F401

__all__ = [
    "BackendUnavailableError",
    "available_backends",
    "cleanup",
    "detect_backend",
    "get_backend",
    "get_rank",
    "get_world_size",
    "is_initialized",
    "setup",
    "DATA_AXIS",
    "MODEL_AXIS",
    "AXIS_ROLES",
    "mesh2d",
    "model_size",
    "data_size",
    "squeeze_model",
    "data_mesh",
    "data_sharded",
    "local_mesh_devices",
    "make_mesh",
    "replicated",
    "collectives",
    "DistributedSampler",
]
