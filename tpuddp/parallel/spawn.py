"""Process launch — the tpuddp analog of ``torch.multiprocessing.spawn``
(SURVEY.md §2b #14; reference run_DDP_training, multi-GPU-training-torch.py:269-279).

The reference forks one process per GPU on one node. The TPU execution model
inverts this: each host of a pod slice runs ONE process that owns all of its
local chips (``jax.process_index()`` is the rank), and single-host multi-chip
needs no spawn at all. So:

- :func:`run_ddp_training` calls the worker once per process with
  ``(rank=process_index, world_size, save_dir, optional_args)`` — signature
  parity with the reference's ``demo_fn`` — after bootstrapping the runtime.
- :func:`maybe_reexec_for_world` reproduces the *development* experience of
  spawning an N-way world on a chipless box: if the CPU rung can't see N
  virtual devices yet, it re-execs the current script with
  ``--xla_force_host_platform_device_count=N`` set, which must happen before
  XLA initializes (the reason mp.spawn-style in-process forking can't work
  with a live XLA runtime).
- Worker exceptions propagate (mp.spawn ``join=True`` contract) since there is
  no intermediate process on the single-host path.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Callable, Optional

import jax

from tpuddp.parallel import backend as _backend
from tpuddp.resilience import guard as _guard
from tpuddp.resilience import preemption as _preemption
from tpuddp.resilience import watchdog as _watchdog

logger = logging.getLogger("tpuddp")

_REEXEC_GUARD = "TPUDDP_SPAWNED"


_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _flags_with_device_count(flags: str, n: int):
    """Return ``(new_flags, already_exact)`` with the virtual-device-count
    flag set to exactly ``n``. Matching must be by exact value and a wrong
    pre-set count must be REPLACED, not appended alongside (two contradictory
    values would leave the winner to ABSL parse order) — and substring
    containment is not a match (``=16`` must not satisfy ``=1``)."""
    import re

    flag = f"{_COUNT_FLAG}={n}"
    existing = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if existing:
        if int(existing.group(1)) == n:
            return flags, True
        return re.sub(rf"{_COUNT_FLAG}=\d+", flag, flags), False
    return f"{flags} {flag}".strip(), False


def maybe_reexec_for_world(world_size: int, backend: Optional[str] = None) -> None:
    """Dev-mode launcher: ensure an N-device CPU world exists, re-execing the
    current process with XLA_FLAGS if needed. No-op when enough devices (of
    the resolved backend) are already visible or when already re-execed."""
    chosen = _backend.detect_backend(backend)
    if chosen != "cpu":
        return
    if len(jax.devices("cpu")) >= world_size:
        return
    if os.environ.get(_REEXEC_GUARD):
        raise RuntimeError(
            f"re-exec with --xla_force_host_platform_device_count={world_size} "
            f"still yields {len(jax.devices('cpu'))} CPU devices; XLA was "
            "initialized before the flag took effect"
        )
    env = dict(os.environ)
    env["XLA_FLAGS"], _ = _flags_with_device_count(
        env.get("XLA_FLAGS", ""), world_size
    )
    env[_REEXEC_GUARD] = "1"
    env.setdefault("TPUDDP_BACKEND", "cpu")
    logger.info("re-exec for %d-device CPU world", world_size)
    os.execvpe(sys.executable, [sys.executable] + sys.argv, env)


def maybe_reexec_for_multihost_world(
    world_size: Optional[int],
    num_processes: int,
    backend: Optional[str] = None,
) -> None:
    """Multi-host flavor of the dev launcher. Decides from the *environment
    only* — probing ``jax.devices()`` here would initialize XLA before
    ``jax.distributed.initialize`` runs in :func:`backend.setup`, which JAX
    forbids. Each process re-execs itself with enough virtual CPU devices for
    its share (world_size // num_processes) of the global world."""
    prefer = backend or os.environ.get(_backend._BACKEND_ENV)
    if prefer != "cpu" or not world_size or num_processes <= 1:
        return
    local = max(1, world_size // num_processes)
    flags = os.environ.get("XLA_FLAGS", "")
    new_flags, already_exact = _flags_with_device_count(flags, local)
    if already_exact:
        return
    if os.environ.get(_REEXEC_GUARD):
        raise RuntimeError(
            f"re-exec with {_COUNT_FLAG}={local} did not stick; "
            f"XLA_FLAGS={flags!r}"
        )
    env = dict(os.environ)
    env["XLA_FLAGS"] = new_flags
    env[_REEXEC_GUARD] = "1"
    logger.info(
        "re-exec for %d-local-device CPU world (%d processes)", local, num_processes
    )
    os.execvpe(sys.executable, [sys.executable] + sys.argv, env)


def run_ddp_training(
    demo_fn: Callable,
    world_size: Optional[int],
    save_dir: str,
    optional_args: dict,
    backend: Optional[str] = None,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Launch DP training — signature parity with the reference's
    ``run_DDP_training(demo_fn, world_size, save_dir, optional_args)`` (:269-279).

    ``demo_fn(rank, world_size, save_dir, optional_args)`` runs once in this
    process; rank is the process index (0 on single host). Exceptions
    propagate like mp.spawn(join=True).

    Resilience wiring (tpuddp.resilience): SIGTERM/SIGINT drain handlers are
    installed before the worker runs, and a :class:`TrainingPreempted` raised
    by the epoch driver (emergency checkpoint already written) becomes
    ``sys.exit(75)`` — EX_TEMPFAIL, the "requeue me" code schedulers
    understand. On the multi-host path, a heartbeat + stale-peer watchdog pair
    is armed when ``$TPUDDP_WATCHDOG_TIMEOUT`` is set, so a dead peer surfaces
    as exit 76 instead of a silent hang in the next collective.
    """
    multihost = coordinator_address is not None and (num_processes or 1) > 1
    if multihost:
        # env-only decision: XLA must stay uninitialized until the rendezvous
        maybe_reexec_for_multihost_world(world_size, num_processes, backend)
    elif world_size is not None:
        maybe_reexec_for_world(world_size, backend)
    _preemption.install_preemption_handler()
    _backend.setup(
        world_size=world_size,
        backend=backend,
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    guard = _watchdog.start(save_dir, jax.process_index(), jax.process_count())
    try:
        demo_fn(jax.process_index(), _backend.get_world_size(), save_dir, optional_args)
    except _preemption.TrainingPreempted as e:
        logger.warning("%s; exiting %d (requeue+resume)", e, _preemption.EXIT_PREEMPTED)
        sys.exit(_preemption.EXIT_PREEMPTED)
    except _guard.ReplicaDesync as e:
        # the numerical guard's auditor found a divergent replica: the state
        # is not trustworthy, so surface the distinct code a scheduler can
        # requeue into auto-resume (restoring the last intact checkpoint)
        logger.critical("%s; exiting %d", e, _preemption.EXIT_DESYNC)
        sys.exit(_preemption.EXIT_DESYNC)
    finally:
        _watchdog.stop(guard)
        _backend.cleanup()
