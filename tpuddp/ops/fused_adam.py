"""FusedAdam — a Pallas TPU kernel for the Adam update.

The reference stack's optimizer path bottoms out in torch's fused C++/CUDA
kernels (`torch.optim.Adam(fused=...)` / apex FusedAdam); this is the
TPU-native analog: one Pallas kernel per parameter leaf performs the whole
m/v/p update in a single VMEM pass.

Measured honestly (AlexNet-class, TPU v5 lite): XLA's own elementwise fusion
of the jnp Adam beats this kernel (10.5 vs 15.6 ms/step) — the pad-to-lane
reshape around each leaf costs extra HBM copies that XLA's native fusion never
materializes. The lesson is recorded here deliberately: on TPU, custom kernels
pay off for ops XLA *can't* fuse (attention-style memory patterns, remote
DMA), not for elementwise chains. ``impl="auto"`` therefore resolves to the
XLA path; ``impl="pallas"`` opts into the kernel (native on TPU, interpret
elsewhere), which remains the framework's validated example of integrating a
custom Pallas op into the training stack (grid/BlockSpec tiling, SMEM scalars,
interpret-mode CPU testing).

Update rule matches tpuddp.optim.Adam (== torch.optim.Adam) exactly:
    m <- b1*m + (1-b1)*g ;  v <- b2*v + (1-b2)*g^2
    p <- p - lr * (m / (1-b1^t)) / (sqrt(v / (1-b2^t)) + eps)
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuddp.optim import Adam, AdamState

LANES = 128
BLOCK_ROWS = 512  # (512, 128) f32 tiles x 7 live arrays ≈ 1.8 MB of VMEM


def _adam_kernel(bc_ref, p_ref, g_ref, m_ref, v_ref, op_ref, om_ref, ov_ref,
                 *, lr, b1, b2, eps):
    bc1 = bc_ref[0, 0]
    bc2 = bc_ref[0, 1]
    g = g_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    om_ref[:] = m
    ov_ref[:] = v
    op_ref[:] = p_ref[:] - lr * (m / bc1) * (1.0 / (jnp.sqrt(v / bc2) + eps))


def _update_leaf(p, g, m, v, bc, *, lr, b1, b2, eps, interpret):
    """Run the kernel over one parameter leaf (any shape/f32)."""
    shape = p.shape
    n = p.size
    rows = max(1, -(-n // LANES))
    rows_padded = -(-rows // BLOCK_ROWS) * BLOCK_ROWS
    total = rows_padded * LANES

    def prep(x):
        flat = x.reshape(-1).astype(jnp.float32)
        return jnp.pad(flat, (0, total - n)).reshape(rows_padded, LANES)

    p2, g2, m2, v2 = prep(p), prep(g), prep(m), prep(v)
    grid = (rows_padded // BLOCK_ROWS,)
    tile = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM)
    out_sds = jax.ShapeDtypeStruct((rows_padded, LANES), jnp.float32)

    op, om, ov = pl.pallas_call(
        functools.partial(_adam_kernel, lr=lr, b1=b1, b2=b2, eps=eps),
        grid=grid,
        in_specs=[smem, tile, tile, tile, tile],
        out_specs=[tile, tile, tile],
        out_shape=[out_sds, out_sds, out_sds],
        interpret=interpret,
    )(bc, p2, g2, m2, v2)

    unpack = lambda x: x.reshape(-1)[:n].reshape(shape)
    return unpack(op).astype(p.dtype), unpack(om), unpack(ov)


def fused_adam_update(params, grads, opt_state: AdamState, *, lr, b1, b2, eps,
                      interpret=False) -> Tuple:
    """Pure-function fused update over a pytree; returns (params, AdamState)."""
    step = opt_state.step + 1
    t = step.astype(jnp.float32)
    bc = jnp.stack([1.0 - jnp.power(b1, t), 1.0 - jnp.power(b2, t)]).reshape(1, 2)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(opt_state.m)
    flat_v = treedef.flatten_up_to(opt_state.v)
    out_p, out_m, out_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        np_, nm, nv = _update_leaf(
            p, g, m, v, bc, lr=lr, b1=b1, b2=b2, eps=eps, interpret=interpret
        )
        out_p.append(np_)
        out_m.append(nm)
        out_v.append(nv)
    unflatten = treedef.unflatten
    return unflatten(out_p), AdamState(step=step, m=unflatten(out_m), v=unflatten(out_v))


class FusedAdam(Adam):
    """Drop-in Adam whose update can run as a Pallas kernel.

    ``impl``: "auto" (XLA math — measured faster, see module docstring),
    "pallas" (force the kernel; ``interpret=True`` off-TPU so CPU tests run),
    or "xla" (inherit tpuddp.optim.Adam explicitly).
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 impl: str = "auto"):
        super().__init__(lr=lr, betas=betas, eps=eps, weight_decay=0.0)
        if impl not in ("auto", "pallas", "xla"):
            raise ValueError(f"unknown impl {impl!r}")
        self.impl = impl

    @staticmethod
    def _platform() -> str:
        # honor an explicit jax_default_device override (e.g. CPU-pinned test
        # environments where a TPU plugin is registered but unused)
        dev = jax.config.jax_default_device
        if dev is not None:
            return dev.platform
        return jax.default_backend()

    def _use_pallas(self):
        if self.impl != "pallas":
            return False, False  # auto == xla: measured faster on TPU
        return True, self._platform() != "tpu"  # interpret off-TPU

    def update(self, grads, opt_state, params):
        use, interpret = self._use_pallas()
        if not use:
            return super().update(grads, opt_state, params)
        return fused_adam_update(
            params, grads, opt_state,
            lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps,
            interpret=interpret,
        )
