"""Custom TPU ops (Pallas kernels) with XLA fallbacks."""

from tpuddp.ops.fused_adam import FusedAdam, fused_adam_update  # noqa: F401

__all__ = ["FusedAdam", "fused_adam_update"]
