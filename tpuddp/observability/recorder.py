"""Step-level telemetry recorder — per-step wall times, percentiles, MFU.

The epoch drivers dispatch in *batch groups* (one jitted call covering
``scan_k`` fused steps), and dispatch is asynchronous: a ``perf_counter``
lap around one dispatch measures issue time, not execution. Timing therefore
works at the honest granularity:

- every dispatch contributes ``n_steps`` ring-buffer entries of
  ``lap / n_steps`` (per-step wall time at dispatch resolution — uniform
  within a fused group, exact at ``scan_k = 1``);
- at *window boundaries* (``training.step_stats_every`` steps) the recorder
  blocks on the last dispatch's metrics — ONE device sync per window, never
  inside a compiled program, so fused/scan paths stay fused and the step
  program is untouched (HLO-identical with telemetry on or off) — and emits
  a ``step_stats`` record;
- the epoch summary (percentiles over the whole epoch's entries) lands in
  the epoch's ``history.jsonl`` row, where the epoch barrier has already
  fenced the device, making the aggregate honest even with windows disabled.

Achieved MFU is best-effort: FLOPs come from XLA cost analysis of the exact
step program when a probe is available (``estimate_step_flops``), the peak
from the chip's spec-sheet bf16 ceiling (:data:`PEAK_FLOPS` — also the
bench's table). Unknown chip or unresolvable FLOPs -> MFU fields are null,
never guessed.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from tpuddp.observability import schema

# Peak bf16 MXU FLOP/s per chip by device kind (public spec sheets). MFU is
# always reported against the bf16 peak: on TPU, f32 matmuls execute on the
# MXU with bf16 multiplies by default, so bf16 peak is the one ceiling.
# (bench.py imports this table — one source of truth for both artifacts.)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
}


def device_peak_flops(kind: Optional[str] = None) -> Optional[float]:
    """Spec-sheet bf16 peak FLOP/s for the (first) local device; None when
    the chip is unknown (e.g. the CPU test world) — MFU is then null."""
    if kind is None:
        import jax

        devices = jax.devices()
        if not devices:
            return None
        kind = devices[0].device_kind
    return PEAK_FLOPS.get(kind)


def percentiles(step_times_s, keys=(50, 95, 99)) -> dict:
    """``{"p50": ..., "p95": ..., "p99": ..., "max": ...}`` in SECONDS over a
    sequence of per-step times; all-None when the sequence is empty."""
    arr = np.asarray(list(step_times_s), dtype=np.float64)
    if arr.size == 0:
        return {f"p{k}": None for k in keys} | {"max": None}
    out = {f"p{k}": float(np.percentile(arr, k)) for k in keys}
    out["max"] = float(arr.max())
    return out


def step_time_fields(step_times_s, flops_per_step=None, peak_flops=None) -> dict:
    """The shared record fields: step-time percentiles in ms plus the
    achieved-MFU transform of the same percentiles (MFU at the median step
    time, and at the p95 tail — the straggler-visible figure)."""
    pct = percentiles(step_times_s)
    fields = {
        f"step_time_ms_{k}": (None if v is None else round(v * 1e3, 4))
        for k, v in pct.items()
    }

    def mfu(t):
        if t is None or not t or not flops_per_step or not peak_flops:
            return None
        # 6 decimals: tiny-but-real utilizations (a toy model on a big chip)
        # must not round to a dishonest exact 0
        return round(flops_per_step / t / peak_flops, 6)

    fields["mfu_p50"] = mfu(pct["p50"])
    fields["mfu_p95"] = mfu(pct["p95"])
    return fields


def estimate_step_flops(
    lower_fn: Callable[[], "object"], world_size: int = 1
) -> Optional[float]:
    """Per-chip FLOPs of one step from XLA cost analysis of the LOWERED
    single-step program — never compiled: a second full XLA compile of a
    large model's step (minutes on TPU) is not an acceptable price for a
    telemetry field, so this stays with the HLO estimate (the bench, whose
    job is rigorous MFU, pays for the compiled figure instead).

    ``lower_fn`` returns a ``jax.stages.Lowered`` for the SINGLE-step program
    (no scan-body counting ambiguity). The whole-program figure is divided by
    ``world_size`` — the cost convention the in-repo bench disambiguated for
    multi-chip programs. Any failure (tracing, unsupported backend, zero
    figure) returns None: MFU is reported as unknown, never guessed."""
    try:
        cost = lower_fn().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        if flops <= 0:
            return None
        return flops / max(1, int(world_size))
    except Exception:
        return None


class StepStatsRecorder:
    """Host-side ring buffer of per-step wall times for ONE training run.

    ``record(n_steps, n_samples, fence=...)`` is called once per dispatch by
    the epoch driver; everything else is bookkeeping around the ring. The
    ring (``capacity`` entries, oldest overwritten) bounds memory on long
    runs; the *epoch* slice used for summaries is reset by
    :meth:`epoch_summary`, so an epoch longer than the capacity degrades to
    the newest ``capacity`` steps with a recorded ``step_stats_truncated``
    count instead of silently skewing percentiles."""

    def __init__(
        self,
        writer=None,
        window: int = 0,
        capacity: int = 65536,
        flops_per_step: Optional[float] = None,
        peak_flops="auto",
        on_window=None,
    ):
        """``peak_flops``: the chip ceiling for MFU — "auto" looks up the
        default device's kind; pass an explicit value (or None, a legitimate
        "unknown" for chips without a table entry) when the caller knows the
        mesh's device better than the default platform does.
        ``on_window``: zero-arg callable invoked right after each window row
        is emitted — the live telemetry plane's pump (shard publish +
        pod aggregation, tpuddp/observability/aggregate.py); host-side only,
        runs at the per-window fence that already exists."""
        self.writer = writer
        self.on_window = on_window
        self.window = max(0, int(window or 0))
        self.capacity = int(capacity)
        self.flops_per_step = flops_per_step
        self.peak_flops = (
            device_peak_flops() if peak_flops == "auto" else peak_flops
        )
        self._ring = np.zeros((self.capacity,), np.float64)
        self._n = 0  # total entries ever written (ring index = _n % capacity)
        self.global_step = 0  # train steps since loop entry (resume-relative)
        # live-plane state: the last emitted step_stats record (what a
        # /metrics scrape and the pod shard publish — both read-only, both
        # matching the flushed history exactly) and run-cumulative counters
        self.last_window: Optional[dict] = None
        self.windows_emitted = 0
        self.total_samples = 0
        self.total_stall_s = 0.0
        self._epoch = 0
        self._epoch_start_n = 0
        self._epoch_samples = 0
        self._epoch_t0: Optional[float] = None
        self._last_t: Optional[float] = None
        # pipeline-occupancy accounting (tpuddp/training/pipeline.py): host
        # stall accumulates per epoch/window; queue depths keep the window max
        self._epoch_stall = 0.0
        self._win_stall = 0.0
        self._win_staging_max = 0
        self._win_inflight_max = 0
        # window accounting
        self._win_start_n = 0
        self._win_start_step = 0
        self._win_samples = 0
        self._win_t0: Optional[float] = None

    # -- epoch lifecycle ---------------------------------------------------

    def start_epoch(self, epoch: int) -> None:
        now = time.perf_counter()
        self._epoch = int(epoch)
        self._epoch_start_n = self._n
        self._epoch_samples = 0
        self._epoch_t0 = now
        self._last_t = now
        self._epoch_stall = 0.0
        self._win_stall = 0.0
        self._win_staging_max = 0
        self._win_inflight_max = 0
        self._win_start_n = self._n
        self._win_start_step = self.global_step
        self._win_samples = 0
        self._win_t0 = now

    def record(
        self, n_steps: int, n_samples: int, fence=None, *,
        host_stall_s: float = 0.0, staging_depth: int = 0,
        inflight_depth: int = 0,
    ) -> None:
        """One dispatch of ``n_steps`` fused steps covering ``n_samples``
        global samples. ``fence`` is the dispatch's output (any pytree of
        device arrays); it is blocked on ONLY at a window boundary.
        ``host_stall_s``/``staging_depth``/``inflight_depth`` are the async
        pipeline's occupancy sample for this dispatch (host-blocked seconds
        since the previous one; staged-chunk / in-flight queue lengths)."""
        now = time.perf_counter()
        if self._last_t is None:  # record() without start_epoch: self-arm
            self.start_epoch(self._epoch)
            now = self._last_t
        lap = now - self._last_t
        n_steps = max(1, int(n_steps))
        per_step = lap / n_steps
        for i in range(n_steps):
            self._ring[(self._n + i) % self.capacity] = per_step
        self._n += n_steps
        self.global_step += n_steps
        self._epoch_samples += int(n_samples)
        self._win_samples += int(n_samples)
        self.total_samples += int(n_samples)
        self._epoch_stall += float(host_stall_s)
        self._win_stall += float(host_stall_s)
        self.total_stall_s += float(host_stall_s)
        self._win_staging_max = max(self._win_staging_max, int(staging_depth))
        self._win_inflight_max = max(self._win_inflight_max, int(inflight_depth))
        self._last_t = now
        if self.window and (self._n - self._win_start_n) >= self.window:
            self._emit_window(fence)

    def _slice(self, start_n: int) -> np.ndarray:
        """Ring entries [start_n, self._n), newest-capacity-bounded."""
        lo = max(start_n, self._n - self.capacity)
        if lo >= self._n:
            return np.zeros((0,), np.float64)
        idx = np.arange(lo, self._n) % self.capacity
        return self._ring[idx]

    def _emit_window(self, fence) -> None:
        if fence is not None:
            # the one telemetry device sync: block on the *latest* dispatch's
            # output so every step in the window has actually executed — the
            # window wall time is then honest, and the compiled program was
            # never touched
            import jax

            jax.block_until_ready(fence)
            self._last_t = time.perf_counter()
        times = self._slice(self._win_start_n)
        wall = self._last_t - (self._win_t0 or self._last_t)
        record = {
            "epoch": self._epoch,
            "step_start": self._win_start_step,
            "steps": int(self._n - self._win_start_n),
            **step_time_fields(times, self.flops_per_step, self.peak_flops),
            "samples_per_sec": round(self._win_samples / max(wall, 1e-9), 2),
            # pipeline occupancy (schema v3): how much of this window's wall
            # the dispatch loop spent blocked on host data, and how deep the
            # staged/in-flight queues ran — wall/device -> 1.0 is observable
            # per window, not just per run
            "host_stall_ms": round(self._win_stall * 1e3, 3),
            "staging_queue_depth": int(self._win_staging_max),
            "inflight_depth": int(self._win_inflight_max),
        }
        if self.writer is not None:
            self.writer.write(schema.stamp("step_stats", record))
        # the live plane reads exactly what the history flushed — a /metrics
        # scrape can never disagree with history.jsonl beyond one window
        self.last_window = record
        self.windows_emitted += 1
        self._win_start_n = self._n
        self._win_start_step = self.global_step
        self._win_samples = 0
        self._win_stall = 0.0
        self._win_staging_max = 0
        self._win_inflight_max = 0
        self._win_t0 = self._last_t
        if self.on_window is not None:
            self.on_window()

    def live_snapshot(self) -> dict:
        """Host-only live view for the exporter and the pod shard: cumulative
        counters plus the LAST emitted window's percentiles (when the window
        cadence is armed) or, without windows, percentiles over the newest
        ring entries at dispatch resolution. Never touches a device — no
        fence beyond the once-per-window one that already happened."""
        snap = {
            "epoch": self._epoch,
            "step": self.global_step,
            "samples_total": self.total_samples,
            "host_stall_ms_total": round(self.total_stall_s * 1e3, 3),
            "windows_emitted": self.windows_emitted,
        }
        if self.last_window is not None:
            for k in (
                "step_time_ms_p50", "step_time_ms_p95", "step_time_ms_p99",
                "step_time_ms_max", "samples_per_sec", "mfu_p50",
                "host_stall_ms",
            ):
                snap[k] = self.last_window.get(k)
            snap["window"] = {
                "epoch": self.last_window.get("epoch"),
                "step_start": self.last_window.get("step_start"),
                "steps": self.last_window.get("steps"),
            }
        else:
            # no window cadence: percentiles over the newest entries, at the
            # honest dispatch resolution (issue-time laps, not fenced)
            tail = self._slice(max(self._epoch_start_n, self._n - 256))
            snap.update(
                step_time_fields(tail, self.flops_per_step, self.peak_flops)
            )
            snap["samples_per_sec"] = None
            snap["window"] = None
        return snap

    def epoch_summary(self) -> dict:
        """Percentile fields for the finished epoch's history row, then reset
        the epoch slice.

        The wall basis is epoch start to the LAST train dispatch (not "now"):
        calling this after the eval pass must not fold eval time into the
        train-throughput figure. That basis is dispatch-resolution — exact
        under the per-window fences, convergent under device backpressure
        otherwise — matching the per-step ring entries it summarizes."""
        steps = self._n - self._epoch_start_n
        times = self._slice(self._epoch_start_n)
        end = self._last_t if self._last_t is not None else time.perf_counter()
        wall = end - (self._epoch_t0 if self._epoch_t0 is not None else end)
        fields = {
            "train_steps": int(steps),
            **step_time_fields(times, self.flops_per_step, self.peak_flops),
            "train_samples_per_sec": round(
                self._epoch_samples / max(wall, 1e-9), 2
            ),
            # whole-epoch host-stall total (the pipeline's residual host
            # bound; 0.0 when nothing stalled or no pipeline ran)
            "host_stall_ms": round(self._epoch_stall * 1e3, 3),
        }
        if steps > self.capacity:
            fields["step_stats_truncated"] = int(steps - self.capacity)
        return fields
