"""RunTelemetry — the one object an epoch driver wires through its hot loop.

Bundles the step recorder (:mod:`recorder`), the $TPUDDP_PROFILE_STEPS
window profiler and the SIGUSR1 epoch-trace trigger (:mod:`profiling`)
behind two per-dispatch calls:

    tel.pre_dispatch(n_steps)                  # before issuing the dispatch
    tel.post_dispatch(n_steps, n_samples, m)   # after, m = its output pytree

plus ``start_epoch``/``end_epoch`` at epoch boundaries and ``finish`` in the
driver's ``finally``. Everything is host-side: the compiled step program is
never touched (telemetry on/off lowers to the identical HLO), no collectives
are added, and the only device syncs are the per-window fence and the
profiler's end-of-window flush.

The live telemetry plane (ISSUE 10) attaches here too: ``attach_live``
wires the /metrics exporter (this run's gauges/counters/summaries), the
telemetry-shard publisher (this host's last window into the heartbeat
channel), and the main-process pod aggregator — all pumped at the
per-window boundary the recorder already fences, so "exporter + aggregator
on" adds zero device syncs and zero collectives.
"""

from __future__ import annotations

from typing import Callable, Optional

from tpuddp.observability import profiling
from tpuddp.observability.recorder import StepStatsRecorder, estimate_step_flops


class _NullTelemetry:
    """Inert stand-in so hot loops call the hooks unconditionally — a
    dispatch site can never forget a ``tel is not None`` guard because
    there is none."""

    def offer_batch(self, host_batch) -> None:
        pass

    def pre_dispatch(self, n_steps: int) -> None:
        pass

    def post_dispatch(self, n_steps: int, n_samples: int, fence=None, **occ) -> None:
        pass

    def start_epoch(self, epoch: int) -> None:
        pass

    def end_epoch(self) -> dict:
        return {}

    def finish(self) -> None:
        pass


NULL = _NullTelemetry()


class RunTelemetry:
    def __init__(
        self,
        writer=None,
        save_dir: Optional[str] = None,
        step_stats_every: int = 0,
        world_size: int = 1,
        flops_lower_fn: Optional[Callable] = None,
        device_kind: Optional[str] = None,
    ):
        """``flops_lower_fn``: zero-arg callable returning the lowered
        single-step program, used once (lazily, failure-tolerant) to resolve
        per-step FLOPs for the MFU fields; None leaves MFU null.
        ``device_kind``: the MESH device's kind (for the peak-FLOPs lookup)
        — pass it so a CPU-ladder run on a TPU-attached host (or the
        reverse) reports MFU against the right ceiling."""
        from tpuddp.observability.recorder import device_peak_flops

        self.recorder = StepStatsRecorder(
            writer=writer,
            window=step_stats_every,
            peak_flops=device_peak_flops(device_kind),
        )
        self.window_profiler = profiling.StepWindowProfiler(save_dir)
        self.writer = writer
        self.save_dir = save_dir
        self.world_size = max(1, int(world_size))
        self.flops_lower_fn = flops_lower_fn
        self.batch_struct = None
        self._flops_probed = False
        self._epoch_trace = False
        self._last_fence = None
        # live plane (attach_live): exporter/aggregator/shard channel plus
        # driver-updated gauges (skip counters, comm bytes, last losses)
        self.exporter = None
        self.aggregator = None
        self._shard_dir = None
        self._shard_pid = 0
        self.live: dict = {}
        self.recorder.on_window = self._on_window
        profiling.install_sigusr1_trigger()

    # -- live telemetry plane (ISSUE 10) -----------------------------------

    def attach_live(
        self,
        exporter=None,
        aggregator=None,
        shard_dir=None,
        process_id: int = 0,
    ) -> None:
        """Wire the live plane: ``exporter`` gets this run's training source,
        ``shard_dir`` arms per-window shard publishing into the heartbeat
        channel (also registered as the watchdog beat's payload so liveness
        rewrites carry the freshest shard), ``aggregator`` (main process) is
        pumped at every window boundary. All host-side, all at the existing
        per-window cadence — no new fences."""
        self.exporter = exporter
        self.aggregator = aggregator
        self._shard_dir = shard_dir
        self._shard_pid = int(process_id)
        if exporter is not None:
            exporter.register_source("training", self.export_source())
            if aggregator is not None:
                exporter.register_source("pod", aggregator.export_source())
        if shard_dir is not None:
            from tpuddp.resilience import watchdog as wd

            wd.set_heartbeat_payload(self._shard)

    def update_live(self, **fields) -> None:
        """Driver-side live gauges the recorder cannot see (guard skip
        totals, comm bytes, last epoch losses) — merged into the exporter's
        training source and the published shard."""
        self.live.update(fields)

    def _shard(self):
        from tpuddp.observability import aggregate

        return aggregate.make_shard(
            self.recorder.live_snapshot(),
            skipped_steps=self.live.get("skipped_steps") or 0,
        )

    def _on_window(self) -> None:
        """Recorder window-boundary pump: publish this host's shard, merge
        the pod view (main process). The window fence already happened —
        this is file IO + arithmetic only."""
        if self._shard_dir is not None:
            from tpuddp.observability import aggregate

            aggregate.publish_shard(
                self._shard_dir, self._shard_pid, self._shard()
            )
        if self.aggregator is not None:
            self.aggregator.update()

    def export_source(self):
        """The exporter's training source: cumulative counters + the last
        emitted window's percentiles (exactly what history.jsonl flushed)."""
        from tpuddp.observability import exporter as exp

        def source():
            live = self.recorder.live_snapshot()
            series = {
                "train_steps_total": exp.counter(
                    live.get("step"), "train steps since loop entry"
                ),
                "train_samples_total": exp.counter(
                    live.get("samples_total"), "global samples dispatched"
                ),
                "epoch": exp.gauge(live.get("epoch"), "current epoch"),
                "step_time_ms": exp.summary(
                    {
                        "0.5": live.get("step_time_ms_p50"),
                        "0.95": live.get("step_time_ms_p95"),
                        "0.99": live.get("step_time_ms_p99"),
                        "1.0": live.get("step_time_ms_max"),
                    },
                    "last-window per-step wall time",
                ),
                "train_samples_per_sec": exp.gauge(
                    live.get("samples_per_sec"), "last-window throughput"
                ),
                "mfu": exp.gauge(
                    live.get("mfu_p50"), "last-window achieved MFU at p50"
                ),
                "host_stall_ms_total": exp.counter(
                    live.get("host_stall_ms_total"),
                    "cumulative host-blocked time",
                ),
                "step_stats_windows_total": exp.counter(
                    live.get("windows_emitted"), "step_stats rows flushed"
                ),
            }
            for key, help_text in (
                ("skipped_steps", "guard-skipped updates (total)"),
                ("grad_comm_bytes_total", "gradient bytes on the wire"),
                ("train_loss", "last completed epoch train loss"),
                ("test_loss", "last completed epoch test loss"),
                ("test_accuracy", "last completed epoch test accuracy (%)"),
            ):
                if key in self.live:
                    kind = (
                        exp.counter
                        if key in ("skipped_steps", "grad_comm_bytes_total")
                        else exp.gauge
                    )
                    series[key] = kind(self.live[key], help_text)
            return series

        return source

    def offer_batch(self, host_batch) -> None:
        """Capture the abstract (shape, dtype) structure of one host batch —
        the FLOPs probe lowers the step program against it later. Reads only
        array metadata; nothing is copied or placed."""
        if self.batch_struct is not None:
            return
        try:
            import jax
            import numpy as np

            self.batch_struct = tuple(
                jax.ShapeDtypeStruct(np.shape(b), np.asarray(b).dtype)
                for b in host_batch
            )
        except Exception:  # metadata-only best effort; MFU stays null
            self.batch_struct = ()

    # -- hot-loop hooks (cheap: integer compares + perf_counter) -----------

    def pre_dispatch(self, n_steps: int) -> None:
        self.window_profiler.before_dispatch(self.recorder.global_step, n_steps)

    def post_dispatch(
        self, n_steps: int, n_samples: int, fence=None, *,
        host_stall_s: float = 0.0, staging_depth: int = 0,
        inflight_depth: int = 0,
    ) -> None:
        """``host_stall_s``/``staging_depth``/``inflight_depth``: the async
        pipeline's occupancy sample for this dispatch (time the dispatch loop
        spent blocked acquiring host batches since the previous dispatch, the
        staged-chunk queue depth, and issued-but-unobserved dispatches) —
        surfaced in step_stats windows and the epoch summary."""
        self._last_fence = fence
        self.recorder.record(
            n_steps, n_samples, fence=fence, host_stall_s=host_stall_s,
            staging_depth=staging_depth, inflight_depth=inflight_depth,
        )
        self.window_profiler.after_dispatch(self.recorder.global_step, fence)

    # -- epoch boundaries --------------------------------------------------

    def start_epoch(self, epoch: int) -> None:
        self.recorder.start_epoch(epoch)
        if profiling.consume_sigusr1_request():
            self._epoch_trace = profiling.start_epoch_trace(self.save_dir, epoch)
            if self._epoch_trace and self.writer is not None:
                from tpuddp.observability import schema

                self.writer.write(
                    schema.stamp(
                        "event", {"event": "profile_epoch", "epoch": epoch}
                    )
                )

    def stop_epoch_trace(self) -> None:
        """Flush an active SIGUSR1 epoch trace. Runs inside :meth:`end_epoch`
        by default; a driver whose train summary happens BEFORE evaluation
        (the managed loop) passes ``stop_trace=False`` there and calls this
        after eval, so the 'trace the next epoch' contract covers the whole
        epoch on both drivers."""
        if self._epoch_trace:
            profiling.stop_profiler()
            self._epoch_trace = False

    def end_epoch(self, stop_trace: bool = True) -> dict:
        """Step-time/MFU fields for the epoch's history row (call after the
        epoch's metric fetch — the device is already fenced there)."""
        if stop_trace:
            self.stop_epoch_trace()
        if not self._flops_probed and self.flops_lower_fn is not None:
            # once per run, at the FIRST epoch boundary (never in the hot
            # loop): lowering traces the step but compiles/executes nothing
            self._flops_probed = True
            self.recorder.flops_per_step = estimate_step_flops(
                self.flops_lower_fn, self.world_size
            )
        return self.recorder.epoch_summary()

    def finish(self) -> None:
        """Driver ``finally``: flush any partial step-window trace (it is the
        post-mortem artifact), release the trace latch, and detach the live
        plane (heartbeat shards must not outlive the telemetry they carry)."""
        self.window_profiler.finish(self._last_fence)
        self.stop_epoch_trace()
        if self._shard_dir is not None:
            from tpuddp.resilience import watchdog as wd

            wd.set_heartbeat_payload(None)
            self._shard_dir = None
        if self.exporter is not None:
            self.exporter.unregister_source("training")
            self.exporter.unregister_source("pod")
            self.exporter = None
        self.aggregator = None
