"""RunTelemetry — the one object an epoch driver wires through its hot loop.

Bundles the step recorder (:mod:`recorder`), the $TPUDDP_PROFILE_STEPS
window profiler and the SIGUSR1 epoch-trace trigger (:mod:`profiling`)
behind two per-dispatch calls:

    tel.pre_dispatch(n_steps)                  # before issuing the dispatch
    tel.post_dispatch(n_steps, n_samples, m)   # after, m = its output pytree

plus ``start_epoch``/``end_epoch`` at epoch boundaries and ``finish`` in the
driver's ``finally``. Everything is host-side: the compiled step program is
never touched (telemetry on/off lowers to the identical HLO), no collectives
are added, and the only device syncs are the per-window fence and the
profiler's end-of-window flush.
"""

from __future__ import annotations

from typing import Callable, Optional

from tpuddp.observability import profiling
from tpuddp.observability.recorder import StepStatsRecorder, estimate_step_flops


class _NullTelemetry:
    """Inert stand-in so hot loops call the hooks unconditionally — a
    dispatch site can never forget a ``tel is not None`` guard because
    there is none."""

    def offer_batch(self, host_batch) -> None:
        pass

    def pre_dispatch(self, n_steps: int) -> None:
        pass

    def post_dispatch(self, n_steps: int, n_samples: int, fence=None, **occ) -> None:
        pass

    def start_epoch(self, epoch: int) -> None:
        pass

    def end_epoch(self) -> dict:
        return {}

    def finish(self) -> None:
        pass


NULL = _NullTelemetry()


class RunTelemetry:
    def __init__(
        self,
        writer=None,
        save_dir: Optional[str] = None,
        step_stats_every: int = 0,
        world_size: int = 1,
        flops_lower_fn: Optional[Callable] = None,
        device_kind: Optional[str] = None,
    ):
        """``flops_lower_fn``: zero-arg callable returning the lowered
        single-step program, used once (lazily, failure-tolerant) to resolve
        per-step FLOPs for the MFU fields; None leaves MFU null.
        ``device_kind``: the MESH device's kind (for the peak-FLOPs lookup)
        — pass it so a CPU-ladder run on a TPU-attached host (or the
        reverse) reports MFU against the right ceiling."""
        from tpuddp.observability.recorder import device_peak_flops

        self.recorder = StepStatsRecorder(
            writer=writer,
            window=step_stats_every,
            peak_flops=device_peak_flops(device_kind),
        )
        self.window_profiler = profiling.StepWindowProfiler(save_dir)
        self.writer = writer
        self.save_dir = save_dir
        self.world_size = max(1, int(world_size))
        self.flops_lower_fn = flops_lower_fn
        self.batch_struct = None
        self._flops_probed = False
        self._epoch_trace = False
        self._last_fence = None
        profiling.install_sigusr1_trigger()

    def offer_batch(self, host_batch) -> None:
        """Capture the abstract (shape, dtype) structure of one host batch —
        the FLOPs probe lowers the step program against it later. Reads only
        array metadata; nothing is copied or placed."""
        if self.batch_struct is not None:
            return
        try:
            import jax
            import numpy as np

            self.batch_struct = tuple(
                jax.ShapeDtypeStruct(np.shape(b), np.asarray(b).dtype)
                for b in host_batch
            )
        except Exception:  # metadata-only best effort; MFU stays null
            self.batch_struct = ()

    # -- hot-loop hooks (cheap: integer compares + perf_counter) -----------

    def pre_dispatch(self, n_steps: int) -> None:
        self.window_profiler.before_dispatch(self.recorder.global_step, n_steps)

    def post_dispatch(
        self, n_steps: int, n_samples: int, fence=None, *,
        host_stall_s: float = 0.0, staging_depth: int = 0,
        inflight_depth: int = 0,
    ) -> None:
        """``host_stall_s``/``staging_depth``/``inflight_depth``: the async
        pipeline's occupancy sample for this dispatch (time the dispatch loop
        spent blocked acquiring host batches since the previous dispatch, the
        staged-chunk queue depth, and issued-but-unobserved dispatches) —
        surfaced in step_stats windows and the epoch summary."""
        self._last_fence = fence
        self.recorder.record(
            n_steps, n_samples, fence=fence, host_stall_s=host_stall_s,
            staging_depth=staging_depth, inflight_depth=inflight_depth,
        )
        self.window_profiler.after_dispatch(self.recorder.global_step, fence)

    # -- epoch boundaries --------------------------------------------------

    def start_epoch(self, epoch: int) -> None:
        self.recorder.start_epoch(epoch)
        if profiling.consume_sigusr1_request():
            self._epoch_trace = profiling.start_epoch_trace(self.save_dir, epoch)
            if self._epoch_trace and self.writer is not None:
                from tpuddp.observability import schema

                self.writer.write(
                    schema.stamp(
                        "event", {"event": "profile_epoch", "epoch": epoch}
                    )
                )

    def stop_epoch_trace(self) -> None:
        """Flush an active SIGUSR1 epoch trace. Runs inside :meth:`end_epoch`
        by default; a driver whose train summary happens BEFORE evaluation
        (the managed loop) passes ``stop_trace=False`` there and calls this
        after eval, so the 'trace the next epoch' contract covers the whole
        epoch on both drivers."""
        if self._epoch_trace:
            profiling.stop_profiler()
            self._epoch_trace = False

    def end_epoch(self, stop_trace: bool = True) -> dict:
        """Step-time/MFU fields for the epoch's history row (call after the
        epoch's metric fetch — the device is already fenced there)."""
        if stop_trace:
            self.stop_epoch_trace()
        if not self._flops_probed and self.flops_lower_fn is not None:
            # once per run, at the FIRST epoch boundary (never in the hot
            # loop): lowering traces the step but compiles/executes nothing
            self._flops_probed = True
            self.recorder.flops_per_step = estimate_step_flops(
                self.flops_lower_fn, self.world_size
            )
        return self.recorder.epoch_summary()

    def finish(self) -> None:
        """Driver ``finally``: flush any partial step-window trace (it is the
        post-mortem artifact) and release the trace latch."""
        self.window_profiler.finish(self._last_fence)
        self.stop_epoch_trace()
