"""On-demand XLA profiling — three triggers, one trace at a time.

- ``TPUDDP_PROFILE=<dir>`` (or ``1`` for ``<save_dir>/trace``): trace the
  FIRST epoch — the original env toggle, unchanged.
- ``TPUDDP_PROFILE_STEPS=<start>:<stop>``: trace the train-step window
  ``[start, stop)`` (global step index since loop entry). The trace starts
  before the dispatch that contains ``start`` and stops after the dispatch
  containing ``stop - 1`` completes on device — exact at ``scan_steps: 1``,
  rounded outward to whole fused groups otherwise (the window always
  *covers* the requested steps). Trace dir: the ``TPUDDP_PROFILE`` value
  when that names a directory, else ``<save_dir>/trace_steps_<start>_<stop>``.
- ``SIGUSR1``: capture ONE full epoch's trace from a live run — send the
  signal, the next epoch is traced into ``<save_dir>/trace_sigusr1_e<N>``.

jax.profiler supports one active trace, so all three funnel through the
module latch; a trigger that finds a trace already running is skipped with
a warning instead of crashing the run.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Optional, Tuple

import jax

logger = logging.getLogger("tpuddp")

_PROFILE_ENV = "TPUDDP_PROFILE"
_PROFILE_STEPS_ENV = "TPUDDP_PROFILE_STEPS"
_profiling = {"active": False}
_sigusr1 = {"installed": False, "requested": False}


def _start_trace(target: str) -> bool:
    if _profiling["active"]:
        logger.warning(
            "profiler trigger for %s skipped: a trace is already active", target
        )
        return False
    os.makedirs(target, exist_ok=True)
    jax.profiler.start_trace(target)
    _profiling["active"] = True
    return True


def maybe_start_profiler(default_dir: Optional[str] = None) -> bool:
    """Start an XLA trace if $TPUDDP_PROFILE is set (its value is the trace
    dir; '1' falls back to ``default_dir``/trace). Returns True if started.

    When $TPUDDP_PROFILE_STEPS is also set, the step window OWNS the trace
    and the first-epoch mode stands down (one trace at a time)."""
    target = os.environ.get(_PROFILE_ENV)
    if not target or _profiling["active"]:
        return False
    if os.environ.get(_PROFILE_STEPS_ENV):
        return False
    if target == "1":
        if default_dir is None:
            return False
        target = os.path.join(default_dir, "trace")
    return _start_trace(target)


def stop_profiler() -> None:
    if _profiling["active"]:
        jax.profiler.stop_trace()
        _profiling["active"] = False


def parse_profile_steps(
    raw: Optional[str] = None,
) -> Optional[Tuple[int, int]]:
    """``$TPUDDP_PROFILE_STEPS`` as ``(start, stop)``; None when unset.
    Malformed values are refused loudly — a typo'd window silently ignored
    would "profile" nothing and report success."""
    raw = os.environ.get(_PROFILE_STEPS_ENV, "") if raw is None else raw
    if not raw:
        return None
    try:
        start_s, stop_s = raw.split(":")
        start, stop = int(start_s), int(stop_s)
    except ValueError:
        raise ValueError(
            f"{_PROFILE_STEPS_ENV}={raw!r} is not <start>:<stop> "
            "(two integers, e.g. 100:110)"
        )
    if start < 0 or stop <= start:
        raise ValueError(
            f"{_PROFILE_STEPS_ENV}={raw!r}: need 0 <= start < stop"
        )
    return start, stop


class StepWindowProfiler:
    """The $TPUDDP_PROFILE_STEPS driver hook.

    The epoch driver calls :meth:`before_dispatch` with the global step index
    the upcoming dispatch starts at and how many fused steps it covers, and
    :meth:`after_dispatch` with the dispatch's output. Inert (two integer
    compares per dispatch) when the env knob is unset."""

    def __init__(self, save_dir: Optional[str]):
        self.window = parse_profile_steps()
        self.dir = None
        self.active = False
        self.done = self.window is None
        if self.window is not None:
            start, stop = self.window
            explicit = os.environ.get(_PROFILE_ENV)
            if explicit and explicit != "1":
                self.dir = explicit
            elif save_dir is not None:
                self.dir = os.path.join(
                    save_dir, f"trace_steps_{start}_{stop}"
                )
            else:
                logger.warning(
                    "%s set but no trace dir resolvable (no save_dir and no "
                    "%s=<dir>); step-window profiling disabled",
                    _PROFILE_STEPS_ENV,
                    _PROFILE_ENV,
                )
                self.done = True

    def before_dispatch(self, global_step: int, n_steps: int) -> None:
        if self.done or self.active:
            return
        start, _ = self.window
        if global_step + n_steps > start:  # this dispatch contains `start`
            self.active = _start_trace(self.dir)
            if not self.active:
                self.done = True  # trace slot taken; don't retry every step

    def after_dispatch(self, global_step_end: int, fence=None) -> None:
        if not self.active:
            return
        _, stop = self.window
        if global_step_end >= stop:
            if fence is not None:
                # the trace must contain the window's *execution*, not just
                # its dispatch: block on the last covered dispatch's output
                jax.block_until_ready(fence)
            stop_profiler()
            self.active = False
            self.done = True
            logger.info(
                "step-window trace [%d, %d) captured -> %s",
                self.window[0],
                stop,
                self.dir,
            )

    def finish(self, fence=None) -> None:
        """Loop teardown: a window that never reached ``stop`` (short run,
        exception) still flushes its partial trace — it is the post-mortem."""
        if self.active:
            self.after_dispatch(self.window[1], fence)
            if self.active:  # stop index never reached: force the flush
                stop_profiler()
                self.active = False
                self.done = True


# --------------------------------------------------------------- SIGUSR1 --


def _on_sigusr1(signum, frame) -> None:
    _sigusr1["requested"] = True


def install_sigusr1_trigger() -> bool:
    """Arm the SIGUSR1 -> trace-next-epoch trigger. Main-thread only (the
    Python signal limitation, same as the preemption handlers); returns False
    and stays a no-op elsewhere."""
    if _sigusr1["installed"]:
        return True
    if threading.current_thread() is not threading.main_thread():
        logger.debug("not main thread; SIGUSR1 profile trigger not installed")
        return False
    try:
        signal.signal(signal.SIGUSR1, _on_sigusr1)
    except (ValueError, OSError, AttributeError):  # exotic platforms
        return False
    _sigusr1["installed"] = True
    return True


def consume_sigusr1_request() -> bool:
    """True once per received SIGUSR1 (the epoch driver polls this at each
    epoch start and traces that epoch when it fires)."""
    if _sigusr1["requested"]:
        _sigusr1["requested"] = False
        return True
    return False


def start_epoch_trace(save_dir: Optional[str], epoch: int) -> bool:
    """Start the SIGUSR1-requested one-epoch trace."""
    if save_dir is None:
        logger.warning("SIGUSR1 trace requested but no save_dir; skipped")
        return False
    return _start_trace(os.path.join(save_dir, f"trace_sigusr1_e{epoch}"))


def reset_profiling_state() -> None:
    """Test isolation: drop the latch and any pending SIGUSR1 request."""
    if _profiling["active"]:
        stop_profiler()
    _sigusr1["requested"] = False
