"""Crash flight recorder — a bounded in-memory ring dumped on abnormal exit.

``history.jsonl`` already records everything, but on a crash the operator's
first question is "what were the last few windows doing?" — answered today
by scanning a possibly-huge file. The flight recorder keeps the LAST N
records of each kind (step_stats windows, events, epoch rows,
serving_stats) in memory, fed by the same tee every history write passes
through (``MetricsWriter(flight=...)``) — so the rings hold exactly what the
history flushed, plus the run_meta header, guard/comm context the epoch rows
carry, and any ad-hoc ``note()`` fields. All host-side; nothing here ever
touches a device.

On an abnormal exit path the recorder dumps one strict-JSON artifact,
``flightrec_<reason>.json``, atomically (tmp+rename) into the run dir:

=================  ========================================================
reason             exit path
=================  ========================================================
preempt            SIGTERM/SIGINT drain -> emergency checkpoint -> exit 75
preempt_forced     the drain blew its grace window; failsafe forced exit 75
watchdog           a peer's heartbeat went stale -> exit 76
desync             the guard's auditor found a divergent replica -> exit 77
exception          unhandled exception in either epoch driver
serving_dispatch   the serving engine lost its last healthy replica
=================  ========================================================

``tools/tpuddp_inspect.py`` validates (schema.validate_flight_file) and
pretty-prints recordings; ``tools/supervise.py`` summarizes the newest one
before deciding restart/shrink. Dumps are idempotent per reason and
best-effort by contract: a failing dump logs and returns None — the exit
path that triggered it must proceed regardless.

A module-level registry (:func:`install`/:func:`dump_all`) lets detached
exit paths (the watchdog thread, the preemption failsafe) dump every live
recorder without plumbing references through the resilience layer.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tpuddp.observability import schema
from tpuddp.observability.metrics import json_sanitize

logger = logging.getLogger("tpuddp")

DEFAULT_CAPACITY = 64

# record types with their own ring; anything else (run_meta) is kept whole
_RING_TYPES = ("step_stats", "event", "epoch", "serving_stats", "decode_stats")

_registry_lock = threading.Lock()
_registry: List["FlightRecorder"] = []


class FlightRecorder:
    """Bounded per-process record rings + the atomic dump."""

    def __init__(
        self,
        save_dir: Optional[str],
        capacity: int = DEFAULT_CAPACITY,
        process_index: Optional[int] = None,
    ):
        if process_index is None:
            try:
                import jax

                process_index = jax.process_index()
            except Exception:
                process_index = 0
        self.save_dir = save_dir
        self.capacity = max(1, int(capacity))
        self.process_index = int(process_index)
        self._lock = threading.Lock()
        self._rings: Dict[str, deque] = {
            t: deque(maxlen=self.capacity) for t in _RING_TYPES
        }
        self._run_meta: Optional[dict] = None
        self._notes: dict = {}
        # live-context providers: zero-arg callables sampled AT DUMP TIME
        # (not at write time) — how the tracing plane embeds its still-open
        # spans so a crash dump names the exact stage the process died in
        self._context: Dict[str, callable] = {}
        self.observed = 0
        self.dumped: Dict[str, str] = {}  # reason -> path (idempotence)

    # ------------------------------------------------------------- feeds --
    def observe(self, record) -> None:
        """Tee one history record into its ring (MetricsWriter calls this on
        every write, BEFORE the process-0 file gate — every process keeps its
        own recording). Unknown/untyped records are ignored."""
        if not isinstance(record, dict):
            return
        rtype = record.get("type")
        with self._lock:
            self.observed += 1
            if rtype == "run_meta":
                self._run_meta = record  # newest header wins (elastic resume)
            elif rtype in self._rings:
                self._rings[rtype].append(record)

    def note(self, **fields) -> None:
        """Attach ad-hoc live context (last guard verdict, comm-byte
        snapshot, in-flight depth) to the next dump."""
        with self._lock:
            self._notes.update(fields)

    def add_context(self, name: str, fn) -> None:
        """Register a live-context provider: ``fn()`` is called at dump
        time and its result lands under ``notes[name]``. Best-effort by the
        dump contract — a raising provider records its failure string
        instead of blocking the exit path."""
        with self._lock:
            self._context[name] = fn

    # -------------------------------------------------------------- dump --
    def payload(self, reason: str) -> dict:
        with self._lock:
            providers = list(self._context.items())
        notes = {}
        for name, fn in providers:
            # sampled OUTSIDE self._lock: a provider takes its own lock
            # (the tracer's), and holding both here would pin a lock order
            # on every future provider
            try:
                notes[name] = fn()
            except Exception as e:  # noqa: BLE001 — never block an exit path
                notes[name] = f"<context provider failed: {e}>"
        with self._lock:
            notes.update(self._notes)
            records = {t: list(ring) for t, ring in self._rings.items()}
            return json_sanitize({
                "type": schema.FLIGHT_TYPE,
                "schema_version": schema.SCHEMA_VERSION,
                "reason": reason,
                "process_index": self.process_index,
                "capacity": self.capacity,
                "dumped_at": round(time.time(), 3),
                "observed_records": self.observed,
                "counts": {t: len(r) for t, r in records.items()},
                "run_meta": self._run_meta,
                "notes": notes,
                "records": records,
            })

    def dump(self, reason: str) -> Optional[str]:
        """Write ``flightrec_<reason>.json`` atomically; returns the path,
        the previous path when this reason already dumped, or None (no
        save_dir, or a failed best-effort write — logged, never raised).

        Non-zero processes write ``flightrec_<reason>_p<i>.json``: on a pod
        the save_dir is SHARED, and an unqualified name would be
        last-rename-wins across hosts — one arbitrary recording surviving a
        multi-host death instead of every process keeping its own."""
        if self.save_dir is None:
            return None
        if reason in self.dumped:
            return self.dumped[reason]
        name = (
            f"flightrec_{reason}.json"
            if self.process_index == 0
            else f"flightrec_{reason}_p{self.process_index}.json"
        )
        path = os.path.join(self.save_dir, name)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.save_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.payload(reason), f, allow_nan=False, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except (OSError, ValueError) as e:
            logger.warning("flight recorder dump (%s) failed: %s", reason, e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        self.dumped[reason] = path
        logger.warning("flight recording (%s) -> %s", reason, path)
        return path

    def describe(self) -> dict:
        """run_meta ``observability.flight_recorder`` provenance fields."""
        return {"capacity": self.capacity}


# ------------------------------------------------------------- registry --


def install(recorder: FlightRecorder) -> FlightRecorder:
    """Register a live recorder so detached exit paths (watchdog thread,
    preemption failsafe) can dump it without holding a reference."""
    with _registry_lock:
        if recorder not in _registry:
            _registry.append(recorder)
    return recorder


def uninstall(recorder: FlightRecorder) -> None:
    with _registry_lock:
        if recorder in _registry:
            _registry.remove(recorder)


def dump_all(reason: str) -> List[str]:
    """Dump every installed recorder (best-effort, exception-free — callers
    are exit paths that must proceed). Returns the written paths."""
    with _registry_lock:
        recorders = list(_registry)
    paths = []
    for rec in recorders:
        try:
            path = rec.dump(reason)
        except Exception:  # noqa: BLE001 — never block an exit path
            logger.exception("flight recorder dump_all(%r) failed", reason)
            continue
        if path:
            paths.append(path)
    return paths


def find_recordings(directory: str) -> List[str]:
    """``flightrec_*.json`` files in ``directory``, newest first (what
    tools/supervise.py summarizes before deciding restart/shrink)."""
    try:
        names = [
            n for n in os.listdir(directory)
            if n.startswith("flightrec_") and n.endswith(".json")
        ]
    except OSError:
        return []
    paths = [os.path.join(directory, n) for n in names]
    return sorted(paths, key=lambda p: os.path.getmtime(p), reverse=True)


def summarize_recording(path: str) -> List[str]:
    """Human-readable one-screen summary lines (shared by tpuddp_inspect and
    the supervisor's pickup log). Tolerant of invalid files — the summary of
    a corrupt recording says so instead of raising."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        return [f"flight recording {path}: unreadable ({e})"]
    if not isinstance(payload, dict):
        return [f"flight recording {path}: not a JSON object"]
    lines = [
        f"flight recording: reason={payload.get('reason')} "
        f"process={payload.get('process_index')} "
        f"capacity={payload.get('capacity')}"
    ]
    meta = payload.get("run_meta") or {}
    if meta:
        lines.append(
            f"  run: api={meta.get('api')} model={meta.get('model')} "
            f"world={meta.get('world_size')} epoch span "
            f"{meta.get('start_epoch')}..{meta.get('num_epochs')}"
        )
    records = payload.get("records") or {}
    windows = records.get("step_stats") or []
    if windows:
        last = windows[-1]
        lines.append(
            f"  last window: epoch {last.get('epoch')} steps "
            f"[{last.get('step_start')}, "
            f"{(last.get('step_start') or 0) + (last.get('steps') or 0)}) "
            f"p50 {last.get('step_time_ms_p50')} ms "
            f"({len(windows)} window(s) retained)"
        )
    epochs = records.get("epoch") or []
    if epochs:
        last = epochs[-1]
        lines.append(
            f"  last epoch: {last.get('epoch')} train_loss "
            f"{last.get('train_loss')} skips "
            f"{last.get('skipped_steps_epoch', 0)}"
        )
    events = records.get("event") or []
    for ev in events[-5:]:
        fields = {
            k: v for k, v in ev.items()
            if k not in ("type", "schema_version", "event")
        }
        lines.append(f"  event: {ev.get('event')} {fields}")
    return lines
