"""Live /metrics exporter — an opt-in background HTTP endpoint.

Everything tpuddp measures today is post-hoc: ``history.jsonl`` is read
after the run, serving SLO windows only exist once flushed. The exporter
makes the SAME numbers scrapeable while the run is alive, with the standing
telemetry invariant intact: **zero new device fences**. Every value served
here is host-side state the per-window fence (recorder) or the dispatch
delivery path (serving stats) already materialized — a scrape reads dicts,
never a device.

Endpoints (ThreadingHTTPServer on a daemon thread; ``observability.exporter``
config block, default OFF):

- ``/metrics``  — Prometheus text exposition (gauges, counters, and
  quantile-labeled summaries);
- ``/healthz``  — ``{"status": "ok", "uptime_s": ...}`` liveness JSON;
- ``/snapshot`` — the raw merged source dicts as JSON (the machine-readable
  twin of /metrics, exact values, no text-format rounding);
- ``/trace``    — the causal tracing plane's live view (the last-N completed
  spans + the open set, observability/trace.py) when a trace source is
  registered (:meth:`MetricsExporter.set_trace_source`); 404 otherwise.

Responses are always WHOLE: the body is fully rendered before a byte is
sent (Content-Length framing), and any rendering error returns a complete
500 — a concurrent writer hammering the sources can never make a scrape
read a torn or half-written payload.

Sources are zero-arg callables returning ``{series_name: series}`` where a
series is built with :func:`gauge`/:func:`counter`/:func:`summary`. The
epoch drivers register the training telemetry source
(``RunTelemetry.export_source``), the serving engine its SLO source
(``ServingStats.export_source``), and the pod aggregator its per-host view —
a failing source is dropped from that scrape with a warning, never a 500 for
the other sources.

``port=0`` binds an ephemeral port (tests, multi-tenant hosts); the bound
port is republished in the run's ``run_meta.observability`` header field and
— when a run dir is known — in ``<dir>/exporter.port`` so operators and the
gate's scrape leg can find a live endpoint without parsing logs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from tpuddp.observability.metrics import json_sanitize

logger = logging.getLogger("tpuddp")

PORT_FILENAME = "exporter.port"
_PREFIX = "tpuddp_"


def gauge(value, help: str = "") -> dict:
    """A point-in-time value (epoch, queue depth, occupancy)."""
    return {"type": "gauge", "help": help, "value": value}


def counter(value, help: str = "") -> dict:
    """A monotonically-increasing total (steps, requests, bytes)."""
    return {"type": "counter", "help": help, "value": value}


def summary(quantiles: Dict[str, object], help: str = "", count=None) -> dict:
    """A latency-style series: ``{"0.5": ms, "0.95": ms, ...}`` quantile
    values (None entries are skipped at render time) plus an optional
    observation count."""
    return {
        "type": "summary",
        "help": help,
        "quantiles": dict(quantiles),
        "count": count,
    }


def _escape_label(value) -> str:
    """Prometheus exposition label-value escaping: backslash, double quote,
    and newline. Label values are caller-supplied strings (tenant ids!) —
    one unescaped quote would make the WHOLE /metrics page unparseable."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value) -> Optional[str]:
    """Prometheus sample value, or None to omit the sample (null metric)."""
    if value is None or isinstance(value, bool):
        return None
    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    return repr(f) if f != int(f) else str(int(f))


class MetricsExporter:
    """The background endpoint. ``start()`` binds and serves; ``stop()``
    tears down (idempotent, called from the drivers' ``finally``)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        run_dir: Optional[str] = None,
        port_filename: str = PORT_FILENAME,
    ):
        """``port_filename``: the discovery file's name inside ``run_dir``.
        On a pod the run dir is SHARED — each process must publish under its
        own name (``exporter_from_config`` qualifies non-zero processes as
        ``exporter_p<i>.port``) or the file is last-writer-wins across hosts
        and the first process to stop deletes it under its peers."""
        self.host = host
        self.requested_port = int(port)
        self.port: Optional[int] = None  # bound port, known after start()
        self.run_dir = run_dir
        self.port_filename = port_filename
        self._sources: Dict[str, Callable[[], dict]] = {}
        # the /trace feed (observability/trace.py Tracer.endpoint_payload);
        # None = no tracing plane attached, the endpoint answers 404
        self.trace_source: Optional[Callable[[], dict]] = None
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self.scrapes = 0

    # ---------------------------------------------------------- sources --
    def register_source(self, name: str, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._sources[name] = fn

    def unregister_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def set_trace_source(self, fn: Optional[Callable[[], dict]]) -> None:
        """Attach (or detach, with None) the /trace endpoint's feed — a
        zero-arg callable returning the span payload dict (the tracer copies
        its ring under its own lock; serialization happens here)."""
        with self._lock:
            self.trace_source = fn

    def collect(self) -> Dict[str, dict]:
        """Merge every source's series; a failing source is skipped with a
        warning (one broken feeder must not take the endpoint down)."""
        with self._lock:
            sources = list(self._sources.items())
        merged: Dict[str, dict] = {}
        for name, fn in sources:
            try:
                series = fn() or {}
            except Exception as e:  # noqa: BLE001 — scrape must survive
                logger.warning("exporter: source %r failed: %s", name, e)
                continue
            merged.update(series)
        return merged

    # --------------------------------------------------------- rendering --
    def render_prometheus(self) -> str:
        lines = []
        for name, series in sorted(self.collect().items()):
            full = name if name.startswith(_PREFIX) else _PREFIX + name
            stype = series.get("type", "gauge")
            if series.get("help"):
                lines.append(f"# HELP {full} {series['help']}")
            lines.append(f"# TYPE {full} {stype}")
            if stype == "summary":
                for q, v in series.get("quantiles", {}).items():
                    s = _fmt(v)
                    if s is not None:
                        lines.append(
                            f'{full}{{quantile="{_escape_label(q)}"}} {s}'
                        )
                c = _fmt(series.get("count"))
                if c is not None:
                    lines.append(f"{full}_count {c}")
            else:
                s = _fmt(series.get("value"))
                if s is not None:
                    labels = series.get("labels")
                    if labels:
                        lab = ",".join(
                            f'{k}="{_escape_label(v)}"'
                            for k, v in sorted(labels.items())
                        )
                        lines.append(f"{full}{{{lab}}} {s}")
                    else:
                        lines.append(f"{full} {s}")
                for extra_labels, v in series.get("values", []):
                    s = _fmt(v)
                    if s is None:
                        continue
                    lab = ",".join(
                        f'{k}="{_escape_label(val)}"'
                        for k, val in sorted(extra_labels.items())
                    )
                    lines.append(f"{full}{{{lab}}} {s}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        return {
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "scrapes": self.scrapes,
            "series": json_sanitize(self.collect()),
        }

    # --------------------------------------------------------- lifecycle --
    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        # a SIGKILLed predecessor never ran its stop(): its port file is
        # still on disk, pointing at a port nobody owns (or, worse, one the
        # OS re-issued to a stranger). Remove it BEFORE binding so a reader
        # polling during our startup sees "no port yet", never a stale one
        # — and readers must treat any port as live only after a /healthz
        # probe succeeds (:func:`read_live_port`) regardless.
        if self.run_dir is not None:
            try:
                os.remove(os.path.join(self.run_dir, self.port_filename))
            except OSError:
                pass
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # stdout silence: we have a logger
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        exporter.scrapes += 1
                        self._send(
                            200,
                            exporter.render_prometheus().encode(),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/healthz":
                        body = json.dumps({
                            "status": "ok",
                            "uptime_s": round(
                                time.monotonic() - exporter._t0, 3
                            ),
                        }).encode()
                        self._send(200, body, "application/json")
                    elif path == "/snapshot":
                        body = json.dumps(
                            exporter.snapshot(), allow_nan=False
                        ).encode()
                        self._send(200, body, "application/json")
                    elif path == "/trace":
                        with exporter._lock:
                            source = exporter.trace_source
                        if source is None:
                            self._send(
                                404, b"tracing not enabled\n", "text/plain"
                            )
                        else:
                            body = json.dumps(
                                json_sanitize(source()), allow_nan=False
                            ).encode()
                            self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass  # client went away mid-response
                except Exception as e:  # noqa: BLE001 — torn-payload guard
                    # every body above is FULLY rendered before _send, so a
                    # rendering error (a source mutated mid-serialize by a
                    # writer thread, a non-finite leak) lands here with
                    # nothing on the wire yet — answer with a COMPLETE 500
                    # instead of a truncated connection the client would
                    # misread as a torn payload
                    logger.warning("exporter: scrape failed: %s", e)
                    try:
                        self._send(
                            500,
                            f"scrape failed: {e}\n".encode(),
                            "text/plain",
                        )
                    except Exception:  # noqa: BLE001 — socket already gone
                        pass

        self._server = ThreadingHTTPServer(
            (self.host, self.requested_port), Handler
        )
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuddp-exporter",
            daemon=True,
        )
        self._thread.start()
        self._write_port_file()
        logger.info(
            "exporter: /metrics /healthz /snapshot live on %s:%d",
            self.host, self.port,
        )
        return self

    def _write_port_file(self) -> None:
        """Publish the bound port next to the run artifacts (atomic write) —
        how operators and the gate's scrape leg discover an ephemeral port.
        Line 1 is the port; line 2 the bound host (the heartbeat-file shape:
        readers that only care about the port parse line 1 ONLY, and
        :func:`read_live_port` probes the recorded host so a
        non-loopback-bound exporter is discoverable too)."""
        if self.run_dir is None or self.port is None:
            return
        try:
            os.makedirs(self.run_dir, exist_ok=True)
            path = os.path.join(self.run_dir, self.port_filename)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"{self.port}\n{self.host}\n")
            os.replace(tmp, path)
        except OSError as e:  # best-effort discovery aid, never fatal
            logger.warning("exporter: port file write failed: %s", e)

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.run_dir is not None:
            try:
                os.remove(os.path.join(self.run_dir, self.port_filename))
            except OSError:
                pass

    def describe(self) -> dict:
        """The run_meta ``observability.exporter`` provenance fields."""
        return {"host": self.host, "port": self.port}


def read_live_port(
    run_dir: str,
    port_filename: str = PORT_FILENAME,
    host: Optional[str] = None,
    probe_timeout: float = 1.0,
) -> Optional[int]:
    """The discovery contract for ``<run_dir>/exporter.port`` READERS (the
    fleet autoscaler, gate scrape legs, operators): a port file is a hint,
    not a liveness proof — a SIGKILLed run leaves its file behind. Returns
    the port only after a ``/healthz`` probe (short ``probe_timeout``)
    answers ``{"status": "ok"}``; None for a missing/garbled file, a dead
    port, or a non-ok answer. The probe targets the file's line-2 host
    (what the exporter actually bound — a non-loopback bind is probed where
    it lives), unless ``host`` overrides it; a single-line legacy file or a
    bind-all host falls back to loopback."""
    import json as json_lib
    import urllib.request

    path = os.path.join(run_dir, port_filename)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
        port = int(lines[0].strip())
    except (OSError, ValueError, IndexError):
        return None
    if host is None:
        host = lines[1].strip() if len(lines) > 1 and lines[1].strip() else ""
        if not host or host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=probe_timeout
        ) as resp:
            health = json_lib.load(resp)
    except Exception:  # noqa: BLE001 — dead/foreign port == not live
        return None
    if isinstance(health, dict) and health.get("status") == "ok":
        return port
    return None


def exporter_from_config(obs_cfg: dict, run_dir=None) -> Optional[MetricsExporter]:
    """Build (not start) an exporter from a resolved ``observability`` config
    block (tpuddp/config.py:OBSERVABILITY_DEFAULTS); None when disabled.

    ``exporter: true`` serves on ``exporter_host:exporter_port``; the default
    port 0 binds ephemerally and publishes the real port in
    ``<run_dir>/exporter.port`` + the run_meta header."""
    if not obs_cfg or not obs_cfg.get("exporter"):
        return None
    try:
        import jax

        process_index = jax.process_index()
    except Exception:
        process_index = 0
    return MetricsExporter(
        host=str(obs_cfg.get("exporter_host") or "127.0.0.1"),
        port=int(obs_cfg.get("exporter_port") or 0),
        run_dir=run_dir,
        # per-process discovery file: the run dir is shared on a pod, and
        # every host serves its own endpoint
        port_filename=(
            PORT_FILENAME
            if process_index == 0
            else f"exporter_p{process_index}.port"
        ),
    )
