"""Trace/metric-driven autotuning advisor — the observability plane's first
CONSUMER (ROADMAP open item 5: every prior PR only produced telemetry).

The advisor is a read-only evidence engine over a finished (or live) run
directory's artifacts:

- ``history.jsonl``       — run_meta provenance + epoch/step_stats/serving/
                            decode windows (schema.py, v12 reader);
- ``trace_<role>.json``   — the causal span trees (dispatch/stage/readback/
                            collective time shares, overlap segment digests);
- ``*.writer.json``       — the async snapshot writer's sidecars (backlog,
                            write seconds, skipped-queue-full counts).

It distills them into typed **evidence features** (:func:`extract_evidence`)
and walks a **rule table** (:data:`RULES`) mapping evidence to knob
recommendations. Each recommendation is a typed config diff carrying its
evidence citations (source artifact + field + observed value) and a
predicted delta on a named metric — never a bare "try X". Rules that need
span evidence report ``insufficient_evidence`` on a trace-less run instead
of guessing (satellite contract: a v11 history with no trace artifact must
degrade gracefully, not silently skip).

Three consumers:

- ``tpuddp_inspect tune <run_dir>``   — offline: print diff + evidence
  table; ``--emit`` writes the merged overlay (:func:`overlay_from`);
- ``tools/autotune.py``               — A/B probe: baseline vs recommended
  through the real epoch driver, predicted-vs-measured into TUNE_r*.json
  (tpuddp/tune/probe.py builds + schema-validates the artifact);
- the fleet tuner (tpuddp/tune/online.py) — applies at most one ENDORSED
  knob per job per cooldown via drain-and-relaunch, reverts on regression.

Deliberately **pure stdlib** (no jax, no tpuddp imports): the jax-free CLI
(tools/tpuddp_inspect.py) loads this module by file path, and the flight
recorder's ``pending_tune`` context provider must never pull device deps
into a crash path.
"""

from __future__ import annotations

import glob as glob_lib
import json
import os
from typing import Dict, List, Optional

RULE_CLASSES = ("pipeline", "comm", "snapshot", "serving")

# Evidence thresholds — module constants so tests can reference (not patch)
# the exact boundaries the rules fire at.
HOST_STALL_SHARE_THRESHOLD = 0.10   # host stall fraction of epoch wall time
READBACK_SHARE_THRESHOLD = 0.20     # readback span share of traced step time
DISPATCH_SHARE_THRESHOLD = 0.30     # dispatch span share of traced step time
SNAPSHOT_HOT_EVERY_STEPS = 2        # a cadence this tight is itself evidence
SNAPSHOT_WRITE_SHARE_FLOOR = 0.02   # min predicted win for cadence backoff
OCCUPANCY_FLOOR = 0.30              # serving batch occupancy below = starved
KV_PRESSURE_THRESHOLD = 0.85        # decode KV-pool occupancy above = thrash
COMM_BYTES_FLOOR = 1024             # per-update grad bytes below this: noise


def _mean(xs) -> Optional[float]:
    vals = [float(x) for x in xs if isinstance(x, (int, float))]
    return sum(vals) / len(vals) if vals else None


def _num(x, default=None):
    return float(x) if isinstance(x, (int, float)) else default


def cite(source: str, field: str, value) -> dict:
    """One evidence citation: which artifact, which field, what we saw."""
    return {"source": source, "field": field, "value": value}


# ---------------------------------------------------------------- loading --


def load_run(run_dir: str) -> dict:
    """Gather a run directory's artifacts, tolerantly: a missing or torn
    artifact yields an absent feature, never an exception — the advisor must
    run over a crashed run's partial output (that is its whole point)."""
    history_path = os.path.join(run_dir, "history.jsonl")
    records: List[dict] = []
    try:
        with open(history_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass

    run_meta: Dict = {}
    for rec in records:
        if rec.get("type") == "run_meta":
            run_meta.update(rec)  # resumed runs append headers; last wins

    traces = []
    for path in sorted(glob_lib.glob(os.path.join(run_dir, "trace_*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(payload, dict):
            payload["_path"] = os.path.basename(path)
            traces.append(payload)

    sidecars = []
    for path in sorted(
        glob_lib.glob(os.path.join(run_dir, "**", "*.writer.json"),
                      recursive=True)
    ):
        try:
            with open(path) as f:
                stats = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(stats, dict):
            sidecars.append({"path": os.path.relpath(path, run_dir),
                             "stats": stats})

    return {
        "run_dir": run_dir,
        "history_path": history_path,
        "records": records,
        "run_meta": run_meta,
        "traces": traces,
        "writer_sidecars": sidecars,
    }


# ------------------------------------------------------ evidence features --


def _epoch_features(records: List[dict]) -> dict:
    epochs = [r for r in records if r.get("type") == "epoch"]
    steps = [r for r in records if r.get("type") == "step_stats"]
    total_time = sum(
        v for v in (_num(r.get("epoch_time_s")) for r in epochs) if v
    )
    total_stall_ms = sum(
        v for v in (_num(r.get("host_stall_ms")) for r in epochs) if v
    )
    stall_share = (
        (total_stall_ms / 1000.0) / total_time if total_time > 0 else None
    )
    return {
        "epochs": len(epochs),
        "step_windows": len(steps),
        "samples_per_sec_mean": _mean(r.get("samples_per_sec") for r in epochs),
        "step_time_ms_p50_mean": _mean(
            r.get("step_time_ms_p50") for r in epochs
        ),
        "epoch_time_s_total": total_time or None,
        "host_stall_ms_total": total_stall_ms or 0.0,
        "host_stall_share": stall_share,
        "inflight_depth_mean": _mean(r.get("inflight_depth") for r in steps),
        "staging_queue_depth_mean": _mean(
            r.get("staging_queue_depth") for r in steps
        ),
    }


def _span_features(traces: List[dict]) -> dict:
    """Per-category span-time shares across every trace artifact. The share
    denominator is the traced step-phase time (dispatch+stage+readback+
    collective), NOT wall time — ring-dropped spans make wall shares lie."""
    if not traces:
        return {"available": False}
    by_cat: Dict[str, float] = {}
    overlap_segments = set()
    spans = 0
    dropped = 0
    for payload in traces:
        meta = payload.get("tpuddp") or {}
        dropped += int(_num(meta.get("dropped"), 0) or 0)
        for e in payload.get("traceEvents") or []:
            if not isinstance(e, dict) or e.get("ph") != "X":
                continue
            spans += 1
            cat = str(e.get("cat") or "")
            dur = _num(e.get("dur"), 0.0) or 0.0
            by_cat[cat] = by_cat.get(cat, 0.0) + dur
            name = str(e.get("name") or "")
            if name.startswith("grad_comm.seg"):
                overlap_segments.add(name)
    phase_total = sum(
        by_cat.get(c, 0.0)
        for c in ("dispatch", "stage", "readback", "collective")
    )
    shares = {}
    if phase_total > 0:
        for c in ("dispatch", "stage", "readback", "collective"):
            shares[c] = by_cat.get(c, 0.0) / phase_total
    return {
        "available": spans > 0,
        "spans": spans,
        "dropped": dropped,
        "time_us_by_cat": by_cat,
        "shares": shares,
        "overlap_segment_names": sorted(overlap_segments),
    }


def _snapshot_features(run_meta: dict, sidecars: List[dict]) -> dict:
    block = run_meta.get("snapshot")
    armed = isinstance(block, dict)
    agg = {"snapshots": 0, "skipped_queue_full": 0, "write_s": 0.0,
           "bytes": 0}
    for sc in sidecars:
        stats = sc["stats"]
        agg["snapshots"] += int(_num(stats.get("snapshots"), 0) or 0)
        agg["skipped_queue_full"] += int(
            _num(stats.get("skipped_queue_full"), 0) or 0
        )
        agg["write_s"] += _num(stats.get("write_s"), 0.0) or 0.0
        agg["bytes"] += int(_num(stats.get("bytes"), 0) or 0)
    return {
        "armed": armed,
        "config": dict(block) if armed else None,
        "sidecars": len(sidecars),
        "writer": agg if sidecars else None,
    }


def _serving_features(records: List[dict]) -> dict:
    windows = [r for r in records if r.get("type") == "serving_stats"]
    if not windows:
        return {"available": False}
    return {
        "available": True,
        "windows": len(windows),
        "occupancy_mean": _mean(r.get("batch_occupancy") for r in windows),
        "queue_ms_p50_mean": _mean(r.get("queue_ms_p50") for r in windows),
        "device_ms_p50_mean": _mean(r.get("device_ms_p50") for r in windows),
        "e2e_ms_p50_mean": _mean(r.get("e2e_ms_p50") for r in windows),
        "throughput_rps_mean": _mean(
            r.get("throughput_rps") for r in windows
        ),
        "shed_total": sum(
            int(v) for v in (_num(r.get("shed")) for r in windows) if v
        ),
        "rejected_total": sum(
            int(v) for v in (_num(r.get("rejected")) for r in windows) if v
        ),
    }


def _decode_features(records: List[dict]) -> dict:
    windows = [r for r in records if r.get("type") == "decode_stats"]
    if not windows:
        return {"available": False}
    return {
        "available": True,
        "windows": len(windows),
        "tokens_per_sec_mean": _mean(
            r.get("tokens_per_sec") for r in windows
        ),
        "ttft_ms_p50_mean": _mean(r.get("ttft_ms_p50") for r in windows),
        "itl_ms_p50_mean": _mean(r.get("itl_ms_p50") for r in windows),
        "itl_ms_p95_mean": _mean(r.get("itl_ms_p95") for r in windows),
        "kv_occupancy_mean": _mean(r.get("kv_occupancy") for r in windows),
        "shed_total": sum(
            int(v) for v in (_num(r.get("shed")) for r in windows) if v
        ),
    }


def extract_evidence(run: dict) -> dict:
    """Distill loaded artifacts into the typed feature dict the rule table
    consumes. Every feature group is present (possibly with ``available:
    False`` / None members) so rules index safely."""
    run_meta = run["run_meta"]
    records = run["records"]
    comm_block = run_meta.get("comm") if isinstance(
        run_meta.get("comm"), dict
    ) else None
    return {
        "run_dir": run["run_dir"],
        "run_meta": {
            "present": bool(run_meta),
            "world_size": _num(run_meta.get("world_size")),
            "process_count": _num(run_meta.get("process_count")),
            "comm_hook": run_meta.get("comm_hook"),
            "comm_topology": run_meta.get("comm_topology"),
            "pipeline": run_meta.get("pipeline") if isinstance(
                run_meta.get("pipeline"), dict
            ) else None,
            "scan_steps": run_meta.get("scan_steps"),
            "overlap": (comm_block or {}).get("overlap"),
            "grad_comm_bytes_per_update": _num(
                run_meta.get("grad_comm_bytes_per_update")
            ),
            "grad_comm_bytes_per_update_f32": _num(
                run_meta.get("grad_comm_bytes_per_update_f32")
            ),
            "grad_comm_bytes_inter_host": _num(
                run_meta.get("grad_comm_bytes_inter_host")
            ),
            "grad_comm_bytes_intra_host": _num(
                run_meta.get("grad_comm_bytes_intra_host")
            ),
            "tuning": run_meta.get("tuning"),
        },
        "train": _epoch_features(records),
        "spans": _span_features(run["traces"]),
        "snapshot": _snapshot_features(run_meta, run["writer_sidecars"]),
        "serving": _serving_features(records),
        "decode": _decode_features(records),
    }


# -------------------------------------------------------------- rule table --


def _rec(rule, rule_class, section, knob, diff, metric, predicted, reason,
         evidence):
    """``predicted_delta_pct`` is a predicted IMPROVEMENT on ``metric``,
    always positive-is-better: for lower-better metrics (latencies, wire
    bytes, sheds) it is the predicted reduction. tpuddp/tune/probe.py
    measures deltas under the same convention, so predicted and measured
    columns compare directly."""
    return {
        "rule": rule,
        "rule_class": rule_class,
        "section": section,
        "knob": knob,
        "diff": diff,
        "metric": metric,
        "predicted_delta_pct": round(float(predicted), 2),
        "reason": reason,
        "evidence": evidence,
    }


def _rule_pipeline_sync(ev):
    """pipeline:false (the synchronous A/B reference) left in production:
    every dispatch blocks on its own readback. Predicted win = the measured
    host-stall share of epoch wall time (the time the device sat idle
    waiting on the host), floored at 2% when stall accounting is absent."""
    pipe = ev["run_meta"]["pipeline"]
    if not pipe:
        return None
    sync = bool(pipe.get("sync_readback")) or (
        int(_num(pipe.get("depth"), 2) or 2) <= 1
        and int(_num(pipe.get("host_workers"), 2) or 2) == 0
    )
    if not sync:
        return None
    stall = ev["train"]["host_stall_share"]
    predicted = max((stall or 0.0) * 100.0, 2.0)
    evidence = [cite("history.jsonl#run_meta", "pipeline", pipe)]
    if stall is not None:
        evidence.append(cite(
            "history.jsonl#epoch", "host_stall_share", round(stall, 4)
        ))
    return _rec(
        "pipeline_sync_readback", "pipeline", "training", "pipeline",
        {"pipeline": True}, "samples_per_sec", predicted,
        "synchronous readback pipeline (depth 1, no host workers) — enable "
        "the staged async pipeline to overlap host assembly with device "
        "compute",
        evidence,
    )


def _rule_pipeline_stall_depth(ev):
    """Pipeline is on but the device still stalls on the host: the staged
    lookahead is too shallow (or too few loader workers). Deepen both;
    predicted win = half the stall share (lookahead hides latency, it does
    not create host bandwidth)."""
    pipe = ev["run_meta"]["pipeline"]
    stall = ev["train"]["host_stall_share"]
    if not pipe or bool(pipe.get("sync_readback")):
        return None
    if stall is None or stall <= HOST_STALL_SHARE_THRESHOLD:
        return None
    depth = int(_num(pipe.get("depth"), 2) or 2)
    workers = int(_num(pipe.get("host_workers"), 2) or 2)
    return _rec(
        "pipeline_host_stall_depth", "pipeline", "training", "pipeline",
        {"pipeline": {"depth": depth * 2,
                      "host_workers": max(workers * 2, 4)}},
        "samples_per_sec", stall * 100.0 / 2.0,
        f"host stall is {stall:.0%} of epoch wall time with the async "
        "pipeline already on — deepen the staged lookahead and host workers",
        [
            cite("history.jsonl#epoch", "host_stall_share", round(stall, 4)),
            cite("history.jsonl#run_meta", "pipeline.depth", depth),
            cite("history.jsonl#run_meta", "pipeline.host_workers", workers),
        ],
    )


def _rule_span_readback(ev):
    """Trace evidence: readback spans dominate the traced step phases —
    the dispatch cursor is draining results too eagerly. Deepen the staged
    chunk lookahead so readbacks ride behind more dispatched work."""
    spans = ev["spans"]
    if not spans.get("available"):
        return "insufficient_evidence"
    share = (spans.get("shares") or {}).get("readback")
    if share is None or share <= READBACK_SHARE_THRESHOLD:
        return None
    pipe = ev["run_meta"]["pipeline"] or {}
    depth = int(_num(pipe.get("depth"), 2) or 2)
    return _rec(
        "span_readback_share", "pipeline", "training", "pipeline",
        {"pipeline": {"depth": depth + 2}},
        "step_time_ms_p50", share * 100.0 / 2.0,
        f"readback spans are {share:.0%} of traced step time — deepen the "
        "staged lookahead so result drains overlap later dispatches",
        [cite("trace_*.json", "shares.readback", round(share, 4))],
    )


def _rule_span_dispatch(ev):
    """Trace evidence: per-step dispatch overhead dominates — fuse more
    steps into one compiled scan so the host pays the dispatch cost once
    per scan window instead of once per step."""
    spans = ev["spans"]
    if not spans.get("available"):
        return "insufficient_evidence"
    share = (spans.get("shares") or {}).get("dispatch")
    if share is None or share <= DISPATCH_SHARE_THRESHOLD:
        return None
    scan = ev["run_meta"]["scan_steps"]
    current = int(scan) if isinstance(scan, (int, float)) else 1
    return _rec(
        "span_dispatch_share", "pipeline", "training", "scan_steps",
        {"scan_steps": max(current * 4, 8)},
        "step_time_ms_p50", share * 100.0 / 2.0,
        f"dispatch spans are {share:.0%} of traced step time — widen the "
        "compiled scan window to amortize per-step dispatch",
        [
            cite("trace_*.json", "shares.dispatch", round(share, 4)),
            cite("history.jsonl#run_meta", "scan_steps", scan),
        ],
    )


def _rule_comm_uncompressed(ev):
    """Gradients cross the wire uncompressed in a multi-chip world. bf16
    with error feedback halves the wire bytes at (empirically) neutral
    convergence — the DynamiQ-style first rung of the compression ladder."""
    rm = ev["run_meta"]
    world = rm["world_size"]
    per_update = rm["grad_comm_bytes_per_update"]
    if rm["comm_hook"] not in (None, "none"):
        return None
    if not world or world <= 1:
        return None
    if not per_update or per_update < COMM_BYTES_FLOOR:
        return None
    return _rec(
        "comm_hook_uncompressed", "comm", "training", "comm_hook",
        {"comm_hook": "bf16_ef"}, "grad_comm_bytes", 50.0,
        f"{int(per_update)} gradient bytes/update cross the interconnect "
        "uncompressed — bf16 error-feedback compression halves the wire "
        "bytes",
        [
            cite("history.jsonl#run_meta", "comm_hook", rm["comm_hook"]),
            cite("history.jsonl#run_meta", "grad_comm_bytes_per_update",
                 int(per_update)),
            cite("history.jsonl#run_meta", "world_size", int(world)),
        ],
    )


def _rule_comm_topology(ev):
    """Multi-host job reducing over a flat topology: every gradient byte
    crosses the slow inter-host wire world_size-wide. Hierarchical reduction
    (intra-host first) cuts inter-host bytes to ~1/local_world of flat."""
    rm = ev["run_meta"]
    procs = rm["process_count"]
    inter = rm["grad_comm_bytes_inter_host"]
    if rm["comm_topology"] != "flat" or not procs or procs <= 1:
        return None
    if not inter or inter <= 0:
        return None
    world = rm["world_size"] or procs
    local = max(int(world // procs), 1)
    predicted = (1.0 - 1.0 / local) * 100.0 if local > 1 else 50.0
    return _rec(
        "comm_topology_flat_multihost", "comm", "training", "comm_topology",
        {"comm_topology": "hierarchical"}, "grad_comm_bytes_inter_host",
        predicted,
        f"{procs} hosts reduce over a flat topology — hierarchical "
        "reduction drains intra-host first and sends one local-reduced "
        "shard across the inter-host wire",
        [
            cite("history.jsonl#run_meta", "comm_topology",
                 rm["comm_topology"]),
            cite("history.jsonl#run_meta", "process_count", int(procs)),
            cite("history.jsonl#run_meta", "grad_comm_bytes_inter_host",
                 int(inter)),
        ],
    )


def _rule_comm_overlap_off(ev):
    """The gradient exchange ran as one trailing barrier although the world
    is multi-chip: segmented-backward overlap interleaves bucket collectives
    with backward compute (run_meta.comm.overlap records enabled: false)."""
    rm = ev["run_meta"]
    overlap = rm["overlap"]
    world = rm["world_size"]
    if not isinstance(overlap, dict) or overlap.get("enabled"):
        return None
    if not world or world <= 1:
        return None
    return _rec(
        "comm_overlap_disabled", "comm", "training", "comm_overlap",
        {"comm_overlap": True}, "step_time_ms_p50", 5.0,
        "gradient exchange ran as a single trailing barrier — segmented "
        "backward overlap hides bucket collectives behind backward compute",
        [
            cite("history.jsonl#run_meta", "comm.overlap", overlap),
            cite("history.jsonl#run_meta", "world_size", int(world)),
        ],
    )


def _rule_snapshot_backlog(ev):
    """The async snapshot writer dropped cadence points because its inflight
    queue was full (sidecar skipped_queue_full > 0): the durability contract
    is silently thinner than configured. Double the inflight budget."""
    snap = ev["snapshot"]
    writer = snap.get("writer")
    if not snap["armed"] or not writer:
        return None
    skipped = writer.get("skipped_queue_full", 0)
    if skipped <= 0:
        return None
    inflight = int(_num((snap["config"] or {}).get("inflight"), 1) or 1)
    return _rec(
        "snapshot_writer_backlog", "snapshot", "training", "snapshot",
        {"snapshot": {"inflight": max(inflight * 2, 2)}},
        "snapshot_skipped_queue_full", 100.0,
        f"writer skipped {skipped} snapshot(s) on a full inflight queue — "
        "double the inflight budget so cadence points are not dropped",
        [
            cite("*.writer.json", "skipped_queue_full", int(skipped)),
            cite("history.jsonl#run_meta", "snapshot.inflight", inflight),
        ],
    )


def _rule_snapshot_cadence(ev):
    """Snapshotting every step (or two): the writer serializes the whole
    model state at step cadence, which even async dispatch cannot make free.
    Back the cadence off; predicted win = the measured write-seconds share
    of epoch wall time (floored — toy runs measure tiny absolute writes)."""
    snap = ev["snapshot"]
    if not snap["armed"]:
        return None
    cfg = snap["config"] or {}
    every = int(_num(cfg.get("every_steps"), 0) or 0)
    if every <= 0 or every > SNAPSHOT_HOT_EVERY_STEPS:
        return None
    writer = snap.get("writer") or {}
    write_s = _num(writer.get("write_s"), 0.0) or 0.0
    total = ev["train"]["epoch_time_s_total"]
    share = write_s / total if total else 0.0
    evidence = [
        cite("history.jsonl#run_meta", "snapshot.every_steps", every),
    ]
    if writer:
        evidence.append(cite("*.writer.json", "write_s", round(write_s, 3)))
        evidence.append(cite("*.writer.json", "snapshots",
                             writer.get("snapshots")))
    return _rec(
        "snapshot_cadence_hot", "snapshot", "training", "snapshot",
        {"snapshot": {"every_steps": max(every * 8, 16)}},
        "samples_per_sec",
        max(share * 100.0, SNAPSHOT_WRITE_SHARE_FLOOR * 100.0),
        f"step snapshots every {every} step(s) serialize model state at "
        "near-step cadence — back off the cadence; mid-epoch resume only "
        "needs bounded replay, not per-step durability",
        evidence,
    )


def _rule_serving_linger(ev):
    """Serving batches leave mostly empty while requests wait in queue:
    the batch window (batch_timeout_ms) lingers for fill that never comes.
    Shorten it; predicted win = the queue share of end-to-end latency."""
    srv = ev["serving"]
    if not srv.get("available"):
        return None
    occ = srv.get("occupancy_mean")
    queue = srv.get("queue_ms_p50_mean")
    device = srv.get("device_ms_p50_mean")
    e2e = srv.get("e2e_ms_p50_mean")
    if occ is None or occ >= OCCUPANCY_FLOOR:
        return None
    if queue is None or device is None or queue <= device:
        return None
    share = queue / e2e if e2e else 0.5
    return _rec(
        "serving_low_occupancy_linger", "serving", "serving",
        "batch_timeout_ms", {"batch_timeout_ms": 1}, "e2e_ms_p50",
        min(share, 0.9) * 100.0,
        f"batch occupancy {occ:.0%} with queue wait ({queue:.1f} ms p50) "
        f"above device time ({device:.1f} ms p50) — the batch window "
        "lingers for fill that never arrives; dispatch eagerly",
        [
            cite("history.jsonl#serving_stats", "batch_occupancy_mean",
                 round(occ, 3)),
            cite("history.jsonl#serving_stats", "queue_ms_p50_mean",
                 round(queue, 2)),
            cite("history.jsonl#serving_stats", "device_ms_p50_mean",
                 round(device, 2)),
        ],
    )


def _rule_serving_shed(ev):
    """The survivability layer shed deadline-expired requests: admission
    capacity is below arrival rate. Deepen the admission queue so bursts
    wait instead of dying (sustained overload needs replicas, not queue —
    the reason lands in the recommendation text)."""
    srv = ev["serving"]
    if not srv.get("available"):
        return None
    shed = srv.get("shed_total", 0)
    if shed <= 0:
        return None
    return _rec(
        "serving_shed_pressure", "serving", "serving", "max_queue_depth",
        {"max_queue_depth": 128}, "shed", 100.0,
        f"{shed} request(s) shed at the deadline — deepen the admission "
        "queue to absorb bursts (if shed persists at depth, the fix is "
        "replicas, not queue)",
        [cite("history.jsonl#serving_stats", "shed_total", int(shed))],
    )


def _rule_decode_kv_pressure(ev):
    """Decode KV pool runs near-full and tail inter-token latency detaches
    from the median: too many concurrent sequences thrash the pool. Fewer
    slots trade admission concurrency for stable ITL."""
    dec = ev["decode"]
    if not dec.get("available"):
        return None
    kv = dec.get("kv_occupancy_mean")
    p50 = dec.get("itl_ms_p50_mean")
    p95 = dec.get("itl_ms_p95_mean")
    if kv is None or kv <= KV_PRESSURE_THRESHOLD:
        return None
    if p50 is None or p95 is None or p95 <= 2.0 * p50:
        return None
    return _rec(
        "decode_kv_pressure", "serving", "decode", "max_slots",
        {"max_slots": 0.75}, "itl_ms_p95", 25.0,
        f"KV occupancy {kv:.0%} with ITL p95 ({p95:.1f} ms) detached from "
        f"p50 ({p50:.1f} ms) — shrink max_slots ~25% so resident sequences "
        "stop thrashing the pool",
        [
            cite("history.jsonl#decode_stats", "kv_occupancy_mean",
                 round(kv, 3)),
            cite("history.jsonl#decode_stats", "itl_ms_p95_mean",
                 round(p95, 2)),
            cite("history.jsonl#decode_stats", "itl_ms_p50_mean",
                 round(p50, 2)),
        ],
    )


# (rule id, rule class, needs) → fn(evidence) -> recommendation | None |
# "insufficient_evidence". ``needs`` names the artifact family the rule
# cannot run without; history-only rules keep firing on a trace-less run.
RULES = (
    ("pipeline_sync_readback", "pipeline", "history", _rule_pipeline_sync),
    ("pipeline_host_stall_depth", "pipeline", "history",
     _rule_pipeline_stall_depth),
    ("span_readback_share", "pipeline", "trace", _rule_span_readback),
    ("span_dispatch_share", "pipeline", "trace", _rule_span_dispatch),
    ("comm_hook_uncompressed", "comm", "history", _rule_comm_uncompressed),
    ("comm_topology_flat_multihost", "comm", "history", _rule_comm_topology),
    ("comm_overlap_disabled", "comm", "history", _rule_comm_overlap_off),
    ("snapshot_writer_backlog", "snapshot", "history", _rule_snapshot_backlog),
    ("snapshot_cadence_hot", "snapshot", "history", _rule_snapshot_cadence),
    ("serving_low_occupancy_linger", "serving", "history",
     _rule_serving_linger),
    ("serving_shed_pressure", "serving", "history", _rule_serving_shed),
    ("decode_kv_pressure", "serving", "history", _rule_decode_kv_pressure),
)


def advise(run_dir: str) -> dict:
    """Run the full rule table over a run directory. Returns::

        {
          "run_dir": ...,
          "evidence": <extract_evidence features>,
          "recommendations": [rec, ...],   # typed diffs, best-first
          "insufficient": [{rule, rule_class, needs, reason}, ...],
        }

    Span-needing rules land in ``insufficient`` (not silence) when no trace
    artifact exists — the reader can tell "evidence said no" from "evidence
    was never collected"."""
    run = load_run(run_dir)
    ev = extract_evidence(run)
    recommendations = []
    insufficient = []
    for rule_id, rule_class, needs, fn in RULES:
        try:
            out = fn(ev)
        except Exception as e:  # noqa: BLE001 — one bad rule must not
            insufficient.append({       # take the advisor down
                "rule": rule_id, "rule_class": rule_class, "needs": needs,
                "reason": f"rule error: {e}",
            })
            continue
        if out == "insufficient_evidence":
            insufficient.append({
                "rule": rule_id, "rule_class": rule_class, "needs": needs,
                "reason": "insufficient_evidence: no trace artifact in "
                          "this run dir (tracing was off or predates v9)",
            })
        elif out is not None:
            recommendations.append(out)
    recommendations.sort(
        key=lambda r: r["predicted_delta_pct"], reverse=True
    )
    return {
        "run_dir": run_dir,
        "evidence": ev,
        "recommendations": recommendations,
        "insufficient": insufficient,
    }


def overlay_from(recommendations: List[dict]) -> dict:
    """Merge recommendation diffs into one config overlay, sectioned the way
    settings files are (``training`` / ``serving`` / ``decode``). Dict-valued
    knobs (pipeline, snapshot) merge shallowly; a later scalar replaces —
    EXCEPT ``True`` landing on a dict: a bare enable never erases a sibling
    rule's refinement of the same knob (``pipeline: true`` after
    ``pipeline: {depth: 3}`` keeps the depth)."""
    overlay: Dict[str, dict] = {}
    for rec in recommendations:
        section = overlay.setdefault(rec.get("section") or "training", {})
        for knob, value in rec["diff"].items():
            have = section.get(knob)
            if isinstance(value, dict) and isinstance(have, dict):
                section[knob] = {**have, **value}
            elif value is True and isinstance(have, dict):
                pass  # already enabled with refinements
            else:
                section[knob] = value
    return overlay


# ------------------------------------------------------------ measurement --


def measure_run(run_dir: str, mode: str = "train") -> dict:
    """The A/B probe's metric reader: summarize a finished run into the
    flat metric dict predicted deltas are verified against. Direction
    semantics live in tpuddp/tune/probe.py (this just reports numbers)."""
    run = load_run(run_dir)
    ev = extract_evidence(run)
    metrics: Dict[str, Optional[float]] = {}
    if mode == "train":
        tr = ev["train"]
        metrics["samples_per_sec"] = tr["samples_per_sec_mean"]
        metrics["step_time_ms_p50"] = tr["step_time_ms_p50_mean"]
        metrics["epoch_time_s"] = tr["epoch_time_s_total"]
        metrics["host_stall_ms"] = tr["host_stall_ms_total"]
        writer = ev["snapshot"].get("writer") or {}
        metrics["snapshot_skipped_queue_full"] = float(
            writer.get("skipped_queue_full", 0)
        )
        metrics["snapshot_write_s"] = float(writer.get("write_s", 0.0))
        rm = ev["run_meta"]
        metrics["grad_comm_bytes"] = rm["grad_comm_bytes_per_update"]
        metrics["grad_comm_bytes_inter_host"] = rm[
            "grad_comm_bytes_inter_host"
        ]
    else:
        srv = ev["serving"]
        metrics["throughput_rps"] = srv.get("throughput_rps_mean")
        metrics["e2e_ms_p50"] = srv.get("e2e_ms_p50_mean")
        metrics["batch_occupancy"] = srv.get("occupancy_mean")
        metrics["shed"] = float(srv.get("shed_total", 0) or 0)
        dec = ev["decode"]
        if dec.get("available"):
            metrics["tokens_per_sec"] = dec.get("tokens_per_sec_mean")
            metrics["itl_ms_p95"] = dec.get("itl_ms_p95_mean")
    return {k: v for k, v in metrics.items() if v is not None}


def pending_summary(run_dir: str) -> Optional[dict]:
    """The flight recorder's ``pending_tune`` context payload: the top
    (unendorsed) recommendation the advisor would make over this run dir
    right now — dumped on preempt/exception so a crash never discards the
    evidence that was about to be acted on. None when nothing fires."""
    try:
        report = advise(run_dir)
    except Exception:  # noqa: BLE001 — crash paths must never re-crash
        return None
    recs = report["recommendations"]
    if not recs:
        return None
    top = recs[0]
    return {
        "rule": top["rule"],
        "rule_class": top["rule_class"],
        "knob": top["knob"],
        "diff": top["diff"],
        "metric": top["metric"],
        "predicted_delta_pct": top["predicted_delta_pct"],
        "endorsed": False,
        "pending_rules": [r["rule"] for r in recs],
    }


# ---------------------------------------------------------------- display --


def format_report(report: dict) -> str:
    """Human rendering for ``tpuddp_inspect tune`` — the diff, then the
    evidence table, then the rules that could not run."""
    lines = [f"advisor report for {report['run_dir']}"]
    recs = report["recommendations"]
    if not recs:
        lines.append("  no recommendations — evidence looks clean")
    for rec in recs:
        lines.append(
            f"  [{rec['rule_class']}] {rec['rule']}: "
            f"{json.dumps(rec['diff'], sort_keys=True)} "
            f"(predicted {rec['predicted_delta_pct']:+.1f}% improvement "
            f"on {rec['metric']})"
        )
        lines.append(f"      why: {rec['reason']}")
        for c in rec["evidence"]:
            lines.append(
                f"      evidence: {c['source']} :: {c['field']} = "
                f"{json.dumps(c['value'], sort_keys=True)}"
            )
    for miss in report["insufficient"]:
        lines.append(
            f"  [{miss['rule_class']}] {miss['rule']}: skipped — "
            f"{miss['reason']}"
        )
    if recs:
        lines.append(
            "  overlay: "
            + json.dumps(overlay_from(recs), sort_keys=True)
        )
    return "\n".join(lines)
