"""Typed record schema for ``history.jsonl`` (and the bench artifact).

Every line of ``history.jsonl`` is one JSON object carrying ``type`` (one of
:data:`RECORD_TYPES`) and ``schema_version``:

- ``run_meta`` — the header row, written once at loop start (and again by a
  resumed run appending to an existing file): mesh shape, process/replica
  counts, jax/tpuddp versions, config hash, comm-hook mode, guard config.
- ``epoch``    — one row per completed epoch: losses/accuracy/throughput plus
  step-time percentiles and achieved-MFU fields from the step recorder.
- ``step_stats`` — one row per recorder window (``training.step_stats_every``
  steps) inside an epoch: the intra-epoch resolution that makes a 10x
  step-time regression or a straggler *within* an epoch visible.
- ``event``    — discrete occurrences: rollback, desync, preempt, skipped
  updates, watchdog staleness, profiler captures, serving drain.
- ``serving_stats`` — one row per serving-engine reporting window
  (tpuddp/serving/stats.py): request/completion/reject counts, queue /
  device / end-to-end latency percentiles, throughput, and batch occupancy
  — the SLO record stream of the inference engine.

``tools/tpuddp_inspect.py --validate`` enforces this schema, so drift fails
a gate instead of corrupting downstream consumers. The validators live here
(not in the tool) so writer tests and the CLI share one definition.

Version history: v1 introduced the envelope and the four training record
types; v2 added ``serving_stats``; v3 added the async-pipeline occupancy
fields to ``step_stats`` (``host_stall_ms``, ``inflight_depth``,
``staging_queue_depth`` — tpuddp/training/pipeline.py); v4 added
``comm_topology`` to ``run_meta`` (the comm-compression-v2 topology knob —
flat vs hierarchical multi-hop reduction, parallel/comm.py; the header also
gained the non-required ``comm_density`` / ``grad_comm_bytes_inter_host`` /
``grad_comm_bytes_intra_host`` accounting fields); v5 added the live
telemetry plane's ``observability`` header field (exporter endpoint /
pod-aggregation / flight-recorder provenance — a reader of a v5 history can
tell whether a missing ``straggler`` event means "no straggler" or
"aggregation was off") plus the ``straggler`` typed event and the
``flight_recording`` sidecar artifact (``flightrec_<reason>.json``,
:func:`validate_flight_payload`); v6 added the ``decode_stats`` record (the
autoregressive decode engine's token-level SLO window —
tpuddp/serving/decode/: tokens/sec, time-to-first-token, inter-token
latency percentiles, KV-cache occupancy) and the required run_meta
``decode`` provenance field (null = not a decode run; a decode header
carries the KV-pool geometry, so a reader can tell "no decode windows"
from "this was never a decode engine"); v7 added the serving
survivability layer's accounting (tpuddp/serving/survive.py): the required
run_meta ``survivability`` provenance field (null = not a serving writer;
a serving header carries the TTL / probation / retry-budget knobs), the
required ``shed`` field on ``serving_stats`` and ``decode_stats`` windows
(deadline-expired requests dropped before dispatch) and the required
``failovers`` field on ``decode_stats`` (sessions migrated off a dead
replica), plus the typed ``session_failover`` / ``replica_recovered`` /
``replica_removed`` / ``no_healthy_replica`` event rows; v8 added the
required run_meta ``mesh`` block (the 2-D device-mesh provenance,
tpuddp/parallel/mesh2d.py): ``data``/``model`` axis widths plus the
``tp_rules_hash`` of the tensor-parallel rule table when ``model > 1`` —
a reader of a v8 header can tell a 4-chip pure-DP run from a TP=2xDP=2
run without parsing mesh_shape, and two TP runs sharded under different
rule tables never read as the same configuration. Null for writers with
no mesh (serving headers), but the KEY must exist — absence is drift;
v9 added the causal tracing plane (tpuddp/observability/trace.py): the
required run_meta ``tracing`` provenance field (null = tracing off — a
reader must distinguish "no spans because tracing was off" from
"predates the tracing plane"), the ``trace_summary`` record type (span
and drop accounting plus the slowest-span table, written once at drain
by every traced writer), and the ``trace_<role>.json`` sidecar artifact
(a Chrome-trace-event file with a ``tpuddp`` provenance block,
:func:`validate_trace_payload` — loadable in Perfetto as-is);
v10 added the required run_meta ``comm`` block (the gradient-exchange
execution provenance, training/step.py ``comm_overlap``): its
``overlap`` member records whether the step ran segmented-backward
({enabled, segments} — the bucket-aligned backward segments whose
collectives interleave with backward compute) or the barrier step and
why. Null for writers with no gradient exchange (serving headers), but
the KEY must exist — a reader must distinguish "barrier because overlap
resolved off" from "predates the overlap mode";
v11 added the required run_meta ``snapshot`` field (the async
step-checkpoint engine, training/snapshot.py): an armed block carries
the resolved config (``every_steps``/``async``/``inflight``/
``peer_redundancy``) plus the writer's identity (prefix, process
index), so a reader of a resumed history can tell which snapshot
cadence produced the checkpoint family it restored from. ``false`` =
the engine was off (epoch-granular checkpoints only); the KEY must
exist — absence is drift, and a reader must distinguish "no step
snapshots because the engine was off" from "predates the engine";
v12 added the autotuning plane (tpuddp/observability/advisor.py +
tpuddp/tune/): the required run_meta ``tuning`` provenance field (null =
advisor off — a tuned-off run must be bitwise-identical to a pre-v12
run; an armed block names the overlay source, rule and generation that
produced the knobs this run trained under), the ``tune_report`` record
type (the ``TUNE_r*.json`` A/B probe artifact: per-rule predicted vs
measured deltas + endorsement verdicts, :func:`validate_tune_payload`)
and the typed ``tune_action`` event rows the fleet tuner appends when it
applies or reverts a knob change through drain-and-relaunch.
Readers accept every version up to their own ``SCHEMA_VERSION`` and
reject newer files; the per-version required-field sets apply at the
version each record CARRIES, so a v2 history (no occupancy fields) stays
valid under a v5 reader.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA_VERSION = 12

RECORD_TYPES = (
    "run_meta", "epoch", "step_stats", "event", "serving_stats",
    "decode_stats", "trace_summary", "tune_report",
)

# Required keys per record type (beyond the envelope's type/schema_version).
# Values may be null where a metric can legitimately blow up (strict-JSON
# post-mortem rows) or be unknowable (MFU without a known chip peak).
_REQUIRED = {
    "run_meta": (
        "jax_version",
        "tpuddp_version",
        "world_size",
        "process_count",
        "process_index",
        "mesh_shape",
        "comm_hook",
        "guard",
    ),
    "epoch": (
        "epoch",
        "train_loss",
        "test_loss",
        "test_accuracy",
        "train_samples",
        "test_samples",
        "epoch_time_s",
        "samples_per_sec",
        "step_time_ms_p50",
        "step_time_ms_p95",
        "step_time_ms_p99",
        "step_time_ms_max",
        "mfu_p50",
    ),
    "step_stats": (
        "epoch",
        "step_start",
        "steps",
        "step_time_ms_p50",
        "step_time_ms_p95",
        "step_time_ms_p99",
        "step_time_ms_max",
        "samples_per_sec",
    ),
    "event": ("event",),
    "serving_stats": (
        "window",
        "requests",
        "completed",
        "rejected",
        "queue_ms_p50",
        "device_ms_p50",
        "e2e_ms_p50",
        "e2e_ms_p95",
        "e2e_ms_p99",
        "throughput_rps",
        "batch_occupancy",
    ),
    # one row per decode-engine reporting window (tpuddp/serving/decode/):
    # token-granularity throughput + the two latencies token traffic lives
    # by (TTFT, ITL) + the KV-pool pressure gauge. Percentiles may be null
    # in a window that completed zero tokens of its kind (e.g. a drain
    # flush), never absent.
    "decode_stats": (
        "window",
        "tokens",
        "completed",
        "rejected",
        "tokens_per_sec",
        "ttft_ms_p50",
        "ttft_ms_p95",
        "itl_ms_p50",
        "itl_ms_p95",
        "itl_ms_p99",
        "kv_occupancy",
        "active_sequences",
    ),
    # the tracing plane's drain digest (schema v9, observability/trace.py):
    # one row per traced writer — completed-span count, ring drops (the
    # honesty field: a reader knows whether the artifact is the WHOLE run
    # or the newest window of it), still-open spans at drain, per-kind
    # counts, and the slowest-span table.
    "trace_summary": (
        "role",
        "spans",
        "dropped",
        "open_spans",
        "by_kind",
        "slowest",
    ),
    # the autotuner's A/B probe artifact (schema v12, tools/autotune.py +
    # tpuddp/tune/probe.py): ONE JSON object — baseline metrics plus one
    # row per advisor rule carrying the predicted delta it promised, the
    # measured delta the probe observed, and the endorsement verdict. The
    # measured field is the honesty contract: a rule whose measured delta
    # regresses MUST carry endorsed=false, so the fleet tuner never acts
    # on a prediction that failed its own A/B.
    "tune_report": (
        "device",
        "mode",
        "baseline_metrics",
        "results",
    ),
}

# Fields additionally required of records stamped at schema_version >= N:
# applied at the version a record CARRIES (older histories keep validating
# under newer readers). v3: the async pipeline's occupancy accounting.
# v4: the gradient-reduction topology knob in the header (comm compression
# v2 — a run_meta without it cannot say which wire its comm bytes crossed).
_REQUIRED_SINCE = {
    3: {
        "step_stats": (
            "host_stall_ms",
            "inflight_depth",
            "staging_queue_depth",
        ),
    },
    4: {
        "run_meta": ("comm_topology",),
    },
    # v5: the live telemetry plane's provenance. The value may be null (a
    # writer with the whole plane off) but the KEY must exist — absence is
    # drift, and downstream consumers need to distinguish "no straggler
    # events because all hosts were uniform" from "aggregation never ran".
    5: {
        "run_meta": ("observability",),
    },
    # v6: the decode engine's provenance. Null for every non-decode writer
    # (training, request-granularity serving), but the KEY must exist — a
    # reader needs to distinguish "no decode_stats windows because nothing
    # decoded" from "this header predates the decode subsystem".
    6: {
        "run_meta": ("decode",),
    },
    # v7: the serving survivability layer (tpuddp/serving/survive.py).
    # run_meta.survivability is null for non-serving writers but the KEY
    # must exist (a reader must tell "no sheds because the layer was off"
    # from "predates the layer"); serving/decode windows carry their shed
    # counts and decode windows their session-failover counts, so the
    # autoscaler's shed-rate rule and the chaos gate read typed records,
    # not log lines.
    7: {
        "run_meta": ("survivability",),
        "serving_stats": ("shed",),
        "decode_stats": ("shed", "failovers"),
    },
    # v8: the 2-D device-mesh provenance (tpuddp/parallel/mesh2d.py). The
    # value may be null (a writer with no mesh — serving headers) but the
    # KEY must exist: a reader needs to distinguish "pure DP" (model=1)
    # from "predates the 2-D mesh", and a model>1 block carries the
    # tp_rules_hash naming the rule table that sharded the run.
    8: {
        "run_meta": ("mesh",),
    },
    # v9: the causal tracing plane (observability/trace.py). Null for every
    # untraced writer (the default — tracing is opt-in) but the KEY must
    # exist: a reader needs to distinguish "no trace artifact because
    # tracing was off" from "this header predates the tracing plane"; an
    # armed block names the ring capacity and the artifact file.
    9: {
        "run_meta": ("tracing",),
    },
    # v10: the gradient-exchange execution provenance (``comm_overlap``,
    # training/step.py). Null for writers with no gradient exchange (serving
    # headers) but the KEY must exist: a reader needs to distinguish
    # "barrier step because overlap resolved off (and why)" from "this
    # header predates segmented-backward execution". An enabled block's
    # ``overlap.segments`` counts the bucket-aligned backward segments whose
    # collectives interleave with backward compute.
    10: {
        "run_meta": ("comm",),
    },
    # v11: the async step-checkpoint engine's provenance (``snapshot``,
    # training/snapshot.py). ``false`` for writers with the engine off (the
    # default — epoch-granular checkpoints only) but the KEY must exist: a
    # reader of a resumed history needs to distinguish "no step snapshots
    # because the engine was off" from "this header predates step-granular
    # checkpointing". An armed block names the cadence (every_steps), the
    # writer mode (async/inflight) and peer-redundancy placement.
    11: {
        "run_meta": ("snapshot",),
    },
    # v12: the autotuning plane's provenance (``tuning``, tpuddp/tune/).
    # Null for every untuned writer (the default — the advisor is read-only
    # until a human or the fleet tuner applies an overlay) but the KEY must
    # exist: a reader needs to distinguish "these knobs were human-chosen"
    # from "this header predates the autotuner". An armed block names the
    # overlay source (fleet/operator), the rule that proposed it, the
    # overlay generation counter, and the knob diff actually applied — so a
    # before/after pair of resumed headers is self-explaining.
    12: {
        "run_meta": ("tuning",),
    },
}

def stamp(record_type: str, record: dict) -> dict:
    """Return ``record`` wrapped in the schema envelope (type first, so the
    line is eyeball-able with ``head``)."""
    if record_type not in RECORD_TYPES:
        raise ValueError(
            f"unknown record type {record_type!r}; expected one of {RECORD_TYPES}"
        )
    return {"type": record_type, "schema_version": SCHEMA_VERSION, **record}


def config_hash(training: Optional[dict]) -> Optional[str]:
    """Stable short hash of a training-config mapping — the run_meta field
    that answers "were these two runs the same configuration?" without
    embedding the whole config in every history file."""
    if not training:
        return None
    canon = json.dumps(training, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def make_run_meta(
    *,
    mesh=None,
    world_size: Optional[int] = None,
    comm_hook: Optional[str] = None,
    comm_topology: Optional[str] = None,
    guard=None,
    observability: Optional[dict] = None,
    decode: Optional[dict] = None,
    survivability: Optional[dict] = None,
    tp_rules_hash: Optional[str] = None,
    tracing: Optional[dict] = None,
    comm: Optional[dict] = None,
    snapshot=None,
    tuning: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Build the run_meta header row from live run objects.

    ``mesh`` is a ``jax.sharding.Mesh`` (or None); ``guard`` is a
    ``GuardConfig``/dict/None; ``tp_rules_hash`` names the tensor-parallel
    rule table when the mesh carries a model axis (the v8 ``mesh`` block);
    ``extra`` carries entrypoint-level fields (config_hash, model, dataset,
    scan_steps, ...)."""
    import jax

    import tpuddp

    mesh_shape: Optional[Dict[str, int]] = None
    device_kind = None
    if mesh is not None:
        mesh_shape = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
        if world_size is None:
            world_size = int(mesh.devices.size)
        # the device actually running the step — NOT jax.devices()[0], which
        # reports whatever platform happens to be default on this host (a
        # CPU-ladder run on a TPU-attached host, or vice versa, would lie)
        device_kind = mesh.devices.flat[0].device_kind
    elif jax.devices():
        device_kind = jax.devices()[0].device_kind
    if dataclasses.is_dataclass(guard):
        guard = dataclasses.asdict(guard)
    # required since schema v8: the 2-D mesh block — data/model axis widths
    # (the hierarchical factoring folds into data) plus the TP rule-table
    # hash when the model axis is real. Null when the writer has no mesh.
    mesh_block = None
    if mesh_shape is not None:
        model_width = int(mesh_shape.get("model", 1))
        data_width = 1
        for name, size in mesh_shape.items():
            if name != "model":
                data_width *= int(size)
        mesh_block = {
            "data": data_width,
            "model": model_width,
            "tp_rules_hash": tp_rules_hash if model_width > 1 else None,
        }
    record = {
        "jax_version": jax.__version__,
        "tpuddp_version": tpuddp.__version__,
        "world_size": world_size,
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "mesh_shape": mesh_shape,
        # required since schema v8: the 2-D mesh provenance (null = no mesh)
        "mesh": mesh_block,
        "device_kind": device_kind,
        "comm_hook": comm_hook,
        # required since schema v4: which wire topology the comm bytes
        # crossed (null = no comm configured, e.g. serving headers)
        "comm_topology": comm_topology,
        "guard": guard,
        # required since schema v5: the live telemetry plane's provenance —
        # exporter endpoint (bound port), pod aggregation + straggler knobs,
        # flight recorder (null = the whole plane off, e.g. minimal headers)
        "observability": observability,
        # required since schema v6: the decode engine's provenance (model,
        # slot width, KV-pool geometry; null = not an autoregressive run)
        "decode": decode,
        # required since schema v7: the serving survivability knobs
        # (request TTL, probation bounds, retry budget; null = not a
        # serving writer — training runs have no shedding/failover story)
        "survivability": survivability,
        # required since schema v9: the causal tracing plane's provenance
        # (ring capacity + artifact name; null = tracing off, the default)
        "tracing": tracing,
        # required since schema v10: the gradient-exchange execution
        # provenance — comm.overlap records whether the step ran
        # segmented-backward ({enabled, segments}) or the barrier step and
        # why (null = no gradient exchange, e.g. serving headers)
        "comm": comm,
        # required since schema v11: the async step-checkpoint engine's
        # provenance — resolved config + writer identity when armed, False
        # when off (epoch-granular checkpoints only)
        "snapshot": False if snapshot is None else snapshot,
        # required since schema v12: the autotuning plane's provenance —
        # the overlay source/rule/generation + knob diff this run trained
        # under (null = advisor off, the run's knobs were human-chosen)
        "tuning": tuning,
    }
    if extra:
        record.update(extra)
    return stamp("run_meta", record)


# ------------------------------------------------------------- validation --


def validate_record(record, index: int = 0) -> List[str]:
    """Schema errors for one history record (empty list = valid)."""
    where = f"record {index}"
    if not isinstance(record, dict):
        return [f"{where}: not a JSON object"]
    errors = []
    rtype = record.get("type")
    if rtype not in RECORD_TYPES:
        return [f"{where}: unknown type {rtype!r} (expected one of {RECORD_TYPES})"]
    version = record.get("schema_version")
    if not isinstance(version, int) or version < 1:
        errors.append(f"{where}: schema_version {version!r} is not a positive int")
    elif version > SCHEMA_VERSION:
        errors.append(
            f"{where}: schema_version {version} is newer than this reader's "
            f"{SCHEMA_VERSION}"
        )
    required = list(_REQUIRED[rtype])
    if isinstance(version, int):
        for since, extra in _REQUIRED_SINCE.items():
            if version >= since:
                required += list(extra.get(rtype, ()))
    missing = [k for k in required if k not in record]
    if missing:
        errors.append(f"{where} ({rtype}): missing required field(s) {missing}")
    if rtype == "event" and not isinstance(record.get("event"), str):
        errors.append(f"{where} (event): 'event' must be a string")
    if rtype == "run_meta":
        shape = record.get("mesh_shape")
        if shape is not None and not isinstance(shape, dict):
            errors.append(f"{where} (run_meta): mesh_shape must be an object or null")
        if isinstance(version, int) and version >= 10 and "comm" in record:
            comm = record.get("comm")
            if comm is not None and (
                not isinstance(comm, dict) or "overlap" not in comm
            ):
                errors.append(
                    f"{where} (run_meta): comm must be null or an object "
                    "with an 'overlap' member"
                )
    return errors


def validate_history_records(records: Iterable[dict]) -> List[str]:
    """Schema errors for a whole history (empty list = valid).

    The FIRST record must be ``run_meta``; later ``run_meta`` rows are legal
    (a resumed run appends a fresh header before its epochs)."""
    errors: List[str] = []
    n = 0
    for i, record in enumerate(records):
        n += 1
        if i == 0 and (
            not isinstance(record, dict) or record.get("type") != "run_meta"
        ):
            errors.append(
                "record 0: history must start with a run_meta header row, got "
                f"type {record.get('type') if isinstance(record, dict) else record!r}"
            )
        errors.extend(validate_record(record, i))
    if n == 0:
        errors.append("empty history: no records")
    return errors


def validate_history_file(path: str) -> Tuple[List[str], int]:
    """Parse + validate a ``history.jsonl`` file. Returns (errors, n_records).
    Non-strict JSON (bare NaN/Infinity tokens) is itself a schema error."""

    def _reject(token):
        raise ValueError(f"non-strict JSON token {token}")

    errors: List[str] = []
    records = []
    try:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    records.append(json.loads(line, parse_constant=_reject))
                except ValueError as e:
                    errors.append(f"line {lineno}: invalid JSON ({e})")
    except OSError as e:
        return [f"cannot read {path}: {e}"], 0
    errors.extend(validate_history_records(records))
    return errors, len(records)


# Bench artifact (bench_results.json) — a single JSON object, not JSONL.
_BENCH_REQUIRED = ("metric", "value", "unit", "vs_baseline", "device", "configs")
_BENCH_ROW_REQUIRED = ("ms_per_step",)
# every row must carry one RATE: samples/sec/chip (training + request
# serving) or tokens/sec (autoregressive decode curves, loadgen --decode)
_BENCH_ROW_RATES = ("samples_per_sec_per_chip", "tokens_per_sec")


def validate_bench_payload(payload) -> List[str]:
    """Schema errors for a ``bench_results.json`` payload (empty = valid)."""
    if not isinstance(payload, dict):
        return ["bench payload is not a JSON object"]
    errors = [f"missing field {k!r}" for k in _BENCH_REQUIRED if k not in payload]
    configs = payload.get("configs")
    if not isinstance(configs, dict):
        errors.append("'configs' must be an object of name -> row")
        return errors
    for name, row in configs.items():
        if not isinstance(row, dict):
            errors.append(f"config {name!r}: not an object")
            continue
        missing = [k for k in _BENCH_ROW_REQUIRED if k not in row]
        if missing:
            errors.append(f"config {name!r}: missing field(s) {missing}")
        if not any(k in row for k in _BENCH_ROW_RATES):
            errors.append(
                f"config {name!r}: needs one of {_BENCH_ROW_RATES}"
            )
    return errors


def validate_bench_file(path: str) -> Tuple[List[str], int]:
    def _reject(token):
        raise ValueError(f"non-strict JSON token {token}")

    try:
        with open(path) as f:
            payload = json.load(f, parse_constant=_reject)
    except (OSError, ValueError) as e:
        return [f"cannot parse {path}: {e}"], 0
    errors = validate_bench_payload(payload)
    n = len(payload.get("configs", {})) if isinstance(payload, dict) else 0
    return errors, n


# Flight recording (flightrec_<reason>.json) — the crash post-mortem sidecar
# dumped by tpuddp/observability/flight.py on abnormal exit paths. ONE JSON
# object: envelope fields plus per-category rings of ordinary history
# records, so every ring entry validates with the same per-record rules the
# history stream uses.
FLIGHT_TYPE = "flight_recording"
FLIGHT_REASONS = (
    "preempt",          # SIGTERM/SIGINT drain (exit 75)
    "preempt_forced",   # drain blew the grace window; failsafe forced exit 75
    "watchdog",         # a peer's heartbeat went stale (exit 76)
    "desync",           # the guard's auditor found a divergent replica (77)
    "exception",        # unhandled exception in an epoch driver
    "serving_dispatch", # the serving engine lost its last healthy replica
)
_FLIGHT_REQUIRED = (
    "reason",
    "process_index",
    "capacity",
    "counts",
    "records",
)
_FLIGHT_RINGS = ("step_stats", "event", "epoch", "serving_stats", "decode_stats")


def validate_flight_payload(payload) -> List[str]:
    """Schema errors for a flight-recording payload (empty = valid)."""
    if not isinstance(payload, dict):
        return ["flight payload is not a JSON object"]
    errors = []
    if payload.get("type") != FLIGHT_TYPE:
        errors.append(
            f"'type' must be {FLIGHT_TYPE!r}, got {payload.get('type')!r}"
        )
    version = payload.get("schema_version")
    if not isinstance(version, int) or version < 5:
        errors.append(
            f"schema_version {version!r} is not an int >= 5 (flight "
            "recordings were introduced at v5)"
        )
    elif version > SCHEMA_VERSION:
        errors.append(
            f"schema_version {version} is newer than this reader's "
            f"{SCHEMA_VERSION}"
        )
    errors += [f"missing field {k!r}" for k in _FLIGHT_REQUIRED if k not in payload]
    reason = payload.get("reason")
    if "reason" in payload and reason not in FLIGHT_REASONS:
        errors.append(
            f"unknown reason {reason!r}; expected one of {FLIGHT_REASONS}"
        )
    records = payload.get("records")
    if records is not None:
        if not isinstance(records, dict):
            errors.append("'records' must be an object of ring -> [records]")
        else:
            for ring in _FLIGHT_RINGS:
                entries = records.get(ring, [])
                if not isinstance(entries, list):
                    errors.append(f"ring {ring!r} is not a list")
                    continue
                for i, rec in enumerate(entries):
                    for e in validate_record(rec, i):
                        errors.append(f"ring {ring!r}: {e}")
                    if isinstance(rec, dict) and rec.get("type") != ring:
                        errors.append(
                            f"ring {ring!r} record {i}: type "
                            f"{rec.get('type')!r} does not belong in this ring"
                        )
    run_meta = payload.get("run_meta")
    if run_meta is not None:
        for e in validate_record(run_meta, 0):
            errors.append(f"run_meta: {e}")
    return errors


# Trace artifact (trace_<role>.json) — the causal tracing plane's
# Chrome-trace-event sidecar (tpuddp/observability/trace.py), loadable in
# Perfetto as-is. ONE JSON object: ``traceEvents`` (complete "X" span
# events + metadata/flow events) plus a ``tpuddp`` provenance block.
TRACE_TYPE = "trace"
_TRACE_META_REQUIRED = (
    "role",
    "process_index",
    "capacity",
    "spans",
    "dropped",
    "open_spans",
    "by_kind",
    "slowest",
    "clock_sync",
)


def validate_trace_payload(payload) -> List[str]:
    """Schema errors for a trace-artifact payload (empty = valid).

    Nesting is part of the contract: every X event's ``parent_id`` must
    resolve to a span present in the artifact — but only when the ring
    dropped nothing (``tpuddp.dropped == 0``); once the ring has evicted
    old spans, orphaned children of evicted parents are expected, not
    drift."""
    if not isinstance(payload, dict):
        return ["trace payload is not a JSON object"]
    errors = []
    events = payload.get("traceEvents")
    if not isinstance(events, list):
        errors.append("'traceEvents' must be a list")
        events = []
    meta = payload.get("tpuddp")
    if not isinstance(meta, dict):
        return errors + ["missing 'tpuddp' provenance block"]
    if meta.get("type") != TRACE_TYPE:
        errors.append(
            f"tpuddp.type must be {TRACE_TYPE!r}, got {meta.get('type')!r}"
        )
    version = meta.get("schema_version")
    if not isinstance(version, int) or version < 9:
        errors.append(
            f"tpuddp.schema_version {version!r} is not an int >= 9 (trace "
            "artifacts were introduced at v9)"
        )
    elif version > SCHEMA_VERSION:
        errors.append(
            f"tpuddp.schema_version {version} is newer than this reader's "
            f"{SCHEMA_VERSION}"
        )
    errors += [
        f"tpuddp block missing field {k!r}"
        for k in _TRACE_META_REQUIRED
        if k not in meta
    ]
    clock = meta.get("clock_sync")
    if isinstance(clock, dict):
        for k in ("unix_us", "perf_ns"):
            if not isinstance(clock.get(k), (int, float)):
                errors.append(f"clock_sync.{k} is not a number")
    span_ids = set()
    x_events = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e:
            errors.append(f"event {i}: not an object with a 'ph' field")
            continue
        if e["ph"] != "X":
            continue
        x_events.append((i, e))
        missing = [k for k in ("name", "ts", "dur", "pid", "tid") if k not in e]
        if missing:
            errors.append(f"event {i} (X): missing field(s) {missing}")
        args = e.get("args")
        if not isinstance(args, dict) or "span_id" not in args or (
            "trace_id" not in args
        ):
            errors.append(
                f"event {i} (X): args must carry span_id and trace_id"
            )
            continue
        span_ids.add(args["span_id"])
    if meta.get("dropped") == 0:
        for i, e in x_events:
            parent = (e.get("args") or {}).get("parent_id")
            if parent is not None and parent not in span_ids:
                errors.append(
                    f"event {i} (X): orphan parent_id {parent} — no such "
                    "span in the artifact (and the ring dropped nothing)"
                )
    return errors


def validate_trace_file(path: str) -> Tuple[List[str], int]:
    """Parse + validate a ``trace_<role>.json`` artifact. Returns
    ``(errors, n_span_events)``; non-strict JSON is itself an error."""

    def _reject(token):
        raise ValueError(f"non-strict JSON token {token}")

    try:
        with open(path) as f:
            payload = json.load(f, parse_constant=_reject)
    except (OSError, ValueError) as e:
        return [f"cannot parse {path}: {e}"], 0
    errors = validate_trace_payload(payload)
    n = 0
    if isinstance(payload, dict) and isinstance(payload.get("traceEvents"), list):
        n = sum(
            1 for e in payload["traceEvents"]
            if isinstance(e, dict) and e.get("ph") == "X"
        )
    return errors, n


# Tune artifact (TUNE_r*.json) — the autotuner's A/B probe report
# (schema v12, tools/autotune.py + tpuddp/tune/probe.py). ONE JSON object
# stamped ``type: tune_report``: envelope + baseline metrics + one result
# row per advisor rule probed.
TUNE_MODES = ("train", "serving")
_TUNE_ROW_REQUIRED = (
    "rule",
    "rule_class",
    "knob",
    "diff",
    "metric",
    "predicted_delta_pct",
    "measured_delta_pct",
    "endorsed",
    "evidence",
)
TUNE_RULE_CLASSES = ("pipeline", "comm", "snapshot", "serving")


def validate_tune_payload(payload) -> List[str]:
    """Schema errors for a ``TUNE_r*.json`` payload (empty = valid).

    The endorsement contract is validated, not just typed: a row whose
    ``measured_delta_pct`` is negative (a regression on its own metric)
    must not carry ``endorsed: true`` — the whole point of the artifact is
    that the fleet never applies a knob the probe watched regress."""
    if not isinstance(payload, dict):
        return ["tune payload is not a JSON object"]
    errors = []
    if payload.get("type") != "tune_report":
        errors.append(
            f"'type' must be 'tune_report', got {payload.get('type')!r}"
        )
    version = payload.get("schema_version")
    if not isinstance(version, int) or version < 12:
        errors.append(
            f"schema_version {version!r} is not an int >= 12 (tune reports "
            "were introduced at v12)"
        )
    elif version > SCHEMA_VERSION:
        errors.append(
            f"schema_version {version} is newer than this reader's "
            f"{SCHEMA_VERSION}"
        )
    errors += [
        f"missing field {k!r}"
        for k in _REQUIRED["tune_report"]
        if k not in payload
    ]
    if "mode" in payload and payload.get("mode") not in TUNE_MODES:
        errors.append(
            f"unknown mode {payload.get('mode')!r}; expected one of {TUNE_MODES}"
        )
    baseline = payload.get("baseline_metrics")
    if "baseline_metrics" in payload and not isinstance(baseline, dict):
        errors.append("'baseline_metrics' must be an object of metric -> value")
    results = payload.get("results")
    if results is None:
        return errors
    if not isinstance(results, list):
        return errors + ["'results' must be a list of rule rows"]
    for i, row in enumerate(results):
        if not isinstance(row, dict):
            errors.append(f"result {i}: not an object")
            continue
        missing = [k for k in _TUNE_ROW_REQUIRED if k not in row]
        if missing:
            errors.append(f"result {i}: missing field(s) {missing}")
        rclass = row.get("rule_class")
        if "rule_class" in row and rclass not in TUNE_RULE_CLASSES:
            errors.append(
                f"result {i}: unknown rule_class {rclass!r}; expected one "
                f"of {TUNE_RULE_CLASSES}"
            )
        if "diff" in row and not isinstance(row.get("diff"), dict):
            errors.append(f"result {i}: 'diff' must be a config-diff object")
        if "evidence" in row and not isinstance(row.get("evidence"), list):
            errors.append(f"result {i}: 'evidence' must be a list of citations")
        measured = row.get("measured_delta_pct")
        if (
            isinstance(measured, (int, float))
            and measured < 0
            and row.get("endorsed") is True
        ):
            errors.append(
                f"result {i}: endorsed=true with a regressing measured "
                f"delta ({measured:+.2f}%) — the probe must refuse"
            )
    return errors


def validate_tune_file(path: str) -> Tuple[List[str], int]:
    """Parse + validate a ``TUNE_r*.json`` artifact. Returns
    ``(errors, n_result_rows)``; non-strict JSON is itself an error."""

    def _reject(token):
        raise ValueError(f"non-strict JSON token {token}")

    try:
        with open(path) as f:
            payload = json.load(f, parse_constant=_reject)
    except (OSError, ValueError) as e:
        return [f"cannot parse {path}: {e}"], 0
    errors = validate_tune_payload(payload)
    n = 0
    if isinstance(payload, dict) and isinstance(payload.get("results"), list):
        n = len(payload["results"])
    return errors, n


def validate_flight_file(path: str) -> Tuple[List[str], int]:
    """Parse + validate a flight recording. Returns (errors, n_ring_records);
    non-strict JSON (bare NaN/Infinity) is itself a schema error."""

    def _reject(token):
        raise ValueError(f"non-strict JSON token {token}")

    try:
        with open(path) as f:
            payload = json.load(f, parse_constant=_reject)
    except (OSError, ValueError) as e:
        return [f"cannot parse {path}: {e}"], 0
    errors = validate_flight_payload(payload)
    n = 0
    if isinstance(payload, dict) and isinstance(payload.get("records"), dict):
        n = sum(
            len(v) for v in payload["records"].values() if isinstance(v, list)
        )
    return errors, n
