"""Cross-host telemetry aggregation + straggler detection.

A multi-host pod has per-host step timing (each process's
``StepStatsRecorder``) but no pod-level view: the watchdog's heartbeat files
answer only alive/dead. This module rides the SAME channel — each process's
``hb_<pid>`` file (tpuddp/resilience/watchdog.py) gains a one-line JSON
*telemetry shard* under its timestamp: the host's last-window step-time p50,
host-stall total, skipped-update count. One shared-filesystem file per host,
rewritten atomically at the per-window cadence the recorder already fences —
**zero new device fences, zero new collectives** (the DCN never carries a
telemetry message; the checkpoint dir's shared FS does).

The main process runs a :class:`PodAggregator`: every window it merges the
shards into pod-level percentiles, feeds the exporter's per-host series, and
detects stragglers — a host whose window p50 exceeds ``straggler_ratio`` x
the pod median for ``straggler_windows`` CONSECUTIVE fresh windows lands
exactly one typed ``straggler`` event row (host id, ratio, window streak) in
``history.jsonl``, and is reported again only after recovering first.

Shard reads are tolerant by contract: a peer mid-rewrite can present a torn
JSON line; the reader skips it with a warning and uses the previous view —
it never crashes the aggregator or fails the run (satellite of ISSUE 10).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

from tpuddp.observability import schema

logger = logging.getLogger("tpuddp")

# the shard fields a publisher fills from StepStatsRecorder.live_snapshot();
# everything optional but the window index (freshness cursor)
SHARD_FIELDS = (
    "window_index",
    "epoch",
    "step",
    "step_time_ms_p50",
    "host_stall_ms",
    "skipped_steps",
    "samples_per_sec",
)


def make_shard(
    live: dict, skipped_steps: int = 0, window_index: Optional[int] = None
) -> dict:
    """Build one host's telemetry shard from a recorder live snapshot.

    ``clock`` is the host's wall<->monotonic anchor (unix µs + the
    ``perf_counter_ns`` taken beside it) — the cross-host skew signal the
    tracing plane's merge workflow uses: per-host ``trace_<role>.json``
    artifacts timestamp spans through their OWN anchor, and differencing
    two hosts' shard anchors bounds the wall-clock skew between their
    timelines (tools/trace_breakdown.py --merge-host)."""
    return {
        "window_index": (
            int(window_index)
            if window_index is not None
            else int(live.get("windows_emitted") or 0)
        ),
        "epoch": live.get("epoch"),
        "step": live.get("step"),
        "step_time_ms_p50": live.get("step_time_ms_p50"),
        "host_stall_ms": live.get("host_stall_ms_total"),
        "skipped_steps": int(skipped_steps or 0),
        "samples_per_sec": live.get("samples_per_sec"),
        "t": time.time(),
        "clock": {
            "unix_us": int(time.time() * 1e6),
            "perf_ns": time.perf_counter_ns(),
        },
    }


def publish_shard(directory: str, process_id: int, shard: dict) -> None:
    """Write this host's shard through the heartbeat channel (atomic
    tmp+replace — a reader sees the old whole file or the new whole file,
    and the heartbeat timestamp rides along so publishing IS beating)."""
    # lazy: resilience.watchdog reaches back into observability for its
    # event writer — a module-level import here would be circular
    from tpuddp.resilience import watchdog as wd

    try:
        wd.write_heartbeat(directory, process_id, payload=shard)
    except OSError as e:  # shared-FS hiccup: telemetry is best-effort
        logger.warning("telemetry shard publish failed: %s", e)


def read_shard(directory: str, process_id: int) -> Optional[dict]:
    """This peer's shard, or None (no file, no payload yet, or a torn JSON
    line mid-rewrite — skipped with a warning, never an exception)."""
    from tpuddp.resilience import watchdog as wd

    return wd.read_heartbeat_payload(directory, process_id)


class PodAggregator:
    """Main-process merge of per-host telemetry shards.

    ``update()`` is called at the window cadence (the recorder's
    ``on_window`` hook) and at epoch boundaries; it is pure host-side file
    reads + arithmetic. ``writer`` is the run's MetricsWriter (straggler
    events become typed history rows); None keeps detection in-memory only
    (tests, exporters without a history)."""

    def __init__(
        self,
        directory: str,
        num_processes: int,
        writer=None,
        straggler_ratio: float = 1.5,
        straggler_windows: int = 3,
        shard_reader: Optional[Callable[[int], Optional[dict]]] = None,
    ):
        if straggler_ratio <= 1.0:
            raise ValueError(
                f"straggler_ratio must be > 1.0, got {straggler_ratio} "
                "(a host at the pod median would be a 'straggler')"
            )
        if straggler_windows < 1:
            raise ValueError(
                f"straggler_windows must be >= 1, got {straggler_windows}"
            )
        self.directory = directory
        self.num_processes = int(num_processes)
        self.writer = writer
        self.straggler_ratio = float(straggler_ratio)
        self.straggler_windows = int(straggler_windows)
        self._read = shard_reader or (
            lambda pid: read_shard(self.directory, pid)
        )
        self._last_window: Dict[int, int] = {}  # host -> freshest window seen
        self._streak: Dict[int, int] = {}  # host -> consecutive slow windows
        self._fired: set = set()  # hosts in an already-reported episode
        self.straggler_events = 0
        self.last: Optional[dict] = None

    # ------------------------------------------------------------- merge --
    def collect(self) -> Dict[int, dict]:
        shards = {}
        for pid in range(self.num_processes):
            shard = self._read(pid)
            if shard is not None:
                shards[pid] = shard
        return shards

    def update(self) -> Optional[dict]:
        """Merge the current shards; detect + record stragglers. Returns the
        merged pod view (also kept on ``self.last``), or None when no shard
        is readable yet."""
        import numpy as np

        shards = self.collect()
        p50s = {
            pid: s["step_time_ms_p50"]
            for pid, s in shards.items()
            if isinstance(s.get("step_time_ms_p50"), (int, float))
        }
        if not p50s:
            return None
        values = np.asarray(list(p50s.values()), np.float64)
        pod_median = float(np.median(values))
        merged = {
            "hosts_reporting": len(p50s),
            "pod_step_time_ms_p50": round(pod_median, 4),
            "pod_step_time_ms_max": round(float(values.max()), 4),
            "pod_step_time_ms_p95": round(float(np.percentile(values, 95)), 4),
            "pod_host_stall_ms": round(sum(
                float(s.get("host_stall_ms") or 0.0) for s in shards.values()
            ), 3),
            "pod_skipped_steps": sum(
                int(s.get("skipped_steps") or 0) for s in shards.values()
            ),
            "hosts": {
                str(pid): {
                    k: shards[pid].get(k)
                    for k in ("window_index", "epoch", "step",
                              "step_time_ms_p50", "host_stall_ms",
                              "skipped_steps", "clock")
                }
                for pid in sorted(shards)
            },
            "stragglers": [],
        }
        for pid, p50 in sorted(p50s.items()):
            win = int(shards[pid].get("window_index") or 0)
            # "fresh" = the shard's window cursor MOVED (any direction: a
            # resumed run restarts its window count below a leftover shard's
            # — a monotonic test would freeze that host's streak forever)
            fresh = win != self._last_window.get(pid)
            self._last_window[pid] = win
            ratio = (p50 / pod_median) if pod_median > 0 else 1.0
            if ratio > self.straggler_ratio:
                if fresh:
                    # only a NEW window extends the streak: a stalled shard
                    # must not convict a host on one repeated measurement
                    self._streak[pid] = self._streak.get(pid, 0) + 1
            else:
                self._streak[pid] = 0
                self._fired.discard(pid)  # recovered: a relapse re-reports
            streak = self._streak.get(pid, 0)
            if streak >= self.straggler_windows:
                merged["stragglers"].append(pid)
                if pid not in self._fired:
                    self._fired.add(pid)
                    self.straggler_events += 1
                    event = {
                        "event": "straggler",
                        "host": pid,
                        "ratio": round(ratio, 3),
                        "windows": streak,
                        "window_p50_ms": round(float(p50), 4),
                        "pod_p50_ms": round(pod_median, 4),
                        "epoch": shards[pid].get("epoch"),
                        "step": shards[pid].get("step"),
                    }
                    logger.warning(
                        "straggler: host %d window p50 %.2f ms is %.2fx the "
                        "pod median %.2f ms for %d consecutive window(s)",
                        pid, p50, ratio, pod_median, streak,
                    )
                    if self.writer is not None:
                        self.writer.write(schema.stamp("event", event))
        self.last = merged
        return merged

    # ---------------------------------------------------------- exporter --
    def export_source(self) -> Callable[[], dict]:
        """Exporter source: pod-level gauges + per-host labeled series from
        the last merge (scrapes never re-read the shard files — update()
        owns the cadence)."""
        from tpuddp.observability import exporter as exp

        def source():
            merged = self.last
            if merged is None:
                return {}
            series = {
                "pod_hosts_reporting": exp.gauge(
                    merged["hosts_reporting"], "hosts with a readable shard"
                ),
                "pod_step_time_ms": exp.summary(
                    {
                        "0.5": merged["pod_step_time_ms_p50"],
                        "0.95": merged["pod_step_time_ms_p95"],
                        "1.0": merged["pod_step_time_ms_max"],
                    },
                    "pod-level percentiles over per-host window p50s",
                ),
                "pod_stragglers": exp.gauge(
                    len(merged["stragglers"]),
                    "hosts currently past the straggler threshold",
                ),
                "pod_straggler_events_total": exp.counter(
                    self.straggler_events, "straggler episodes reported"
                ),
            }
            host_series = {"type": "gauge", "help": (
                "per-host last-window step-time p50"
            ), "values": []}
            for pid, h in merged["hosts"].items():
                host_series["values"].append(
                    ({"host": pid}, h.get("step_time_ms_p50"))
                )
            series["host_step_time_ms_p50"] = host_series
            return series

        return source
