"""Metrics sinks — the JSONL history writer and the comm-bytes counter.

``history.jsonl`` is the machine-readable record of a run (one typed JSON
record per line; see :mod:`tpuddp.observability.schema`), written by
process 0 next to the checkpoints. Every value passes through
:func:`json_sanitize` + ``json.dumps(..., allow_nan=False)`` so the file is
*strict* JSON on disk: a blown-up epoch's post-mortem row serializes its
NaN/Inf metrics as ``null``, never as the bare tokens strict parsers (jq,
serde, JSON.parse, BigQuery loads) reject.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Optional

import jax
import numpy as np

_NANS_ENV = "TPUDDP_DEBUG_NANS"


def nan_checks_enabled() -> bool:
    return os.environ.get(_NANS_ENV, "") not in ("", "0")


def json_sanitize(value):
    """Strict-JSON form of a record: non-finite floats become ``None``
    (serialized ``null``), recursively through dicts/lists/tuples, and numpy
    leaves (``np.float32``/``np.int64``/``np.bool_`` scalars and 0-d arrays —
    a stray device scalar that leaked into a record) fail into clean Python
    values instead of tripping ``allow_nan=False`` or emitting non-JSON reprs.

    Python's ``json.dumps`` default emits bare ``NaN``/``Infinity`` tokens —
    *invalid* JSON that strict parsers reject, which made ``history.jsonl``
    and ``bench_results.json`` unconsumable the moment an epoch blew up (the
    empty-test-loader path writes ``float("nan")`` test metrics by design).
    Writers here pair this with ``json.dumps(..., allow_nan=False)`` so any
    future non-finite leak fails loudly at write time instead of corrupting
    the artifact."""
    if isinstance(value, dict):
        return {k: json_sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_sanitize(v) for v in value]
    # numpy scalars / 0-d arrays (incl. jax arrays fetched to host): .item()
    # yields the native Python value, then the float rule below applies —
    # np.bool_ must resolve before the generic test (it is not a Number json
    # knows) and np.float32(nan) must land as null like any other NaN
    if isinstance(value, np.generic):
        value = value.item()
    elif isinstance(value, np.ndarray) and value.ndim == 0:
        value = value.item()
    if isinstance(value, float) and not math.isfinite(value):
        return None
    return value


def check_finite(value: float, what: str) -> None:
    """Raise if a host-side aggregated metric went non-finite (only when
    $TPUDDP_DEBUG_NANS is set)."""
    if nan_checks_enabled() and not math.isfinite(value):
        raise FloatingPointError(f"non-finite {what}: {value}")


class CommBytesCounter:
    """Running gradient-communication byte counter (per replica).

    The per-update payload is static (compiled into the step program), so the
    counter is host-side multiplication — free next to a device step. ``None``
    bytes-per-update (a ddp object predating init_state, or an Accelerator
    facade without the attribute) degrades to an inert counter whose
    :meth:`snapshot` returns ``{}`` so epoch records stay unchanged. A true
    ``0`` (a hookless / no-grad-comm configuration) is a *real measurement*
    and stays 0 — it must not collapse into the inert None case, or a
    zero-byte path would silently vanish from the record instead of being
    reported as zero."""

    def __init__(self, bytes_per_update):
        self.bytes_per_update = (
            int(bytes_per_update) if bytes_per_update is not None else None
        )
        self.updates = 0

    def add_updates(self, n: int) -> None:
        self.updates += int(n)

    @property
    def total_bytes(self):
        if self.bytes_per_update is None:
            return None
        return self.bytes_per_update * self.updates

    def snapshot(self, epoch_updates: int = None) -> dict:
        """Record fields for the JSONL history: the static per-update payload,
        the cumulative total, and (when given) this epoch's slice."""
        if self.bytes_per_update is None:
            return {}
        out = {
            "grad_comm_bytes_per_update": self.bytes_per_update,
            "grad_comm_bytes_total": self.total_bytes,
        }
        if epoch_updates is not None:
            out["grad_comm_bytes_epoch"] = self.bytes_per_update * int(epoch_updates)
        return out


class MetricsWriter:
    """JSONL metrics sink (``history.jsonl`` in the run dir).

    Holds one line-buffered append handle (opened lazily at the first record),
    so the file always ends on a whole JSON record — a crash or preemption
    mid-epoch must not truncate the machine-readable history. :meth:`sync`
    additionally ``os.fsync``-s the file so a record survives an imminent
    SIGKILL; :meth:`close` (called from the epoch driver's ``finally``) syncs
    too, covering the preemption-drain path where the scheduler's kill lands
    seconds after the emergency checkpoint.

    ``main_only=True`` (the default) gates writing to process 0 — the normal
    single-writer history contract. ``main_only=False`` lets any process
    append (used by the watchdog, whose stale-peer event fires on whichever
    process detected it); single-line appends below PIPE_BUF are atomic on
    POSIX, so concurrent writers interleave whole records, never bytes.

    Writes are additionally serialized by an intra-process lock: the serving
    engine's dispatch threads share ONE writer (serving_stats windows,
    dispatch-error events, the drain event), and ``TextIOWrapper`` gives no
    cross-thread atomicity guarantee of its own — an unserialized interleave
    would corrupt a line and fail the schema gate."""

    def __init__(
        self,
        save_dir: Optional[str],
        filename: str = "history.jsonl",
        main_only: bool = True,
        flight=None,
    ):
        """``flight``: an ``observability.flight.FlightRecorder`` tee — every
        record passed to :meth:`write` is observed by the crash ring BEFORE
        the process-0 file gate, so non-main processes keep a recording even
        though they never write the file."""
        self.path = None
        self._f = None
        self._lock = threading.Lock()
        self.flight = flight
        if save_dir is not None and (not main_only or jax.process_index() == 0):
            os.makedirs(save_dir, exist_ok=True)
            self.path = os.path.join(save_dir, filename)

    def write(self, record: dict) -> None:
        if self.flight is not None:
            self.flight.observe(record)
        if self.path is None:
            return
        # serialize the record OUTSIDE the lock (the expensive part), append
        # the whole line inside it
        line = json.dumps(json_sanitize(record), allow_nan=False) + "\n"
        with self._lock:
            if self._f is None:
                # line-buffered: every completed line reaches the OS
                # immediately, without a per-write flush syscall pair
                self._f = open(self.path, "a", buffering=1)
            # strict JSON on disk: NaN/Inf metrics (a blown-up epoch's
            # post-mortem row) serialize as null, never the bare NaN token
            # strict parsers reject
            self._f.write(line)

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def sync(self) -> None:
        """Flush + fsync: force written records to disk *now*. Called on the
        preemption-drain path (and by :meth:`close`) so the final event row
        survives the SIGKILL that follows the grace window."""
        with self._lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        if self._f is not None:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass  # fsync is best-effort on exotic filesystems

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._sync_locked()
                self._f.close()
                self._f = None

    def __del__(self):  # backstop for callers that never reach close()
        try:
            self.close()
        except Exception:
            pass
