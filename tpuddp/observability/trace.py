"""Causal tracing plane — host-side span trees across training, serving, fleet.

Everything the repo measures today is *aggregate*: percentiles, windows,
counters. None of it answers the causal question — WHICH queue wait, prefill,
decode steps, and failover episode produced a slow serving p99, or WHICH of
staging / dispatch / collective / readback ate a training step's wall time.
This module is the span model that closes that gap:

- a **span** is one timed host-side interval: ``trace_id`` (the tree it
  belongs to), ``span_id``, ``parent_id`` (nesting), a typed ``kind`` (one of
  :data:`SPAN_KINDS`), monotonic start/end clocks (``perf_counter_ns`` —
  wall-clock steps under NTP must not corrupt durations), free-form ``attrs``
  (wire bytes, tenant, replica index), and an optional ``follows_from`` link
  — the causal edge that keeps a failover-resumed decode stream one trace;
- a :class:`Tracer` holds a **bounded per-process ring** of completed spans
  (oldest dropped with explicit ``dropped`` accounting — a long run must not
  grow host memory per span), the open-span set (the crash evidence: the
  flight recorder embeds it on abnormal exits, so a dump shows *where in the
  step* the process died), cumulative per-kind counters, and a small
  slowest-span table;
- export is two-way: :meth:`Tracer.export` writes ``trace_<role>.json`` — a
  Chrome-trace-event artifact (``traceEvents`` + a ``tpuddp`` provenance
  block, schema v9) loadable directly in Perfetto and mergeable with the
  device-side ``*.trace.json.gz`` via ``tools/trace_breakdown.py
  --merge-host`` — and the live ``/trace`` endpoint on the
  :class:`~tpuddp.observability.exporter.MetricsExporter` serves the last-N
  completed spans (:meth:`Tracer.endpoint_payload`).

Everything is host-side by construction: spans bracket calls the hot paths
already make, never add a ``block_until_ready``, and never touch the compiled
step program — tracing on/off lowers to the identical HLO and a traced run's
loss trajectory is bitwise the untraced one (asserted in tests and the full
gate's tracing leg). Default OFF via the ``observability.tracing`` config
knob; when off the :data:`NULL` tracer's no-op methods are all the hot path
pays.

Clock model: span timestamps are ``perf_counter_ns`` (monotonic). The tracer
captures ONE wall↔monotonic anchor at construction (``clock_sync`` in the
artifact: ``unix_us`` + ``perf_ns`` taken back to back), so export maps every
span onto the unix-epoch microsecond axis Chrome/Perfetto expect. On a pod,
each host's telemetry shard carries the same anchor pair through the
heartbeat channel (:func:`tpuddp.observability.aggregate.make_shard`), which
is what lets a merger correct cross-host skew when overlaying per-host trace
artifacts.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import Counter, deque
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("tpuddp")

DEFAULT_CAPACITY = 4096
_SLOWEST_TABLE = 8  # spans retained in the slowest-span summary table
# /trace serves the last-N completed spans by default: the payload is built
# UNDER the tracer lock, and copying the whole 4096-capacity ring per scrape
# would stall hot-path end_span calls behind every poller
ENDPOINT_SPANS_DEFAULT = 256

# Typed span kinds. Training: one epoch span per epoch, with stage (host
# batch -> device placement), dispatch (the jitted call's issue window),
# collective (the comm hook's bucketed exchange, annotated with wire bytes —
# an annotation span: the exchange itself runs inside the compiled program),
# and readback (deferred metric drain / explicit sync) children. Serving:
# one request span per admitted request with admission / queue_wait /
# prefill / serve children; decode_step spans are the engine-side step
# timeline; failover and probation mark survivability episodes. Fleet: one
# job span per submitted job with action children (start/resize/preempt).
KIND_EPOCH = "epoch"
KIND_STAGE = "stage"
KIND_DISPATCH = "dispatch"
KIND_COLLECTIVE = "collective"
KIND_READBACK = "readback"
KIND_REQUEST = "request"
KIND_ADMISSION = "admission"
KIND_QUEUE_WAIT = "queue_wait"
KIND_PREFILL = "prefill"
KIND_SERVE = "serve"
KIND_DECODE_STEP = "decode_step"
KIND_FAILOVER = "failover"
KIND_PROBATION = "probation"
KIND_JOB = "job"
KIND_ACTION = "action"

SPAN_KINDS = (
    KIND_EPOCH, KIND_STAGE, KIND_DISPATCH, KIND_COLLECTIVE, KIND_READBACK,
    KIND_REQUEST, KIND_ADMISSION, KIND_QUEUE_WAIT, KIND_PREFILL, KIND_SERVE,
    KIND_DECODE_STEP, KIND_FAILOVER, KIND_PROBATION, KIND_JOB, KIND_ACTION,
)


class Span:
    """One completed-or-open host interval. Mutable only through the owning
    tracer (``end_span`` stamps ``t_end_ns``); ``attrs`` is the free-form
    annotation dict callers extend at end time."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "kind",
        "t_start_ns", "t_end_ns", "attrs", "follows_from", "tid",
    )

    def __init__(
        self, trace_id: str, span_id: int, parent_id: Optional[int],
        name: str, kind: str, t_start_ns: int, tid: str,
        attrs: Optional[dict] = None, follows_from: Optional[int] = None,
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.t_start_ns = t_start_ns
        self.t_end_ns: Optional[int] = None
        self.attrs = dict(attrs) if attrs else {}
        self.follows_from = follows_from
        self.tid = tid

    @property
    def duration_ms(self) -> Optional[float]:
        if self.t_end_ns is None:
            return None
        return (self.t_end_ns - self.t_start_ns) / 1e6

    def summary(self) -> dict:
        """Compact dict form (flight-recorder embed, /trace endpoint)."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "t_start_ns": self.t_start_ns,
            "duration_ms": (
                None if self.duration_ms is None else round(self.duration_ms, 4)
            ),
            "tid": self.tid,
            "follows_from": self.follows_from,
            "attrs": dict(self.attrs),
        }


class _NullSpan:
    """The inert span the :data:`NULL` tracer hands out — attribute writes
    land nowhere, so instrumented hot paths never branch on enablement."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    kind = None
    follows_from = None
    duration_ms = None
    attrs: dict = {}

    def summary(self) -> dict:
        return {}


NULL_SPAN = _NullSpan()


class _NullTracer:
    """No-op stand-in when ``observability.tracing`` is off (the default):
    the hot paths call the same two methods unconditionally and pay two
    no-op calls — the NULL-telemetry pattern. Nothing is recorded, no
    artifact is ever written."""

    enabled = False
    role = None

    def new_trace(self) -> None:
        return None

    def start_span(self, *a, **kw) -> _NullSpan:
        return NULL_SPAN

    def end_span(self, span, **attrs) -> None:
        pass

    def span(self, *a, **kw):
        import contextlib

        return contextlib.nullcontext(NULL_SPAN)

    def open_span_summaries(self) -> list:
        return []

    def endpoint_payload(self, limit=None) -> dict:
        return {"enabled": False, "spans": [], "open": [], "dropped": 0}

    def summary_record(self) -> dict:
        return {}

    def describe(self) -> None:
        return None  # the run_meta ``tracing`` block: null = tracing off

    def export(self, path: Optional[str] = None) -> None:
        return None


NULL = _NullTracer()
NULL_TRACER = NULL  # the package-level export name


class Tracer:
    """The live span recorder for one process and one role (train / serving
    / decode / fleet). Thread-safe: serving dispatch threads and the client
    submit path share one tracer."""

    enabled = True

    def __init__(
        self,
        role: str,
        capacity: int = DEFAULT_CAPACITY,
        run_dir: Optional[str] = None,
        process_index: Optional[int] = None,
    ):
        if process_index is None:
            try:
                import jax

                process_index = jax.process_index()
            except Exception:
                process_index = 0
        self.role = str(role)
        self.capacity = max(1, int(capacity))
        self.run_dir = run_dir
        self.process_index = int(process_index)
        self._lock = threading.Lock()
        self._ring: deque = deque()  # completed spans, oldest first
        self._open: Dict[int, Span] = {}
        self._ids = 0
        self._traces = 0
        self.dropped = 0
        self.completed = 0
        self.kind_counts: Counter = Counter()
        self._slowest: List[dict] = []  # [{name, kind, duration_ms, span_id}]
        self._tids: Dict[str, int] = {}  # tid name -> chrome tid int
        # the ONE wall<->monotonic anchor (taken back to back): every export
        # maps perf_counter_ns onto the unix-us axis through this pair, and
        # the pod shard channel republishes it for cross-host skew correction
        self.clock_unix_us = int(time.time() * 1e6)
        self.clock_perf_ns = time.perf_counter_ns()

    # ------------------------------------------------------------ recording --
    def new_trace(self) -> str:
        """Mint a trace id (one span tree: a training run, one request, one
        job). Unique within this process's artifact, stable across export."""
        with self._lock:
            self._traces += 1
            return f"{self.role}-p{self.process_index}-{self._traces:06d}"

    def start_span(
        self,
        name: str,
        kind: str,
        *,
        trace_id: Optional[str] = None,
        parent=None,
        follows_from: Optional[int] = None,
        tid: Optional[str] = None,
        attrs: Optional[dict] = None,
    ) -> Span:
        """Open one span. ``parent`` (a Span) supplies the trace and the
        nesting edge unless overridden; no parent and no trace_id mints a
        fresh trace. ``follows_from`` is a *causal, non-nesting* predecessor
        span id (the failover link). ``tid`` names the timeline row the span
        renders on (defaults to the parent's row, else the role)."""
        if kind not in SPAN_KINDS:
            raise ValueError(f"unknown span kind {kind!r}; one of {SPAN_KINDS}")
        parent_id = None
        if parent is not None and getattr(parent, "span_id", None) is not None:
            parent_id = parent.span_id
            if trace_id is None:
                trace_id = parent.trace_id
            if tid is None:
                tid = parent.tid
        if trace_id is None:
            trace_id = self.new_trace()
        now = time.perf_counter_ns()
        with self._lock:
            self._ids += 1
            span = Span(
                trace_id, self._ids, parent_id, str(name), kind, now,
                tid if tid is not None else self.role, attrs,
                follows_from=follows_from,
            )
            self._open[span.span_id] = span
        return span

    def end_span(self, span, **attrs) -> None:
        """Close one span (idempotent; the NULL span is ignored): stamp the
        end clock, move it into the bounded ring (dropping — and counting —
        the oldest past capacity), update the per-kind counters and the
        slowest-span table. The stamp, the attrs merge, AND the
        already-closed check all happen under the tracer lock: a /trace
        scrape or flight dump iterating ``span.attrs`` under the same lock
        must never see it mid-update, and two racing closers must never
        ring the same span twice."""
        if not isinstance(span, Span):
            return
        now = time.perf_counter_ns()
        with self._lock:
            if span.t_end_ns is not None:
                return
            span.t_end_ns = now
            if attrs:
                span.attrs.update(attrs)
            self._open.pop(span.span_id, None)
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self.dropped += 1
            self._ring.append(span)
            self.completed += 1
            self.kind_counts[span.kind] += 1
            dur = span.duration_ms or 0.0
            if (
                len(self._slowest) < _SLOWEST_TABLE
                or dur > self._slowest[-1]["duration_ms"]
            ):
                self._slowest.append({
                    "name": span.name,
                    "kind": span.kind,
                    "duration_ms": round(dur, 4),
                    "span_id": span.span_id,
                })
                self._slowest.sort(
                    key=lambda r: r["duration_ms"], reverse=True
                )
                del self._slowest[_SLOWEST_TABLE:]

    def span(self, name: str, kind: str, **kw):
        """Context-manager sugar over start/end for non-hot-path callers."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            s = self.start_span(name, kind, **kw)
            try:
                yield s
            finally:
                self.end_span(s)

        return _cm()

    # ------------------------------------------------------------ live views --
    def open_span_summaries(self) -> List[dict]:
        """The still-open spans, outermost first — what the flight recorder
        embeds on abnormal exit so a crash dump names the exact stage the
        process died in. Summaries are built UNDER the lock: an open span's
        attrs may be mid-update by a concurrent ``end_span`` otherwise."""
        with self._lock:
            return [
                s.summary()
                for s in sorted(self._open.values(), key=lambda s: s.span_id)
            ]

    def endpoint_payload(
        self, limit: Optional[int] = ENDPOINT_SPANS_DEFAULT
    ) -> dict:
        """The ``/trace`` endpoint's JSON: the last-``limit`` completed
        spans (newest last; ``None``/0 = the whole ring) plus the open set
        and drop accounting. Copied under the lock — which is why the
        default is bounded: a scrape must not hold the lock for a
        4096-span copy while dispatch threads wait to end spans.
        Serialization happens in the endpoint, outside the lock."""
        with self._lock:
            spans = list(self._ring)
            if limit is not None and limit > 0:
                spans = spans[-int(limit):]
            payload = {
                "enabled": True,
                "role": self.role,
                "process_index": self.process_index,
                "capacity": self.capacity,
                "completed": self.completed,
                "dropped": self.dropped,
                "spans": [s.summary() for s in spans],
                "open": [
                    s.summary()
                    for s in sorted(self._open.values(), key=lambda s: s.span_id)
                ],
            }
        return payload

    def summary_record(self) -> dict:
        """The typed ``trace_summary`` history record (schema v9): span and
        drop accounting plus the slowest-span table — the one-line causal
        digest a reader gets without opening the artifact."""
        with self._lock:
            return {
                "role": self.role,
                "spans": self.completed,
                "dropped": self.dropped,
                "open_spans": len(self._open),
                "traces": self._traces,
                "by_kind": dict(self.kind_counts),
                "slowest": [dict(r) for r in self._slowest],
            }

    def describe(self) -> dict:
        """The run_meta ``tracing`` provenance block (schema v9)."""
        return {"capacity": self.capacity, "artifact": self.artifact_name()}

    # --------------------------------------------------------------- export --
    def artifact_name(self) -> str:
        """``trace_<role>.json``; non-zero processes qualify the name (the
        run dir is shared on a pod — the flight-recorder convention)."""
        if self.process_index == 0:
            return f"trace_{self.role}.json"
        return f"trace_{self.role}_p{self.process_index}.json"

    def _ts_us(self, t_ns: int) -> float:
        return self.clock_unix_us + (t_ns - self.clock_perf_ns) / 1e3

    def _tid_for(self, name: str) -> int:
        if name not in self._tids:
            self._tids[name] = len(self._tids)
        return self._tids[name]

    def chrome_payload(self) -> dict:
        """The full Chrome-trace-event artifact payload: completed spans as
        ``ph: "X"`` complete events, still-open spans as X events flagged
        ``open`` (their dur runs to "now" — the honest crash view), flow
        ``s``/``f`` pairs for every ``follows_from`` edge whose predecessor
        survived the ring, and process/thread metadata rows.

        The whole event build runs under the tracer lock (export is a
        drain/crash-path rarity): an open span's attrs may be mid-``end_span``
        on a live dispatch thread otherwise."""
        from tpuddp.observability import schema

        now_ns = time.perf_counter_ns()
        with self._lock:
            spans = list(self._ring) + sorted(
                self._open.values(), key=lambda s: s.span_id
            )
            meta = {
                "type": "trace",
                "schema_version": None,  # stamped by the caller (export)
                "role": self.role,
                "process_index": self.process_index,
                "capacity": self.capacity,
                "spans": self.completed,
                "dropped": self.dropped,
                "open_spans": len(self._open),
                "traces": self._traces,
                "by_kind": dict(self.kind_counts),
                "slowest": [dict(r) for r in self._slowest],
                "clock_sync": {
                    "unix_us": self.clock_unix_us,
                    "perf_ns": self.clock_perf_ns,
                },
            }
            meta["schema_version"] = schema.SCHEMA_VERSION
            pid = self.process_index
            by_id = {s.span_id: s for s in spans}  # O(1) follows_from lookups
            events = [
                {
                    "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": f"tpuddp {self.role} p{pid}"},
                },
            ]
            for tname in sorted({s.tid for s in spans}):
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": self._tid_for(tname), "args": {"name": tname},
                })
            flow = 0
            for s in spans:
                open_span = s.t_end_ns is None
                end_ns = now_ns if open_span else s.t_end_ns
                args = {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    **s.attrs,
                }
                if s.follows_from is not None:
                    args["follows_from"] = s.follows_from
                if open_span:
                    args["open"] = True
                events.append({
                    "ph": "X",
                    "name": s.name,
                    "cat": s.kind,
                    "pid": pid,
                    "tid": self._tid_for(s.tid),
                    "ts": round(self._ts_us(s.t_start_ns), 3),
                    "dur": round(max(end_ns - s.t_start_ns, 0) / 1e3, 3),
                    "args": args,
                })
                if s.follows_from is not None and s.follows_from in by_id:
                    pred = by_id[s.follows_from]
                    flow += 1
                    pred_end = (
                        pred.t_end_ns if pred.t_end_ns is not None else now_ns
                    )
                    events.append({
                        "ph": "s", "id": flow, "name": "follows_from",
                        "cat": "flow", "pid": pid,
                        "tid": self._tid_for(pred.tid),
                        "ts": round(self._ts_us(pred_end), 3),
                    })
                    events.append({
                        "ph": "f", "bp": "e", "id": flow,
                        "name": "follows_from",
                        "cat": "flow", "pid": pid,
                        "tid": self._tid_for(s.tid),
                        "ts": round(self._ts_us(s.t_start_ns), 3),
                    })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "tpuddp": meta,
        }

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the artifact atomically (tmp+fsync+rename — the flight
        recorder's contract: drains and crash paths call this and must
        proceed regardless). Returns the path, or None without a
        destination / on a failed best-effort write."""
        if path is None:
            if self.run_dir is None:
                return None
            path = os.path.join(self.run_dir, self.artifact_name())
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            from tpuddp.observability.metrics import json_sanitize

            with open(tmp, "w") as f:
                json.dump(
                    json_sanitize(self.chrome_payload()), f, allow_nan=False
                )
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except (OSError, ValueError) as e:
            logger.warning("trace export (%s) failed: %s", path, e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        logger.info(
            "trace: %d span(s) (%d dropped) -> %s",
            self.completed, self.dropped, path,
        )
        return path


def end_request_trace(tracer, request, error) -> None:
    """Close a queued/serving request's trace context — the ONE
    close-with-error sequence every failure exit shares across both serving
    engines (shed, retry exhaustion, max-failovers, mortuary): stringify
    the error (exception or reason string), end the open child span if any,
    end the root, clear ``request.trace``. No-op for untraced requests."""
    trace = getattr(request, "trace", None)
    if not trace:
        return
    reason = error if isinstance(error, str) else repr(error)
    open_span = trace.get("open")
    if open_span is not None:
        tracer.end_span(open_span, error=reason)
    tracer.end_span(trace["root"], error=reason)
    request.trace = None


def tracer_from_config(
    obs_cfg, role: str, run_dir: Optional[str] = None
):
    """Build the role's tracer from a resolved ``observability`` block
    (tpuddp/config.py:OBSERVABILITY_DEFAULTS): :data:`NULL` unless
    ``tracing`` is armed — the off path must cost nothing and write
    nothing."""
    if not obs_cfg or not obs_cfg.get("tracing"):
        return NULL
    return Tracer(
        role,
        capacity=int(obs_cfg.get("trace_capacity") or DEFAULT_CAPACITY),
        run_dir=run_dir,
    )
