"""Observability — step-level telemetry, typed metrics schema, profiling.

Promoted from ``tpuddp/utils/observability.py`` (which remains as a
re-export shim) into a real subsystem once the ad-hoc JSONL writes outgrew
their one file: resilience events (rollback/desync/preempt), comm-bytes
accounting, and the bench harness all emit measurement artifacts, and
pod-scale TPU work treats per-step timing and MFU accounting as first-class
(MLPerf-on-TPU-pods, arxiv 1909.09756) rather than something grepped out of
stdout.

- :mod:`metrics`   — strict-JSON history writer (fsync-on-drain), comm-bytes
  counter, ``json_sanitize``/``check_finite``.
- :mod:`schema`    — the typed record schema (``run_meta``/``epoch``/
  ``step_stats``/``event`` + ``schema_version``) and its validators, shared
  by the writers and ``tools/tpuddp_inspect.py``.
- :mod:`recorder`  — per-step wall-time ring buffer, p50/p95/p99/max +
  achieved-MFU summaries, the chip peak-FLOPs table.
- :mod:`profiling` — ``TPUDDP_PROFILE`` (first epoch),
  ``TPUDDP_PROFILE_STEPS=<start>:<stop>`` (step window), SIGUSR1 (one epoch
  on demand).
- :mod:`telemetry` — :class:`RunTelemetry`, the bundle the epoch drivers
  wire through their hot loops.
- :mod:`exporter`  — the opt-in live ``/metrics`` + ``/healthz`` +
  ``/snapshot`` HTTP endpoint (ISSUE 10), fed by the recorder/serving state
  the per-window fence already materialized.
- :mod:`aggregate` — per-host telemetry shards over the heartbeat-file
  channel + the main-process pod aggregator and straggler detector.
- :mod:`flight`    — the bounded crash flight recorder, dumped to
  ``flightrec_<reason>.json`` on abnormal exit paths.
- :mod:`trace`     — the causal tracing plane (ISSUE 15): host-side span
  trees (trace_id / span_id / parent_id, typed kinds, bounded ring with
  drop accounting) through training (epoch → stage/dispatch/collective/
  readback), serving (request → admission → queue-wait → prefill →
  decode-step, failover follow-from links), and the fleet controller;
  exported as Perfetto-loadable ``trace_<role>.json`` artifacts at drain
  and served live on the exporter's ``/trace`` endpoint. Default OFF
  (``observability.tracing``); zero device fences either way.
"""

from tpuddp.observability.aggregate import PodAggregator  # noqa: F401
from tpuddp.observability.exporter import (  # noqa: F401
    MetricsExporter,
    exporter_from_config,
)
from tpuddp.observability.flight import FlightRecorder  # noqa: F401
from tpuddp.observability.metrics import (  # noqa: F401
    CommBytesCounter,
    MetricsWriter,
    check_finite,
    json_sanitize,
    nan_checks_enabled,
)
from tpuddp.observability.profiling import (  # noqa: F401
    install_sigusr1_trigger,
    maybe_start_profiler,
    parse_profile_steps,
    stop_profiler,
)
from tpuddp.observability.recorder import (  # noqa: F401
    PEAK_FLOPS,
    StepStatsRecorder,
    device_peak_flops,
    estimate_step_flops,
    percentiles,
    step_time_fields,
)
from tpuddp.observability.schema import (  # noqa: F401
    RECORD_TYPES,
    SCHEMA_VERSION,
    config_hash,
    make_run_meta,
    stamp,
    validate_bench_file,
    validate_history_file,
    validate_history_records,
)
from tpuddp.observability.telemetry import RunTelemetry  # noqa: F401
from tpuddp.observability.trace import (  # noqa: F401
    NULL_TRACER,
    SPAN_KINDS,
    Tracer,
    tracer_from_config,
)

__all__ = [
    "CommBytesCounter",
    "FlightRecorder",
    "MetricsExporter",
    "MetricsWriter",
    "NULL_TRACER",
    "PodAggregator",
    "SPAN_KINDS",
    "Tracer",
    "tracer_from_config",
    "exporter_from_config",
    "PEAK_FLOPS",
    "RECORD_TYPES",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "StepStatsRecorder",
    "check_finite",
    "config_hash",
    "device_peak_flops",
    "estimate_step_flops",
    "install_sigusr1_trigger",
    "json_sanitize",
    "make_run_meta",
    "maybe_start_profiler",
    "nan_checks_enabled",
    "parse_profile_steps",
    "percentiles",
    "stamp",
    "step_time_fields",
    "stop_profiler",
    "validate_bench_file",
    "validate_history_file",
    "validate_history_records",
]
