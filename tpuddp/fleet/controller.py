"""The live fleet controller — per-job supervisors under one planner.

Each admitted :class:`~tpuddp.fleet.spec.JobSpec` runs as its own
:class:`~tpuddp.resilience.supervisor.RestartSupervisor` (on a thread, with
the supervisor's full exit-code policy: 75 resume-now, backoff restarts,
signal-death classification) inside a **namespaced run dir**
``<fleet_dir>/jobs/<name>`` — heartbeats, ``exporter.port``, checkpoints,
``history.jsonl`` and flight recordings all live under the job's own dir,
so co-scheduled jobs cannot clobber each other's channels.

Every control decision is the pure planner's
(:func:`~tpuddp.fleet.scheduler.plan_fleet`); the controller only *applies*
plans, and always through the drain contract:

- **start**   — spawn the job's supervisor at its planned world on its slice;
- **resize**  — retarget the supervisor's world (``set_world``), then
  SIGTERM the live child: it drains to exit 75 (emergency checkpoint) and
  the supervisor relaunches IMMEDIATELY at the new
  ``$TPUDDP_WORLD_SIZE`` / ``$TPUDDP_SERVING_REPLICAS`` — the elastic v2
  restore reshards the state; nothing is lost to a rebalance;
- **preempt** — ``request_stop()`` FIRST (so the supervisor cannot win the
  race and relaunch preempted work), then SIGTERM and let the child drain.

**Never SIGKILL first.** A drained/resized/preempted child gets the full
``$TPUDDP_PREEMPT_GRACE`` window (plus a margin for the in-child failsafe
to dump its flight recording and force exit 75); only a child still alive
past that deadline is escalated to SIGKILL — and that lands as a negative
rc the supervisor classifies by signal name.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

from tpuddp.fleet.scheduler import JobView, Plan, plan_fleet
from tpuddp.fleet.spec import FleetAdmissionError, JobSpec
from tpuddp.observability import trace as trace_lib
from tpuddp.resilience.preemption import preemption_grace_seconds
from tpuddp.resilience.supervisor import (
    WORLD_ENV,
    RestartSupervisor,
    SupervisorPolicy,
)

logger = logging.getLogger("tpuddp")

SERVING_WORLD_ENV = "TPUDDP_SERVING_REPLICAS"

# job lifecycle states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
PREEMPTED = "preempted"
TERMINAL = (DONE, FAILED, PREEMPTED)

# headroom past $TPUDDP_PREEMPT_GRACE before SIGKILL: the child's own
# failsafe needs time to dump its flight recording and force exit 75
_ESCALATE_MARGIN_S = 5.0


def escalate_drain(
    proc: subprocess.Popen,
    grace: Optional[float] = None,
    poll: float = 0.1,
) -> int:
    """Blocking drain-then-escalate: SIGTERM, wait up to ``grace`` seconds
    for the child to drain (exit 75 on the contract), SIGKILL only past the
    deadline. Returns the child's rc (negative = killed by signal). The
    controller's async path mirrors this with per-step deadlines; this
    helper is for shutdown paths and the chaos proof of the escalation
    ordering."""
    if grace is None:
        grace = preemption_grace_seconds() + _ESCALATE_MARGIN_S
    if proc.poll() is not None:
        return proc.returncode
    try:
        proc.send_signal(signal.SIGTERM)
    except (ProcessLookupError, OSError):
        return proc.wait()
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return proc.returncode
        time.sleep(poll)
    logger.critical(
        "fleet: child pid %d ignored SIGTERM for %.1fs; escalating to "
        "SIGKILL", proc.pid, grace,
    )
    try:
        proc.kill()
    except (ProcessLookupError, OSError):
        pass
    return proc.wait()


class ManagedJob:
    """One job's live state under the controller."""

    def __init__(self, spec: JobSpec, arrival: int, run_dir: str):
        self.spec = spec
        self.arrival = arrival
        self.run_dir = run_dir
        self.trace_span = None  # the job's lifecycle span (tracing on only)
        self.state = QUEUED
        self.desired = spec.initial_desired()
        self.slice: Optional[tuple] = None
        self.supervisor: Optional[RestartSupervisor] = None
        self.thread: Optional[threading.Thread] = None
        self.exit_code: Optional[int] = None
        self.stopping = False
        # drain-escalation bookkeeping: the child we SIGTERMed + when to
        # give up on its drain
        self.drain_child: Optional[subprocess.Popen] = None
        self.drain_deadline: Optional[float] = None
        self.resizes = 0
        self.preempted_by: Optional[str] = None

    @property
    def world(self) -> int:
        if self.supervisor is not None and self.supervisor.world_size:
            return self.supervisor.world_size
        return 0

    def view(self) -> JobView:
        return JobView(
            name=self.spec.name,
            priority=self.spec.priority,
            arrival=self.arrival,
            min_world=self.spec.min_world,
            max_world=self.spec.max_world,
            desired=self.desired,
            running=self.state == RUNNING,
            current_world=self.world,
            kind=self.spec.kind,
        )


class FleetController:
    """Gang-schedule jobs over a ``pool_size``-device pool.

    ``max_jobs`` bounds the admission queue (running + queued);
    ``supervisor_policy`` is shared by every per-job supervisor (restart
    budget overridden per spec); ``autoscaler`` (optional) moves each
    running job's ``desired`` world from its scraped live metrics.
    ``env`` is the base environment every job inherits (specs layer their
    own on top). ``clock`` is injectable for deterministic tests."""

    def __init__(
        self,
        pool_size: int,
        fleet_dir: str,
        max_jobs: int = 16,
        supervisor_policy: Optional[SupervisorPolicy] = None,
        autoscaler=None,
        tuner=None,
        env: Optional[Dict[str, str]] = None,
        drain_grace: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        observability: Optional[dict] = None,
    ):
        """``observability``: the live-plane block (config shape); the
        controller consumes its ``tracing`` knobs — one job-lifecycle
        span per submitted job (start/resize/preempt/tune action children),
        exported as ``trace_fleet.json`` at shutdown — and, with
        ``exporter: true``, serves a fleet-level /metrics endpoint carrying
        the tuner's ``tpuddp_tune_*`` counters.

        ``tuner`` (optional, a :class:`tpuddp.tune.online.FleetTuner`)
        closes the observe->advise->act loop: its decisions apply by
        mutating the job supervisor's ``$TPUDDP_TUNE_OVERLAY`` env and
        draining the child — the same exit-75 relaunch contract resizes
        ride, so a knob change is exactly as disruptive as a resize and
        never less safe."""
        if pool_size < 1:
            raise ValueError(f"pool_size must be >= 1, got {pool_size}")
        self.pool_size = int(pool_size)
        self.fleet_dir = fleet_dir
        self.max_jobs = int(max_jobs)
        self.supervisor_policy = supervisor_policy or SupervisorPolicy(
            backoff_base=0.5, backoff_cap=5.0
        )
        self.autoscaler = autoscaler
        self.tuner = tuner
        self.env = dict(env or {})
        self.drain_grace = drain_grace
        self.clock = clock
        self._lock = threading.RLock()
        self.jobs: Dict[str, ManagedJob] = {}
        self._arrivals = 0
        self.last_plan: Optional[Plan] = None
        from tpuddp import config as cfg_lib
        from tpuddp.observability import exporter as exp_lib

        obs_cfg = cfg_lib.resolve_observability(observability)
        self.tracer = trace_lib.tracer_from_config(
            obs_cfg, "fleet", run_dir=fleet_dir,
        )
        self.exporter = exp_lib.exporter_from_config(
            obs_cfg, run_dir=fleet_dir
        )
        if self.exporter is not None:
            self.exporter.start()
            if self.tuner is not None:
                self.exporter.register_source(
                    "tune", self.tuner.export_source
                )
        os.makedirs(os.path.join(fleet_dir, "jobs"), exist_ok=True)

    # -------------------------------------------------------------- admit --
    def submit(self, spec: JobSpec) -> ManagedJob:
        """Admit one job into the bounded queue; the next :meth:`step`
        places it (or leaves it queued behind higher-priority gangs)."""
        if spec.min_world > self.pool_size:
            raise FleetAdmissionError(
                "bad_spec",
                f"job {spec.name!r}: min_world {spec.min_world} exceeds the "
                f"pool ({self.pool_size} devices) — it can never gang-place",
            )
        with self._lock:
            if spec.name in self.jobs:
                raise FleetAdmissionError(
                    "duplicate_name", f"job {spec.name!r} already submitted"
                )
            active = sum(
                1 for j in self.jobs.values() if j.state not in TERMINAL
            )
            if active >= self.max_jobs:
                raise FleetAdmissionError(
                    "fleet_full",
                    f"{active} active jobs >= max_jobs {self.max_jobs}",
                )
            run_dir = os.path.join(self.fleet_dir, "jobs", spec.name)
            os.makedirs(run_dir, exist_ok=True)
            job = ManagedJob(spec, self._arrivals, run_dir)
            job.trace_span = self.tracer.start_span(
                f"job {spec.name}", trace_lib.KIND_JOB, tid="jobs",
                attrs={
                    "kind": spec.kind,
                    "priority": spec.priority,
                    "min_world": spec.min_world,
                    "max_world": spec.max_world,
                },
            )
            self._arrivals += 1
            self.jobs[spec.name] = job
            logger.info(
                "fleet: admitted %s (%s, prio %d, world %d-%d) -> %s",
                spec.name, spec.kind, spec.priority, spec.min_world,
                spec.max_world, run_dir,
            )
            return job

    # -------------------------------------------------------------- spawn --
    @staticmethod
    def _gang_world(spec: JobSpec, world: int) -> int:
        """Clamp a planned world to a (data, model)-factorable gang size.

        The planner and autoscaler reason in raw chip counts; a TP job can
        only gang-run at multiples of its model width (mesh_from refuses
        anything else). Floor to the nearest multiple — min_world is
        validated as a multiple at admission, so the floor never violates
        gang semantics."""
        m = spec.model_size or 1
        if m <= 1:
            return world
        return max((world // m) * m, spec.min_world)

    def _start(self, job: ManagedJob, world: int) -> None:
        spec = job.spec
        world = self._gang_world(spec, world)
        env = dict(self.env)
        env.update(spec.resolved_env(job.run_dir))
        policy = SupervisorPolicy(
            max_restarts=spec.max_restarts,
            backoff_base=self.supervisor_policy.backoff_base,
            backoff_cap=self.supervisor_policy.backoff_cap,
            jitter=self.supervisor_policy.jitter,
            shrink_after=self.supervisor_policy.shrink_after,
            shrink_factor=self.supervisor_policy.shrink_factor,
            min_world=spec.min_world,
        )
        job.supervisor = RestartSupervisor(
            spec.resolved_argv(job.run_dir),
            policy=policy,
            world_size=world,
            env=env,
            first_attempt_env=dict(spec.first_attempt_env),
            flight_dir=job.run_dir,
            world_env_var=(
                SERVING_WORLD_ENV if spec.kind == "serving" else WORLD_ENV
            ),
            # TP training jobs pin their model width ($TPUDDP_MODEL_SIZE) so
            # every relaunch factors the handed world as (data, model) and
            # the supervisor's capacity-loss shrink stays mesh-aware
            model_size=(
                spec.model_size
                if spec.kind == "training" and spec.model_size > 1
                else None
            ),
        )
        job.state = RUNNING
        self.tracer.end_span(self.tracer.start_span(
            "start", trace_lib.KIND_ACTION, parent=job.trace_span,
            attrs={"world": world},
        ))

        def _supervise():
            rc = job.supervisor.run()
            with self._lock:
                job.exit_code = rc
                job.state = (
                    PREEMPTED if job.stopping else (DONE if rc == 0 else FAILED)
                )
                self.tracer.end_span(
                    job.trace_span, state=job.state, exit_code=rc,
                    resizes=job.resizes,
                )
                logger.info(
                    "fleet: %s finished supervision: state=%s rc=%s",
                    spec.name, job.state, rc,
                )

        job.thread = threading.Thread(
            target=_supervise, name=f"fleet-{spec.name}", daemon=True
        )
        job.thread.start()

    # ------------------------------------------------------ drain machinery --
    def _signal_drain(self, job: ManagedJob) -> None:
        """SIGTERM the live child and arm the escalation deadline. If no
        child is live (supervisor mid-backoff) there is nothing to drain —
        the next attempt already picks up the new world / the stop flag."""
        sup = job.supervisor
        child = sup.child if sup is not None else None
        signaled = False
        if child is not None and child.poll() is None:
            # signal the SNAPSHOT, not sup.child re-read: if the old child
            # exits between the poll and the signal, the supervisor's
            # immediate exit-75 relaunch would make a re-read deliver this
            # SIGTERM to the NEW child — a pointless extra drain whose
            # escalation deadline would then track the wrong process
            try:
                child.send_signal(signal.SIGTERM)
                signaled = True
            except (ProcessLookupError, OSError):
                pass
        if signaled:
            grace = (
                self.drain_grace
                if self.drain_grace is not None
                else preemption_grace_seconds() + _ESCALATE_MARGIN_S
            )
            job.drain_child = child
            job.drain_deadline = self.clock() + grace
        else:
            job.drain_child = None
            job.drain_deadline = None

    def _escalate_expired_drains(self, now: float) -> None:
        for job in self.jobs.values():
            if job.drain_child is None:
                continue
            if job.drain_child.poll() is not None:
                job.drain_child = None
                job.drain_deadline = None
                continue
            if job.drain_deadline is not None and now >= job.drain_deadline:
                logger.critical(
                    "fleet: %s ignored SIGTERM past the grace window; "
                    "escalating to SIGKILL", job.spec.name,
                )
                try:
                    job.drain_child.kill()
                except (ProcessLookupError, OSError):
                    pass
                job.drain_child = None
                job.drain_deadline = None

    def _resize(self, job: ManagedJob, world: int) -> None:
        if job.supervisor is None:
            return
        world = self._gang_world(job.spec, world)
        if job.supervisor.world_size == world:
            return
        logger.warning(
            "fleet: resizing %s %s -> %d via the drain contract",
            job.spec.name, job.supervisor.world_size, world,
        )
        # retarget FIRST: if the child exits before our SIGTERM lands (or
        # is already draining), the relaunch still gets the new world
        self.tracer.end_span(self.tracer.start_span(
            "resize", trace_lib.KIND_ACTION, parent=job.trace_span,
            attrs={"from_world": job.supervisor.world_size, "to_world": world},
        ))
        job.supervisor.set_world(world)
        job.resizes += 1
        self._signal_drain(job)

    def _apply_tune(self, job: ManagedJob, decision: dict, now: float) -> None:
        """Commit one tuner decision: mutate the supervisor's
        ``$TPUDDP_TUNE_OVERLAY`` (consumed by ``_child_env`` at every
        attempt) and drain the child so the relaunch resolves its config
        THROUGH the overlay. ``keep`` endorses the live overlay in place —
        no env change, no drain. The tuner's own state machine advances in
        ``mark_applied`` (which also lands the ``tune_action`` history
        event), called only after the env mutation is really in."""
        from tpuddp import config as cfg_lib

        sup = job.supervisor
        if sup is None:
            return
        action = decision["action"]
        self.tracer.end_span(self.tracer.start_span(
            f"tune_{action}", trace_lib.KIND_ACTION, parent=job.trace_span,
            attrs={
                "rule": decision.get("rule"),
                "generation": decision.get("generation"),
                "measured_delta_pct": decision.get("measured_delta_pct"),
            },
        ))
        if action in ("apply", "revert"):
            overlay_env = decision.get("overlay_env")
            if overlay_env is not None:
                sup.env[cfg_lib.TUNE_OVERLAY_ENV] = json.dumps(
                    overlay_env, sort_keys=True
                )
            else:
                # revert to the pristine config: no kept overlay remains
                sup.env.pop(cfg_lib.TUNE_OVERLAY_ENV, None)
            logger.warning(
                "fleet: tune %s on %s (rule %s, gen %s) via the drain "
                "contract", action, job.spec.name, decision.get("rule"),
                decision.get("generation"),
            )
            self._signal_drain(job)
        self.tuner.mark_applied(job.spec.name, job.run_dir, decision, now)

    def _preempt(self, job: ManagedJob, by: Optional[str] = None) -> None:
        if job.stopping or job.supervisor is None:
            return
        job.stopping = True
        job.preempted_by = by
        self.tracer.end_span(self.tracer.start_span(
            "preempt", trace_lib.KIND_ACTION, parent=job.trace_span,
            attrs={"by": by},
        ))
        logger.warning(
            "fleet: preempting %s%s — drain first, SIGKILL only after the "
            "grace window", job.spec.name, f" (displaced by {by})" if by else "",
        )
        # order matters: stop BEFORE the signal, or the supervisor can
        # relaunch between the child's exit and our flag
        job.supervisor.request_stop()
        if job.drain_child is not None and job.drain_child.poll() is None:
            # already draining (e.g. a resize in flight): a second SIGTERM
            # would be the "operator escalated" signal and force an
            # immediate exit mid-flush — let the running drain finish; the
            # stop flag keeps the supervisor from relaunching
            return
        self._signal_drain(job)

    def _held_devices(self) -> int:
        """Devices the pool is ACTUALLY holding right now: a draining child
        still occupies the world it was LAUNCHED at (``current_world``),
        regardless of where ``set_world`` has already retargeted the next
        attempt. New starts are gated on this sum so a drain window can
        never transiently oversubscribe the pool."""
        held = 0
        for job in self.jobs.values():
            if job.state in TERMINAL:
                continue
            sup = job.supervisor
            if sup is None:
                continue
            child = sup.child
            if child is not None and child.poll() is None:
                held += sup.current_world or 0
            elif job.state == RUNNING and not job.stopping:
                # between attempts (backoff / relaunch gap): the supervisor
                # is about to claim its target world again
                held += sup.world_size or 0
        return held

    # --------------------------------------------------------------- tick --
    def step(self, now: Optional[float] = None) -> Plan:
        """One control tick: reap finished supervisors, let the autoscaler
        move desires, re-plan, apply the diff, escalate expired drains."""
        now = self.clock() if now is None else now
        # autoscaler scrapes are blocking HTTP probes (healthz + /metrics,
        # seconds against a blackholed port) — run them OUTSIDE the lock so
        # supervisor completion threads, submit() and stop_job() never stall
        # behind a slow endpoint; proposals re-checked under the lock
        proposals: Dict[str, int] = {}
        if self.autoscaler is not None:
            with self._lock:
                targets = [
                    (j.spec.name, j.spec.kind, j.run_dir,
                     j.world or j.desired, j.spec.min_world, j.spec.max_world)
                    for j in self.jobs.values()
                    if j.state == RUNNING and not j.stopping
                ]
            for name, kind, run_dir, current, min_w, max_w in targets:
                proposal = self.autoscaler.observe_and_propose(
                    name, kind, run_dir,
                    current=current, min_world=min_w, max_world=max_w,
                    now=now,
                )
                if proposal is not None:
                    proposals[name] = proposal
        # tuner decisions read run-dir artifacts (history/trace files) —
        # same outside-the-lock rule as the autoscaler's scrapes; the
        # decisions are re-checked against job state before applying
        tune_decisions: List[tuple] = []
        if self.tuner is not None:
            with self._lock:
                tune_targets = [
                    (j.spec.name, j.spec.kind, j.run_dir)
                    for j in self.jobs.values()
                    if j.state == RUNNING and not j.stopping
                ]
            for name, kind, run_dir in tune_targets:
                decision = self.tuner.observe_and_decide(
                    name, kind, run_dir, now=now
                )
                if decision is not None:
                    tune_decisions.append((name, decision))
        with self._lock:
            # reap: threads that returned already set their final state
            for job in self.jobs.values():
                if (
                    job.state == RUNNING
                    and job.thread is not None
                    and not job.thread.is_alive()
                    and job.exit_code is None
                ):
                    job.state = FAILED  # defensive: thread died un-reported
            for name, desired in proposals.items():
                job = self.jobs.get(name)
                if job is not None and job.state == RUNNING and not job.stopping:
                    job.desired = desired
            views = [
                j.view() for j in self.jobs.values() if j.state not in TERMINAL
            ]
            plan = plan_fleet(self.pool_size, views) if views else Plan(
                self.pool_size, (), (), self.pool_size
            )
            self.last_plan = plan
            alloc = plan.alloc
            held = self._held_devices()
            for name, action in plan.actions:
                job = self.jobs[name]
                if job.stopping:
                    continue  # already on its way out; let the drain finish
                if action == "start":
                    # the plan's capacity math assumes resizes/preempts have
                    # LANDED; a draining child still holds its old world, so
                    # defer the gang until the pool can really seat it
                    if held + alloc[name] > self.pool_size:
                        logger.info(
                            "fleet: deferring start of %s (world %d): %d/%d "
                            "devices still held through a drain window",
                            name, alloc[name], held, self.pool_size,
                        )
                        continue
                    held += alloc[name]
                    job.slice = plan.slices[name]
                    self._start(job, alloc[name])
                elif action == "resize":
                    # shrinks always proceed (they free capacity); a GROW
                    # relaunches at the bigger world the moment its own
                    # drain lands, so gate it on the same held-device sum —
                    # a neighbor's unfinished shrink must complete first
                    delta = alloc[name] - (job.world or 0)
                    if delta > 0 and held + delta > self.pool_size:
                        logger.info(
                            "fleet: deferring grow of %s (+%d): %d/%d "
                            "devices still held through a drain window",
                            name, delta, held, self.pool_size,
                        )
                        continue
                    held += max(delta, 0)
                    job.slice = plan.slices[name]
                    self._resize(job, alloc[name])
                elif action == "preempt":
                    displacer = next(
                        (p.name for p in plan.placements
                         if self.jobs[p.name].state == QUEUED), None,
                    )
                    self._preempt(job, by=displacer)
                elif action == "keep":
                    job.slice = plan.slices[name]
            for name, decision in tune_decisions:
                job = self.jobs.get(name)
                if job is None or job.state != RUNNING or job.stopping:
                    continue  # the job left while we were deciding
                self._apply_tune(job, decision, now)
            self._escalate_expired_drains(now)
            return plan

    # ---------------------------------------------------------- lifecycle --
    def stop_job(self, name: str) -> None:
        with self._lock:
            job = self.jobs[name]
            if job.state == QUEUED:
                job.state = PREEMPTED
                job.stopping = True
                self.tracer.end_span(
                    job.trace_span, state=PREEMPTED, cancelled=True
                )
                return
            if job.state == RUNNING:
                self._preempt(job)

    def run_until(
        self,
        predicate: Callable[["FleetController"], bool],
        poll: float = 1.0,
        timeout: Optional[float] = None,
    ) -> bool:
        """Tick until ``predicate(self)`` holds; False on timeout."""
        deadline = None if timeout is None else self.clock() + timeout
        while True:
            self.step()
            if predicate(self):
                return True
            if deadline is not None and self.clock() >= deadline:
                return False
            time.sleep(poll)

    def training_complete(self) -> bool:
        with self._lock:
            return all(
                j.state in TERMINAL
                for j in self.jobs.values()
                if j.spec.kind == "training"
            )

    def shutdown(self, timeout: float = 120.0) -> None:
        """Drain every still-running job (preempt path: SIGTERM, grace,
        escalate) and join the supervisor threads. Queued jobs are cancelled
        too — the capacity the drains free must not gang-place NEW work in
        the step() calls below."""
        with self._lock:
            for job in self.jobs.values():
                if job.state == QUEUED:
                    job.state = PREEMPTED
                    job.stopping = True
                    self.tracer.end_span(
                        job.trace_span, state=PREEMPTED, cancelled=True
                    )
                elif job.state == RUNNING:
                    self._preempt(job)
        deadline = time.monotonic() + timeout
        alive = []
        while time.monotonic() < deadline:
            self.step()
            with self._lock:
                alive = [
                    j for j in self.jobs.values()
                    if j.thread is not None and j.thread.is_alive()
                ]
            if not alive:
                self._shutdown_telemetry()
                return
            time.sleep(0.2)
        for j in alive:  # last resort: the escalation path already SIGKILLed
            logger.error(
                "fleet: %s supervisor thread still alive at shutdown "
                "timeout", j.spec.name,
            )
        self._shutdown_telemetry()

    def _shutdown_telemetry(self) -> None:
        self.tracer.export()
        if self.exporter is not None:
            self.exporter.stop()

    def status(self) -> List[dict]:
        with self._lock:
            return [
                {
                    "name": j.spec.name,
                    "kind": j.spec.kind,
                    "priority": j.spec.priority,
                    "state": j.state,
                    "world": j.world,
                    "desired": j.desired,
                    "slice": j.slice,
                    "resizes": j.resizes,
                    "exit_code": j.exit_code,
                    "run_dir": j.run_dir,
                }
                for j in sorted(self.jobs.values(), key=lambda x: x.arrival)
            ]
