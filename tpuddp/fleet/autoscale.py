"""Metric-driven autoscaler — the fleet's sensor-to-planner loop.

The live telemetry plane (ISSUE 10) made every job scrapeable while it
runs; this module closes the loop: each poll it discovers a job's endpoint
through the NAMESPACED ``<run_dir>/exporter.port`` file, verifies liveness
with a short-timeout ``/healthz`` probe (a SIGKILLed predecessor's stale
port file must never be trusted — ``exporter.read_live_port``), scrapes
``/metrics``, and proposes a new *desired world* for the planner:

- **serving**: scale replicas up on a p99-latency or batch-occupancy SLO
  breach, back down when p99 sits far under the SLO — one replica at a
  time, so capacity moves at the rate evidence accumulates;
- **training**: shrink a job the PodAggregator has CONVICTED as
  straggler-plagued (the typed ``straggler`` events surface as the
  ``tpuddp_pod_straggler_events_total`` counter) — a pod that keeps
  convicting hosts is better off smaller than stalled.

Flapping is structurally damped three ways: a breach must hold for
``hysteresis`` consecutive FRESH observations (the freshness cursor must
move — re-reading one stale window is one piece of evidence, not N); at
most one action per job per ``cooldown_s``; and every proposal is clamped
to the spec's ``[min_world, max_world]`` by the planner anyway.

:class:`Autoscaler` is deliberately split from scraping: ``propose()`` is a
pure function of (observation, per-job streak state, now) so the policy
matrix is unit-testable without sockets, and the controller injects the
real :func:`scrape_job` at the edge.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("tpuddp")


# ------------------------------------------------------ prometheus parsing --
_SAMPLE_RE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Prometheus text exposition -> ``{name: [(labels, value), ...]}``.
    Comment/HELP/TYPE lines and unparseable samples are skipped — the
    scraper consumes its OWN exporter's format, but a partial page (endpoint
    died mid-response) must degrade to fewer samples, not an exception."""
    families: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {
            k: v.replace('\\"', '"').replace("\\\\", "\\")
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        families.setdefault(m.group("name"), []).append((labels, value))
    return families


def metric_value(
    families: Dict[str, List[Tuple[Dict[str, str], float]]],
    name: str,
    **labels: str,
) -> Optional[float]:
    """First sample of ``name`` whose labels include every given pair."""
    for sample_labels, value in families.get(name, []):
        if all(sample_labels.get(k) == v for k, v in labels.items()):
            return value
    return None


# --------------------------------------------------------------- scraping --
def scrape_job(run_dir: str, timeout: float = 2.0) -> Optional[dict]:
    """One observation of a job's live plane, or None (no live endpoint —
    port file missing/stale, /healthz dead, or the scrape failed). The
    observation carries the SLO signals plus a ``fresh_cursor``: a value
    that moves only when the job produced new evidence (completed requests
    for serving, telemetry scrapes of a moving counter for training)."""
    from tpuddp.observability import exporter as exp

    port = exp.read_live_port(run_dir, probe_timeout=timeout)
    if port is None:
        return None
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=timeout
        ) as resp:
            families = parse_prometheus(resp.read().decode())
    except Exception as e:  # noqa: BLE001 — a dying job must read as "no data"
        logger.warning("autoscale: scrape of %s failed: %s", run_dir, e)
        return None
    completed = metric_value(families, "tpuddp_serving_completed_total")
    tokens = metric_value(families, "tpuddp_decode_tokens_total")
    steps = metric_value(families, "tpuddp_train_steps_total")
    # survivability (schema v7): a decode job exports decode_shed_total
    # where the request engine exports serving_shed_total — either one is
    # "work shed past its deadline", the overload signal the shed-rate
    # scale-up rule consumes
    shed = metric_value(families, "tpuddp_serving_shed_total")
    if shed is None:
        shed = metric_value(families, "tpuddp_decode_shed_total")
    cursor = completed
    if cursor is None:
        cursor = tokens
    if cursor is None:
        cursor = steps
    return {
        "p99_ms": metric_value(
            families, "tpuddp_serving_e2e_ms", quantile="0.99"
        ),
        "occupancy": metric_value(families, "tpuddp_serving_batch_occupancy"),
        "straggler_events": metric_value(
            families, "tpuddp_pod_straggler_events_total"
        ),
        "shed_total": shed,
        "fresh_cursor": cursor,
        "port": port,
    }


# ----------------------------------------------------------------- policy --
@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The knob table (README "Fleet operations").

    ``slo_p99_ms``/``occupancy_high`` arm serving scale-up;
    ``shed_high`` arms the survivability scale-up rule: >= this many NEWLY
    shed requests (``tpuddp_serving_shed_total`` / ``decode_shed_total``
    delta) in a fresh window is a breach — the engine is dropping
    deadline-expired work, the most direct overload evidence there is;
    ``scale_down_below`` (fraction of the SLO) arms scale-down;
    ``hysteresis`` fresh breached observations are required before any
    action, and ``cooldown_s`` bounds the action rate per job.
    ``straggler_shrink`` arms the training-shrink path."""

    slo_p99_ms: Optional[float] = None
    occupancy_high: Optional[float] = None
    shed_high: Optional[int] = None
    scale_down_below: float = 0.25
    hysteresis: int = 2
    cooldown_s: float = 30.0
    straggler_shrink: bool = True
    shrink_factor: int = 2

    def __post_init__(self):
        if self.hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {self.hysteresis}")
        if self.shed_high is not None and self.shed_high < 1:
            raise ValueError(
                f"shed_high must be >= 1 or None, got {self.shed_high}"
            )
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {self.cooldown_s}")
        if not (0.0 <= self.scale_down_below < 1.0):
            raise ValueError(
                f"scale_down_below must be in [0, 1), got {self.scale_down_below}"
            )
        if self.shrink_factor < 2:
            raise ValueError(
                f"shrink_factor must be >= 2, got {self.shrink_factor}"
            )


class Autoscaler:
    """Per-job streak/cooldown state around the pure breach rules.

    ``scraper`` is injectable (tests feed synthetic observations); the
    controller calls :meth:`observe_and_propose` per running job per poll
    and forwards any proposal to the planner as the job's new desired."""

    def __init__(
        self,
        policy: Optional[AutoscalePolicy] = None,
        scraper: Callable[[str], Optional[dict]] = scrape_job,
    ):
        self.policy = policy or AutoscalePolicy()
        self.scraper = scraper
        self._breach: Dict[str, int] = {}
        self._low: Dict[str, int] = {}
        self._cursor: Dict[str, object] = {}
        self._last_action: Dict[str, float] = {}
        self._stragglers_seen: Dict[str, float] = {}
        self._shed_seen: Dict[str, float] = {}
        self.actions: List[dict] = []  # audit trail (tests + CLI logging)

    # ------------------------------------------------------------ helpers --
    def _cooled(self, name: str, now: float) -> bool:
        last = self._last_action.get(name)
        return last is None or (now - last) >= self.policy.cooldown_s

    def _record(self, name: str, now: float, action: str, world: int, why: str):
        self._last_action[name] = now
        self._breach[name] = 0
        self._low[name] = 0
        entry = {"job": name, "action": action, "world": world, "why": why,
                 "t": now}
        self.actions.append(entry)
        logger.warning(
            "autoscale: %s -> %s to world %d (%s)", name, action, world, why
        )

    # ------------------------------------------------------------- decide --
    def propose(
        self,
        name: str,
        kind: str,
        current: int,
        min_world: int,
        max_world: int,
        obs: Optional[dict],
        now: float,
    ) -> Optional[int]:
        """New desired world, or None (no action this poll). Pure in
        (obs, internal streaks, now) — no I/O."""
        if obs is None:
            return None  # a dead endpoint is absence of evidence, not breach
        pol = self.policy
        fresh = obs.get("fresh_cursor") != self._cursor.get(name)
        self._cursor[name] = obs.get("fresh_cursor")

        if kind == "training":
            events = obs.get("straggler_events")
            if events is None or not pol.straggler_shrink:
                return None
            seen = self._stragglers_seen.get(name)
            if seen is None:
                self._stragglers_seen[name] = events  # baseline observation
                return None
            if events <= seen:
                return None
            if current <= min_world:
                # convicted, but nowhere to go: consume the evidence so a
                # later (autoscaler-external) grow doesn't re-fire on it
                self._stragglers_seen[name] = events
                return None
            if not self._cooled(name, now):
                # keep the evidence pending: a conviction landing inside
                # the cooldown must still shrink once the cooldown ends
                return None
            self._stragglers_seen[name] = events
            world = max(min_world, current // pol.shrink_factor)
            if world < current:
                self._record(
                    name, now, "shrink", world,
                    f"straggler conviction(s) {seen:.0f} -> {events:.0f}",
                )
                return world
            return None

        # serving: SLO-driven replica scaling
        p99 = obs.get("p99_ms")
        occ = obs.get("occupancy")
        # shed-rate rule (survivability, schema v7): newly shed work since
        # the last FRESH observation is overload evidence — the first
        # observation is a baseline, never a breach
        shed_now = obs.get("shed_total")
        shed_delta = 0.0
        if shed_now is not None:
            seen = self._shed_seen.get(name)
            if seen is not None:
                shed_delta = shed_now - seen
            if fresh or seen is None:
                self._shed_seen[name] = shed_now
        breach = (
            pol.slo_p99_ms is not None and p99 is not None and p99 > pol.slo_p99_ms
        ) or (
            pol.occupancy_high is not None
            and occ is not None
            and occ > pol.occupancy_high
        ) or (
            pol.shed_high is not None and shed_delta >= pol.shed_high
        )
        low = (
            pol.slo_p99_ms is not None
            and p99 is not None
            and p99 < pol.slo_p99_ms * pol.scale_down_below
        )
        if fresh:  # only new evidence moves a streak
            self._breach[name] = self._breach.get(name, 0) + 1 if breach else 0
            self._low[name] = self._low.get(name, 0) + 1 if low else 0
        if (
            self._breach.get(name, 0) >= pol.hysteresis
            and self._cooled(name, now)
            and current < max_world
        ):
            self._record(
                name, now, "scale_up", current + 1,
                f"p99 {p99} ms / occupancy {occ} / shed +{shed_delta:.0f} "
                f"breached for {self._breach[name]} fresh window(s)",
            )
            return current + 1
        if (
            self._low.get(name, 0) >= pol.hysteresis
            and self._cooled(name, now)
            and current > min_world
        ):
            self._record(
                name, now, "scale_down", current - 1,
                f"p99 {p99} ms under {pol.scale_down_below:.0%} of SLO for "
                f"{self._low[name]} fresh window(s)",
            )
            return current - 1
        return None

    # ---------------------------------------------------------- full tick --
    def observe_and_propose(
        self,
        name: str,
        kind: str,
        run_dir: str,
        current: int,
        min_world: int,
        max_world: int,
        now: float,
    ) -> Optional[int]:
        return self.propose(
            name, kind, current, min_world, max_world,
            self.scraper(run_dir), now,
        )
