"""Fleet control plane — gang-schedule many jobs over one device pool.

`tools/supervise.py` babysits ONE process tree; this package promotes that
into a controller for a whole pool (ROADMAP item 5 / ISSUE 11):

- :mod:`tpuddp.fleet.spec`       — declarative job specs + admission rules;
- :mod:`tpuddp.fleet.scheduler`  — the pure, deterministic gang-placement /
  priority-preemption / rebalance planner (no processes, unit-testable);
- :mod:`tpuddp.fleet.controller` — the live controller: per-job
  ``RestartSupervisor`` under a namespaced run dir, drain-first preemption
  with grace-window SIGKILL escalation, elastic resizes through the exit-75
  -> ``$TPUDDP_WORLD_SIZE`` resume contract;
- :mod:`tpuddp.fleet.autoscale`  — the metric-driven autoscaler: scrapes
  each job's live ``/metrics`` endpoint (port discovered via the namespaced
  ``exporter.port`` file, liveness-verified through ``/healthz``) and moves
  the planner's per-job desired worlds with hysteresis + cooldown.

``tools/fleet.py`` is the CLI; the chaos proof lives in its ``chaos-demo``
subcommand and ``tests/test_chaos.py``.
"""

from tpuddp.fleet.autoscale import Autoscaler, AutoscalePolicy  # noqa: F401
from tpuddp.fleet.controller import FleetController  # noqa: F401
from tpuddp.fleet.scheduler import JobView, Plan, plan_fleet  # noqa: F401
from tpuddp.fleet.spec import FleetAdmissionError, JobSpec  # noqa: F401
