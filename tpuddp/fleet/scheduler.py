"""Gang-placement + rebalance planner — pure, deterministic, process-free.

The controller's every placement decision routes through :func:`plan_fleet`:
given (pool size, the current job views) it returns the complete target
allocation, the disjoint device slices, and the action diff against what is
currently running. No wall clock, no randomness, no I/O — the acceptance
criterion is that the planner is unit-testable apart from any process tree,
and that the same inputs always produce the same plan (input order
included: jobs are ordered by ``(-priority, arrival, name)`` before any
capacity is handed out, so a dict-ordering change upstream can never move
a job).

Policy, in order:

1. **Admission (gang, all-or-nothing).** Walk jobs by priority; admit each
   whose ``min_world`` still fits the remaining pool. A job that does not
   fit is skipped (it stays queued — or, if running, is *preempted*: a
   higher-priority arrival consumed the capacity its gang needs). Lower-
   priority jobs behind a skipped large job may still backfill.
2. **Growth.** In the same order, grow each admitted job toward
   ``clamp(desired, min_world, max_world)`` from whatever pool remains.
   ``desired`` is the autoscaler's lever (serving replicas under SLO
   pressure, straggler-convicted training shrink); it can never push a job
   outside its spec bounds.
3. **Slices.** Placements pack the pool left-to-right in the same order —
   disjoint ``[offset, offset + world)`` ranges by construction.

The action diff compares target allocation to each view's
``running``/``current_world``: ``start`` (queued -> placed), ``resize``
(placed at a different world — the controller drains through exit 75 and
the supervisor resumes at the new world), ``preempt`` (running -> not
placed), ``keep``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class JobView:
    """The planner's entire knowledge of one job — a pure value."""

    name: str
    priority: int = 0
    arrival: int = 0
    min_world: int = 1
    max_world: int = 1
    desired: Optional[int] = None  # None -> max_world
    running: bool = False
    current_world: int = 0
    kind: str = "training"


@dataclasses.dataclass(frozen=True)
class Placement:
    name: str
    world: int
    offset: int  # device slice = [offset, offset + world)


@dataclasses.dataclass(frozen=True)
class Plan:
    pool_size: int
    placements: Tuple[Placement, ...]
    # action per job name: "start" | "resize" | "preempt" | "keep" | "queued"
    actions: Tuple[Tuple[str, str], ...]
    free: int

    @property
    def alloc(self) -> Dict[str, int]:
        return {p.name: p.world for p in self.placements}

    @property
    def slices(self) -> Dict[str, Tuple[int, int]]:
        return {p.name: (p.offset, p.offset + p.world) for p in self.placements}

    def action(self, name: str) -> Optional[str]:
        for n, a in self.actions:
            if n == name:
                return a
        return None


def _order(jobs: Sequence[JobView]) -> list:
    return sorted(jobs, key=lambda j: (-j.priority, j.arrival, j.name))


def plan_fleet(pool_size: int, jobs: Sequence[JobView]) -> Plan:
    if pool_size < 1:
        raise ValueError(f"pool_size must be >= 1, got {pool_size}")
    names = [j.name for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate job names in plan input: {sorted(names)}")
    order = _order(jobs)

    # 1. gang admission by priority, all-or-nothing at min_world
    remaining = pool_size
    alloc: Dict[str, int] = {}
    for j in order:
        if j.min_world <= remaining:
            alloc[j.name] = j.min_world
            remaining -= j.min_world

    # 2. growth toward clamp(desired) in the same order
    for j in order:
        if j.name not in alloc or remaining == 0:
            continue
        desired = j.max_world if j.desired is None else j.desired
        want = max(j.min_world, min(j.max_world, desired))
        extra = min(want - alloc[j.name], remaining)
        if extra > 0:
            alloc[j.name] += extra
            remaining -= extra

    # 3. disjoint slices, packed in priority order
    placements = []
    offset = 0
    for j in order:
        if j.name in alloc:
            placements.append(Placement(j.name, alloc[j.name], offset))
            offset += alloc[j.name]

    actions = []
    for j in order:
        target = alloc.get(j.name)
        if target is None:
            actions.append((j.name, "preempt" if j.running else "queued"))
        elif not j.running:
            actions.append((j.name, "start"))
        elif target != j.current_world:
            actions.append((j.name, "resize"))
        else:
            actions.append((j.name, "keep"))
    return Plan(
        pool_size=pool_size,
        placements=tuple(placements),
        actions=tuple(actions),
        free=remaining,
    )
