"""Declarative job specs — what the fleet controller admits and places.

A :class:`JobSpec` is everything the controller needs to run one job under
its own :class:`~tpuddp.resilience.supervisor.RestartSupervisor`: the argv,
the world-size range the job can gang-run at, a priority (higher preempts
lower), and the job kind — ``training`` jobs speak the exit-75 drain ->
``$TPUDDP_WORLD_SIZE`` elastic-resume contract, ``serving`` jobs the same
drain contract with ``$TPUDDP_SERVING_REPLICAS`` as their world knob
(``config.serving_config`` honors it the way ``world_size_from`` honors the
training override).

``argv`` and ``env`` values may carry a ``{run_dir}`` placeholder: the
controller substitutes each job's NAMESPACED run dir
(``<fleet_dir>/jobs/<name>``) so heartbeats, ``exporter.port``, checkpoints,
``history.jsonl`` and flight recordings of co-scheduled jobs can never
clobber each other — two jobs sharing one pool must never share a channel.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional, Sequence, Tuple

KINDS = ("training", "serving")

# job names become directory components (the run-dir namespace) and metric
# labels; keep them path- and label-safe
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class FleetAdmissionError(ValueError):
    """A job the queue refused, with a machine-readable ``reason``
    (``bad_spec`` / ``duplicate_name`` / ``fleet_full``) — the serving
    queue's AdmissionError shape, at the job granularity."""

    def __init__(self, reason: str, message: str):
        self.reason = reason
        super().__init__(message)


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One declarative fleet job.

    ``min_world``/``max_world`` bound the gang size: the planner never
    places the job below ``min_world`` (gang semantics — all or nothing)
    and never grows it past ``max_world``. ``priority`` breaks every tie:
    a higher-priority arrival preempts lower-priority capacity through the
    drain contract, never by losing work. ``first_attempt_env`` rides the
    supervisor's attempt-0-only env (chaos injection)."""

    name: str
    argv: Tuple[str, ...]
    kind: str = "training"
    priority: int = 0
    min_world: int = 1
    max_world: int = 1
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    first_attempt_env: Dict[str, str] = dataclasses.field(default_factory=dict)
    max_restarts: int = 8
    # tensor-parallel width (training jobs): every world the planner hands
    # this job must factor as (data, model) — min/max_world and resizes are
    # clamped to multiples of model_size; exported as $TPUDDP_MODEL_SIZE
    model_size: int = 1

    def __post_init__(self):
        object.__setattr__(self, "argv", tuple(str(a) for a in self.argv))
        for k in ("env", "first_attempt_env"):
            v = getattr(self, k)
            if v is None:  # a YAML `env:` with no value parses to None
                object.__setattr__(self, k, {})
            elif not isinstance(v, dict):
                raise FleetAdmissionError(
                    "bad_spec", f"job {self.name!r}: {k} must be a mapping"
                )
        if not _NAME_RE.match(self.name):
            raise FleetAdmissionError(
                "bad_spec",
                f"job name {self.name!r} must match {_NAME_RE.pattern} "
                "(it becomes the run-dir namespace component)",
            )
        if self.kind not in KINDS:
            raise FleetAdmissionError(
                "bad_spec", f"job kind {self.kind!r} not in {KINDS}"
            )
        if not self.argv:
            raise FleetAdmissionError("bad_spec", f"job {self.name!r}: empty argv")
        if self.min_world < 1:
            raise FleetAdmissionError(
                "bad_spec",
                f"job {self.name!r}: min_world must be >= 1, got {self.min_world}",
            )
        if self.max_world < self.min_world:
            raise FleetAdmissionError(
                "bad_spec",
                f"job {self.name!r}: max_world {self.max_world} < "
                f"min_world {self.min_world}",
            )
        if self.max_restarts < 0:
            raise FleetAdmissionError(
                "bad_spec",
                f"job {self.name!r}: max_restarts must be >= 0",
            )
        if self.model_size < 1:
            raise FleetAdmissionError(
                "bad_spec",
                f"job {self.name!r}: model_size must be >= 1, got "
                f"{self.model_size}",
            )
        if self.model_size > 1:
            if self.kind != "training":
                raise FleetAdmissionError(
                    "bad_spec",
                    f"job {self.name!r}: model_size applies to training "
                    f"jobs only (got kind {self.kind!r})",
                )
            # gang worlds must factor as (data, model): a world that is not
            # a multiple of model_size has no mesh, so refuse it at
            # admission instead of at the child's mesh_from
            for field in ("min_world", "max_world"):
                w = getattr(self, field)
                if w % self.model_size:
                    raise FleetAdmissionError(
                        "bad_spec",
                        f"job {self.name!r}: {field} {w} is not a multiple "
                        f"of model_size {self.model_size} — no (data, "
                        f"model) mesh exists at that world",
                    )

    # ------------------------------------------------------- substitution --
    def resolved_argv(self, run_dir: str) -> list:
        return [a.replace("{run_dir}", run_dir) for a in self.argv]

    def resolved_env(self, run_dir: str) -> Dict[str, str]:
        return {
            k: str(v).replace("{run_dir}", run_dir) for k, v in self.env.items()
        }

    # the world the controller starts a job at before the autoscaler has an
    # opinion: training jobs soak whatever capacity the planner can spare
    # (elastic — they shrink when neighbors arrive); serving jobs start at
    # min and earn replicas from measured SLO pressure, not from idle pool
    def initial_desired(self) -> int:
        return self.max_world if self.kind == "training" else self.min_world


def spec_from_dict(obj: dict) -> JobSpec:
    """Build a JobSpec from a parsed fleet-file entry (tools/fleet.py),
    refusing unknown keys the way the config blocks do."""
    if not isinstance(obj, dict):
        raise FleetAdmissionError("bad_spec", f"job entry must be a mapping, got {obj!r}")
    known = {f.name for f in dataclasses.fields(JobSpec)}
    unknown = set(obj) - known
    if unknown:
        raise FleetAdmissionError(
            "bad_spec",
            f"unknown job key(s) {sorted(unknown)}; known: {sorted(known)}",
        )
    kw = dict(obj)
    argv = kw.pop("argv", None)
    if not isinstance(argv, Sequence) or isinstance(argv, str):
        raise FleetAdmissionError(
            "bad_spec", f"job {kw.get('name')!r}: argv must be a list"
        )
    return JobSpec(argv=tuple(argv), **kw)
