"""Batch scheduler — coalesces queued requests into static device batches.

The TPU-first batching invariant (tpuddp/data/loader.py) applies to serving
too: every dispatched batch has one of a *small, fixed* set of shapes, so
the compile cache warms once and stays warm. Variable-size requests
concatenate row-wise, then pad to the smallest power-of-two bucket that
holds them (``tpuddp/utils/batching.bucket_for``): at most
``log2(max_batch_size) + 1`` compiled programs per sample shape per replica
— a compile storm is structurally impossible, the same property the
FusedEvaluator's shape_key bucketing proved out for eval (~85x the
per-batch facade, BENCH_r04/r05).

Padding rows ride with weight 0 (``batching.pad_batch``) and their logits
are never sliced back to any request; occupancy (real rows / bucket rows) is
the efficiency the SLO stats report.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from tpuddp.serving.queue import Request, RequestQueue
from tpuddp.utils import batching


class Batch:
    """One coalesced, padded, ready-to-dispatch batch."""

    __slots__ = ("requests", "slices", "x", "w", "rows", "bucket")

    def __init__(
        self,
        requests: List[Request],
        slices: List[Tuple[int, int]],
        x: np.ndarray,
        w: np.ndarray,
    ):
        self.requests = requests
        self.slices = slices  # request i's rows are x[slices[i][0]:slices[i][1]]
        self.x = x
        # 0/1 row mask from pad_batch (already allocated by the shared
        # padding path). The dispatch loop never consumes it — padded rows
        # are simply not sliced back to any request — but masked consumers
        # (a future loss/metric head) and the padding-contract tests read it.
        self.w = w
        self.rows = sum(r.rows for r in requests)
        self.bucket = int(x.shape[0])

    @property
    def occupancy(self) -> float:
        return self.rows / self.bucket


class BatchScheduler:
    """Pulls same-shape request groups off the queue and forms padded
    bucketed batches. One instance is shared by every replica's dispatch
    loop; the queue's lock serializes assembly, the (cheap) host-side
    concatenate + pad runs outside it."""

    def __init__(
        self,
        queue: RequestQueue,
        max_batch_size: int,
        batch_timeout_ms: float = 0.0,
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        self.queue = queue
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = max(0.0, float(batch_timeout_ms)) / 1e3
        # static property, computed once: the full ladder of batch shapes
        # this scheduler can ever emit
        self.buckets = batching.bucket_sizes(self.max_batch_size)

    def form(self, requests: List[Request]) -> Batch:
        """Concatenate + pad one same-key group into a dispatchable batch."""
        assert requests, "cannot form an empty batch"
        slices: List[Tuple[int, int]] = []
        at = 0
        for r in requests:
            slices.append((at, at + r.rows))
            at += r.rows
        x = (
            requests[0].x
            if len(requests) == 1
            else np.concatenate([r.x for r in requests], axis=0)
        )
        bucket = batching.bucket_for(at, self.max_batch_size)
        x, _, w = batching.pad_batch(x, None, bucket)
        return Batch(requests, slices, x, w)

    def next_batch(self) -> Optional[Batch]:
        """Block until a batch can be formed; ``None`` = queue closed and
        drained (the dispatch loop's exit signal)."""
        group = self.queue.take_group(
            self.max_batch_size, top_up_wait=self.batch_timeout_s
        )
        if group is None:
            return None
        return self.form(group)
