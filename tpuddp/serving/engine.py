"""ServingEngine — queue + scheduler + replica pool + SLO stats, assembled.

One dispatch loop thread per replica pulls coalesced batches off the shared
scheduler and runs them on its own device; N replicas therefore serve N
batches genuinely concurrently (distinct devices, distinct programs) while
admission, fairness, and bucketing stay centralized. ``submit`` is the whole
client API: synchronous admission verdict (raises :class:`AdmissionError`
with a machine-readable reason), asynchronous result future.

Lifecycle: ``start()`` writes the ``run_meta`` header and compiles every
bucket program on every replica (warmup — the first real request never pays
a compile), ``drain()`` closes admission, lets the queued work finish,
flushes the final stats window, and stamps a ``serving_drain`` event. The
``__main__`` entrypoint maps SIGTERM onto drain + exit 75 — the resilience
exit-code contract (tpuddp/resilience/preemption.py), so schedulers treat a
draining server exactly like a draining trainer.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import numpy as np

from tpuddp.observability import MetricsWriter, schema
from tpuddp.serving import queue as queue_mod
from tpuddp.serving.queue import AdmissionError, Request, RequestQueue, ServedResult
from tpuddp.serving.replica import Replica, ReplicaPool
from tpuddp.serving.scheduler import BatchScheduler
from tpuddp.serving.stats import ServingStats

logger = logging.getLogger("tpuddp")


class ServingEngine:
    """Continuous-batching inference over a replica pool. See module doc."""

    def __init__(
        self,
        pool: ReplicaPool,
        max_batch_size: int = 32,
        max_queue_depth: int = 256,
        per_tenant_quota: Optional[int] = None,
        batch_timeout_ms: float = 2.0,
        stats_window: int = 64,
        out_dir: Optional[str] = None,
        config: Optional[dict] = None,
        unhealthy_after: int = 3,
        observability: Optional[dict] = None,
    ):
        """``unhealthy_after``: K consecutive dispatch errors mark a replica
        unhealthy — its loop stops pulling work (a broken device/program no
        longer fails every batch routed to it) and a ``replica_unhealthy``
        event row lands in history.jsonl; healthy replicas keep serving and
        drain still exits cleanly. 0 disables the marking (legacy behavior:
        each batch on the broken replica fails individually, forever).

        ``observability``: the live-plane block (config.OBSERVABILITY_DEFAULTS
        shape): ``exporter: true`` serves /metrics from the SLO stats (last
        flushed window + cumulative counters — host dicts only, the dispatch
        hot path is untouched); the flight recorder tees every history record
        and dumps ``flightrec_serving_dispatch.json`` if the engine ever
        loses its last healthy replica."""
        from tpuddp import config as cfg_lib
        from tpuddp.observability import exporter as exp_lib
        from tpuddp.observability import flight as flight_lib

        self.pool = pool
        self.queue = RequestQueue(max_queue_depth, per_tenant_quota)
        self.scheduler = BatchScheduler(
            self.queue, max_batch_size, batch_timeout_ms
        )
        self.unhealthy_after = int(unhealthy_after or 0)
        self._obs_cfg = cfg_lib.resolve_observability(observability)
        self.flight = None
        if self._obs_cfg["flight_recorder"] and out_dir:
            self.flight = flight_lib.install(flight_lib.FlightRecorder(
                out_dir, capacity=int(self._obs_cfg["flight_capacity"]),
            ))
        self.writer = (
            MetricsWriter(out_dir, flight=self.flight) if out_dir else None
        )
        self.stats = ServingStats(self.writer, window=stats_window)
        self.exporter = exp_lib.exporter_from_config(
            self._obs_cfg, run_dir=out_dir
        )
        self._config = dict(config or {})
        self._threads: List[threading.Thread] = []
        self._started = False
        self._drained = False

    @classmethod
    def from_config(
        cls, cfg: dict, out_dir: Optional[str] = None, devices=None,
        observability: Optional[dict] = None,
    ) -> "ServingEngine":
        """Build pool + engine from a ``serving`` config block
        (tpuddp/config.py:SERVING_DEFAULTS / serving_config); the optional
        ``observability`` block arms the exporter/flight recorder."""
        pool = ReplicaPool.from_config(cfg, devices=devices)
        quota = cfg.get("per_tenant_quota")
        return cls(
            pool,
            max_batch_size=int(cfg["max_batch_size"]),
            max_queue_depth=int(cfg["max_queue_depth"]),
            per_tenant_quota=None if quota is None else int(quota),
            batch_timeout_ms=float(cfg["batch_timeout_ms"]),
            stats_window=int(cfg["stats_window"]),
            out_dir=out_dir,
            config=cfg,
            unhealthy_after=int(cfg.get("unhealthy_after", 3) or 0),
            observability=observability,
        )

    # ------------------------------------------------------------- lifecycle --
    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._started:
            return self
        if self.exporter is not None:
            # bind before the header so run_meta records the real port
            self.exporter.start()
            self.exporter.register_source(
                "serving", self.stats.export_source(engine=self)
            )
        if self.writer is not None:
            cfg = self._config
            self.writer.write(
                schema.make_run_meta(
                    world_size=len(self.pool),
                    comm_hook=None,
                    guard=None,
                    observability={
                        "exporter": (
                            self.exporter.describe()
                            if self.exporter is not None
                            else False
                        ),
                        "aggregate": False,  # no pod axis on the serving path
                        "flight_recorder": (
                            self.flight.describe()
                            if self.flight is not None
                            else False
                        ),
                    },
                    extra={
                        "api": "serving",
                        "model": cfg.get("model"),
                        "num_replicas": len(self.pool),
                        "max_batch_size": self.scheduler.max_batch_size,
                        "max_queue_depth": self.queue.max_depth,
                        "per_tenant_quota": self.queue.per_tenant_quota,
                        "batch_timeout_ms": (
                            self.scheduler.batch_timeout_s * 1e3
                        ),
                        "buckets": self.scheduler.buckets,
                        "input_shape": list(self.pool.sample_shape),
                        "restored_epoch": self.pool.restored_epoch,
                        "checkpoint_dir": cfg.get("checkpoint_dir"),
                        "config_hash": schema.config_hash(cfg or None),
                    },
                )
            )
        if warmup:
            t0 = time.perf_counter()
            self.pool.warmup(self.scheduler.buckets)
            logger.info(
                "serving: %d replica(s) warm over buckets %s in %.1fs",
                len(self.pool), self.scheduler.buckets,
                time.perf_counter() - t0,
            )
        # window 0's throughput must measure serving, not bucket compiles
        self.stats.reset_clock()
        for replica in self.pool.replicas:
            t = threading.Thread(
                target=self._dispatch_loop,
                args=(replica,),
                name=f"tpuddp-serve-r{replica.index}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def drain(self, reason: str = "shutdown", timeout: Optional[float] = None) -> dict:
        """Close admission, finish queued + in-flight work, flush stats.
        Idempotent; returns the final :meth:`ServingStats.summary`.

        With a ``timeout``, dispatch threads may outlive the join — then the
        stats are NOT finalized and the writer stays open (the still-running
        loops keep recording honestly); call ``drain`` again to finish."""
        self.queue.close()
        for t in self._threads:
            t.join(timeout)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "serving: dispatch thread(s) %s still running after the "
                "drain timeout; stats not finalized yet", alive,
            )
            return self.stats.summary()
        if not self._drained:
            self._drained = True
            self.stats.flush_window()
            if self.writer is not None:
                self.writer.write(
                    schema.stamp(
                        "event",
                        {
                            "event": "serving_drain",
                            "reason": reason,
                            **{
                                k: v
                                for k, v in self.stats.summary().items()
                                if k in (
                                    "submitted", "completed", "rejected",
                                    "batches", "throughput_rps",
                                )
                            },
                        },
                    )
                )
                self.writer.close()
            if self.exporter is not None:
                self.exporter.stop()
            if self.flight is not None:
                from tpuddp.observability import flight as flight_lib

                flight_lib.uninstall(self.flight)
        return self.stats.summary()

    # --------------------------------------------------------------- client --
    def submit(self, tenant: str, x: np.ndarray) -> ServedResult:
        """Admit one request of ``(rows, *sample_shape)`` float32 rows.
        Raises :class:`AdmissionError` (reason queue_full / tenant_quota /
        draining / oversized / bad_shape) or returns the result future."""
        x = np.asarray(x)
        self.stats.record_submit()
        try:
            if x.ndim != 1 + len(self.pool.sample_shape) or (
                tuple(x.shape[1:]) != self.pool.sample_shape
            ):
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"rows of shape {tuple(x.shape[1:])} != the served "
                    f"model's sample shape {self.pool.sample_shape}",
                )
            if x.dtype != np.float32:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"dtype {x.dtype} != float32",
                )
            if x.shape[0] < 1:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE, "empty request (0 rows)"
                )
            if x.shape[0] > self.scheduler.max_batch_size:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"{x.shape[0]} rows > max_batch_size="
                    f"{self.scheduler.max_batch_size}; split the request",
                )
            # own the rows: a client reusing (mutating) its submit buffer
            # must not rewrite a still-queued request's inputs
            request = Request(tenant, np.array(x, copy=True))
            self.queue.put(request)
        except AdmissionError as e:
            self.stats.record_reject(tenant, e.reason)
            raise
        return request.result

    # -------------------------------------------------------------- dispatch --
    def _dispatch_loop(self, replica: Replica) -> None:
        """One replica's life: pull, dispatch, deliver, repeat — exits when
        the queue closes and drains. A failed dispatch fails its batch's
        requests (never the loop): clients see the exception through their
        future, the next batch proceeds. ``unhealthy_after`` consecutive
        failures mark the replica unhealthy: with healthy peers remaining,
        this loop simply stops pulling (traffic continues on the peers);
        when it was the LAST healthy replica, the loop keeps pulling and
        fails batches immediately so queued clients get errors instead of a
        hung drain."""
        while True:
            batch = self.scheduler.next_batch()
            if batch is None:
                return
            if not replica.healthy:
                # only reachable when no healthy replica remains (see below)
                err = RuntimeError(
                    f"serving: replica {replica.index} is unhealthy and no "
                    "healthy replicas remain"
                )
                for r in batch.requests:
                    r.result._deliver(None, error=err)
                continue
            t_dispatch = time.perf_counter()
            try:
                logits = np.asarray(replica.infer(batch.x))  # fetch = fence
            except BaseException as e:  # noqa: BLE001 — delivered to clients
                logger.exception(
                    "serving: dispatch failed on replica %d", replica.index
                )
                replica.consecutive_errors += 1
                for r in batch.requests:
                    r.result._deliver(None, error=e)
                if self.writer is not None:
                    self.writer.write(
                        schema.stamp(
                            "event",
                            {
                                "event": "serving_dispatch_error",
                                "replica": replica.index,
                                "error": repr(e),
                                "requests": len(batch.requests),
                            },
                        )
                    )
                if (
                    self.unhealthy_after
                    and replica.healthy
                    and replica.consecutive_errors >= self.unhealthy_after
                ):
                    replica.healthy = False
                    logger.critical(
                        "serving: replica %d marked UNHEALTHY after %d "
                        "consecutive dispatch errors; routing stops",
                        replica.index, replica.consecutive_errors,
                    )
                    if self.writer is not None:
                        self.writer.write(
                            schema.stamp(
                                "event",
                                {
                                    "event": "replica_unhealthy",
                                    "replica": replica.index,
                                    "consecutive_errors":
                                        replica.consecutive_errors,
                                },
                            )
                        )
                    if any(r.healthy for r in self.pool.replicas):
                        return  # healthy peers keep serving; stop routing here
                    logger.critical(
                        "serving: NO healthy replicas remain; failing queued "
                        "requests instead of hanging the drain"
                    )
                    if self.flight is not None:
                        # serving dispatch death: the last windows + the
                        # dispatch-error/unhealthy events are in the ring
                        self.flight.dump("serving_dispatch")
                continue
            replica.consecutive_errors = 0
            t_done = time.perf_counter()
            for r, (lo, hi) in zip(batch.requests, batch.slices):
                # copy, don't view: a view would pin the whole padded
                # bucket's logits per result and alias clients to each other
                r.result._deliver(logits[lo:hi].copy())
            self.stats.record_batch(batch, t_dispatch, t_done)
