"""ServingEngine — queue + scheduler + replica pool + SLO stats, assembled.

One dispatch loop thread per replica pulls coalesced batches off the shared
scheduler and runs them on its own device; N replicas therefore serve N
batches genuinely concurrently (distinct devices, distinct programs) while
admission, fairness, and bucketing stay centralized. ``submit`` is the whole
client API: synchronous admission verdict (raises :class:`AdmissionError`
with a machine-readable reason), asynchronous result future.

Lifecycle: ``start()`` writes the ``run_meta`` header and compiles every
bucket program on every replica (warmup — the first real request never pays
a compile), ``drain()`` closes admission, lets the queued work finish,
flushes the final stats window, and stamps a ``serving_drain`` event. The
``__main__`` entrypoint maps SIGTERM onto drain + exit 75 — the resilience
exit-code contract (tpuddp/resilience/preemption.py), so schedulers treat a
draining server exactly like a draining trainer.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import List, Optional

import numpy as np

from tpuddp.observability import MetricsWriter, schema
from tpuddp.observability import trace as trace_lib
from tpuddp.resilience import faults
from tpuddp.serving import queue as queue_mod
from tpuddp.serving import survive as survive_lib
from tpuddp.serving.queue import AdmissionError, Request, RequestQueue, ServedResult
from tpuddp.serving.replica import Replica, ReplicaPool
from tpuddp.serving.scheduler import BatchScheduler
from tpuddp.serving.stats import ServingStats
from tpuddp.serving.survive import NoHealthyReplicaError, SurvivePolicy

logger = logging.getLogger("tpuddp")


class ServingEngine:
    """Continuous-batching inference over a replica pool. See module doc."""

    def __init__(
        self,
        pool: ReplicaPool,
        max_batch_size: int = 32,
        max_queue_depth: int = 256,
        per_tenant_quota: Optional[int] = None,
        batch_timeout_ms: float = 2.0,
        stats_window: int = 64,
        out_dir: Optional[str] = None,
        config: Optional[dict] = None,
        unhealthy_after: int = 3,
        observability: Optional[dict] = None,
        survive: Optional[SurvivePolicy] = None,
    ):
        """``unhealthy_after``: K consecutive dispatch errors mark a replica
        unhealthy — it leaves routing and enters probation (see ``survive``)
        while a ``replica_unhealthy`` event row lands in history.jsonl;
        healthy replicas keep serving and drain still exits cleanly. 0
        disables the marking (legacy behavior: each batch on the broken
        replica fails individually, forever).

        ``survive``: the survivability policy
        (:class:`~tpuddp.serving.survive.SurvivePolicy`): probation/recovery
        bounds for unhealthy replicas (jittered-backoff rebuild + canary,
        ``max_recoveries`` lifetime rejoins, permanent removal as the
        fallback), the admission-time request TTL, and the per-tenant
        transient-dispatch retry budget. None -> defaults (recovery on,
        TTL and retries off).

        ``observability``: the live-plane block (config.OBSERVABILITY_DEFAULTS
        shape): ``exporter: true`` serves /metrics from the SLO stats (last
        flushed window + cumulative counters — host dicts only, the dispatch
        hot path is untouched); the flight recorder tees every history record
        and dumps ``flightrec_serving_dispatch.json`` if the engine ever
        loses its last healthy replica."""
        from tpuddp import config as cfg_lib
        from tpuddp.observability import exporter as exp_lib
        from tpuddp.observability import flight as flight_lib

        self.pool = pool
        self.queue = RequestQueue(max_queue_depth, per_tenant_quota)
        self.scheduler = BatchScheduler(
            self.queue, max_batch_size, batch_timeout_ms
        )
        self.unhealthy_after = int(unhealthy_after or 0)
        self.survive = survive or SurvivePolicy()
        self.retry_budget = survive_lib.RetryBudget(self.survive.retry_budget)
        self.queue.shed_handler = self._on_shed
        self._health_lock = threading.Lock()
        self._batch_counter = itertools.count(1)  # chaos site batch=N
        self._obs_cfg = cfg_lib.resolve_observability(observability)
        # causal tracing plane (observability/trace.py, default OFF): one
        # span tree per request — request -> admission -> queue_wait ->
        # serve — plus per-replica infer/probation rows, exported as
        # trace_serving.json at drain and served live on /trace
        self.tracer = trace_lib.tracer_from_config(
            self._obs_cfg, "serving", run_dir=out_dir
        )
        self.flight = None
        if self._obs_cfg["flight_recorder"] and out_dir:
            self.flight = flight_lib.install(flight_lib.FlightRecorder(
                out_dir, capacity=int(self._obs_cfg["flight_capacity"]),
            ))
            if self.tracer.enabled:
                self.flight.add_context(
                    "open_spans", self.tracer.open_span_summaries
                )
        self.writer = (
            MetricsWriter(out_dir, flight=self.flight) if out_dir else None
        )
        self.stats = ServingStats(self.writer, window=stats_window)
        self.exporter = exp_lib.exporter_from_config(
            self._obs_cfg, run_dir=out_dir
        )
        self._config = dict(config or {})
        self._threads: List[threading.Thread] = []
        self._started = False
        self._drained = False

    @classmethod
    def from_config(
        cls, cfg: dict, out_dir: Optional[str] = None, devices=None,
        observability: Optional[dict] = None,
    ) -> "ServingEngine":
        """Build pool + engine from a ``serving`` config block
        (tpuddp/config.py:SERVING_DEFAULTS / serving_config); the optional
        ``observability`` block arms the exporter/flight recorder."""
        pool = ReplicaPool.from_config(cfg, devices=devices)
        quota = cfg.get("per_tenant_quota")
        return cls(
            pool,
            max_batch_size=int(cfg["max_batch_size"]),
            max_queue_depth=int(cfg["max_queue_depth"]),
            per_tenant_quota=None if quota is None else int(quota),
            batch_timeout_ms=float(cfg["batch_timeout_ms"]),
            stats_window=int(cfg["stats_window"]),
            out_dir=out_dir,
            config=cfg,
            unhealthy_after=int(cfg.get("unhealthy_after", 3) or 0),
            observability=observability,
            survive=SurvivePolicy.from_config(cfg),
        )

    # ------------------------------------------------------------- lifecycle --
    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._started:
            return self
        from tpuddp import config as cfg_lib
        if self.exporter is not None:
            # bind before the header so run_meta records the real port
            self.exporter.start()
            self.exporter.register_source(
                "serving", self.stats.export_source(engine=self)
            )
            if self.tracer.enabled:
                self.exporter.set_trace_source(self.tracer.endpoint_payload)
        if self.writer is not None:
            cfg = self._config
            self.writer.write(
                schema.make_run_meta(
                    world_size=len(self.pool),
                    comm_hook=None,
                    guard=None,
                    observability={
                        "exporter": (
                            self.exporter.describe()
                            if self.exporter is not None
                            else False
                        ),
                        "aggregate": False,  # no pod axis on the serving path
                        "flight_recorder": (
                            self.flight.describe()
                            if self.flight is not None
                            else False
                        ),
                    },
                    survivability=self.survive.meta(),
                    tracing=self.tracer.describe(),
                    # v12: overlay provenance (null = no tune overlay)
                    tuning=cfg_lib.tuning_provenance_from_env("serving"),
                    extra={
                        "api": "serving",
                        "model": cfg.get("model"),
                        "num_replicas": len(self.pool),
                        "max_batch_size": self.scheduler.max_batch_size,
                        "max_queue_depth": self.queue.max_depth,
                        "per_tenant_quota": self.queue.per_tenant_quota,
                        "batch_timeout_ms": (
                            self.scheduler.batch_timeout_s * 1e3
                        ),
                        "buckets": self.scheduler.buckets,
                        "input_shape": list(self.pool.sample_shape),
                        "restored_epoch": self.pool.restored_epoch,
                        "checkpoint_dir": cfg.get("checkpoint_dir"),
                        "config_hash": schema.config_hash(cfg or None),
                    },
                )
            )
        if warmup:
            t0 = time.perf_counter()
            self.pool.warmup(self.scheduler.buckets)
            logger.info(
                "serving: %d replica(s) warm over buckets %s in %.1fs",
                len(self.pool), self.scheduler.buckets,
                time.perf_counter() - t0,
            )
        # window 0's throughput must measure serving, not bucket compiles
        self.stats.reset_clock()
        for replica in self.pool.replicas:
            t = threading.Thread(
                target=self._dispatch_loop,
                args=(replica,),
                name=f"tpuddp-serve-r{replica.index}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def drain(self, reason: str = "shutdown", timeout: Optional[float] = None) -> dict:
        """Close admission, finish queued + in-flight work, flush stats.
        Idempotent; returns the final :meth:`ServingStats.summary`.

        With a ``timeout``, dispatch threads may outlive the join — then the
        stats are NOT finalized and the writer stays open (the still-running
        loops keep recording honestly); call ``drain`` again to finish."""
        self.queue.close()
        for t in self._threads:
            t.join(timeout)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "serving: dispatch thread(s) %s still running after the "
                "drain timeout; stats not finalized yet", alive,
            )
            return self.stats.summary()
        if not self._drained:
            self._drained = True
            self.stats.flush_window()
            if self.tracer.enabled:
                if self.writer is not None:
                    self.writer.write(schema.stamp(
                        "trace_summary", self.tracer.summary_record()
                    ))
                self.tracer.export()
            if self.writer is not None:
                self.writer.write(
                    schema.stamp(
                        "event",
                        {
                            "event": "serving_drain",
                            "reason": reason,
                            **{
                                k: v
                                for k, v in self.stats.summary().items()
                                if k in (
                                    "submitted", "completed", "rejected",
                                    "batches", "throughput_rps",
                                )
                            },
                        },
                    )
                )
                self.writer.close()
            if self.exporter is not None:
                self.exporter.stop()
            if self.flight is not None:
                from tpuddp.observability import flight as flight_lib

                flight_lib.uninstall(self.flight)
        return self.stats.summary()

    # --------------------------------------------------------------- client --
    def _trace_close(self, request, error) -> None:
        """Close a failed/shed request's trace (the shared
        :func:`~tpuddp.observability.trace.end_request_trace` sequence)."""
        trace_lib.end_request_trace(self.tracer, request, error)

    def _on_shed(self, request) -> None:
        """Queue shed callback: one queued request expired past its deadline
        and was dropped before dispatch (its future already carries the
        typed ``deadline_exceeded`` rejection). A shed request LEAVES the
        system — any retry tokens it consumed while bouncing off a failed
        dispatch are refunded, like every other exit path."""
        if getattr(request, "retries", 0):
            self.retry_budget.refund(request.tenant, request.retries)
            request.retries = 0
        self._trace_close(request, "deadline_exceeded")
        self.stats.record_shed(request.tenant)

    def submit(
        self, tenant: str, x: np.ndarray, deadline_s: Optional[float] = None
    ) -> ServedResult:
        """Admit one request of ``(rows, *sample_shape)`` float32 rows.
        Raises :class:`AdmissionError` (reason queue_full / tenant_quota /
        draining / oversized / bad_shape) or returns the result future.

        ``deadline_s``: optional client deadline (seconds from now). The
        effective deadline is the tighter of it and the engine's
        ``request_ttl_s``; a request still QUEUED past it is shed with a
        ``deadline_exceeded`` rejection delivered through the future —
        work already dispatched always completes."""
        x = np.asarray(x)
        self.stats.record_submit()
        t = self.tracer
        root = t.start_span(
            "request", trace_lib.KIND_REQUEST, tid="client",
            attrs={"tenant": str(tenant)},
        )
        adm = t.start_span("admission", trace_lib.KIND_ADMISSION, parent=root)
        request = None
        try:
            if x.ndim != 1 + len(self.pool.sample_shape) or (
                tuple(x.shape[1:]) != self.pool.sample_shape
            ):
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"rows of shape {tuple(x.shape[1:])} != the served "
                    f"model's sample shape {self.pool.sample_shape}",
                )
            if x.dtype != np.float32:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"dtype {x.dtype} != float32",
                )
            if x.shape[0] < 1:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE, "empty request (0 rows)"
                )
            if x.shape[0] > self.scheduler.max_batch_size:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"{x.shape[0]} rows > max_batch_size="
                    f"{self.scheduler.max_batch_size}; split the request",
                )
            # own the rows: a client reusing (mutating) its submit buffer
            # must not rewrite a still-queued request's inputs
            request = Request(
                tenant,
                np.array(x, copy=True),
                deadline=survive_lib.admission_deadline(
                    time.perf_counter(), self.survive.request_ttl_s, deadline_s
                ),
            )
            t.end_span(adm, rows=int(x.shape[0]), request=request.id)
            if t.enabled:
                # attach BEFORE put: the instant put() publishes the request
                # a dispatcher may take and serve it — a trace attached
                # after would race the dispatch and leak a never-closed
                # queue_wait. The trace then rides the queue on the request
                # itself, so retries and failovers extend the SAME tree.
                request.trace = {
                    "root": root,
                    "open": t.start_span(
                        "queue_wait", trace_lib.KIND_QUEUE_WAIT, parent=root,
                    ),
                }
            self.queue.put(request)
        except AdmissionError as e:
            if request is not None and request.trace:
                t.end_span(request.trace["open"], error=e.reason)
                request.trace = None
            t.end_span(adm, rejected=e.reason)
            t.end_span(root, error=e.reason)
            self.stats.record_reject(tenant, e.reason)
            raise
        return request.result

    # -------------------------------------------------------------- dispatch --
    def _event(self, record: dict) -> None:
        if self.writer is not None:
            self.writer.write(schema.stamp("event", record))

    def _dispatch_loop(self, replica: Replica) -> None:
        """One replica's life: pull, dispatch, deliver, repeat — exits when
        the queue closes and drains. A failed dispatch retries its batch's
        requests within the per-tenant retry budget (they re-enter the
        queue and another — or the recovered — replica serves them) and
        fails the rest through their futures; the loop itself never dies on
        a dispatch. ``unhealthy_after`` consecutive failures put the
        replica on PROBATION: a bounded jittered-backoff recovery loop
        (rebuild + re-warm + canary) runs here, off the serving path, and
        the replica rejoins routing only after the canary passes
        (``replica_recovered`` event). Probation exhausted -> permanent
        removal: with surviving peers this thread exits (traffic continues
        on them); as the LAST replica, after that one recovery round, the
        loop keeps pulling and fails everything with the typed
        ``no_healthy_replica`` reason — queued clients get machine-readable
        errors, never a hung drain."""
        replica.loop_alive = True
        try:
            self._dispatch_loop_body(replica)
        finally:
            replica.loop_alive = False

    def _dispatch_loop_body(self, replica: Replica) -> None:
        while True:
            batch = self.scheduler.next_batch()
            if batch is None:
                return
            if replica.state == "removed":
                # mortuary mode — only reachable when no servable replica
                # remains and the recovery round already failed
                err = NoHealthyReplicaError(
                    f"replica {replica.index} is removed and no healthy "
                    "replicas remain"
                )
                for r in batch.requests:
                    self._fail_request(r, err)
                continue
            t_dispatch = time.perf_counter()
            t = self.tracer
            for r in batch.requests:
                if r.trace:
                    # queue wait is over; the serve interval (dispatch ->
                    # delivery, shared by the whole coalesced batch) begins
                    t.end_span(r.trace["open"])
                    r.trace["open"] = t.start_span(
                        "serve", trace_lib.KIND_SERVE, parent=r.trace["root"],
                        attrs={"replica": replica.index},
                    )
            bsp = t.start_span(
                "infer", trace_lib.KIND_DISPATCH,
                tid=f"replica{replica.index}",
                attrs={
                    "requests": len(batch.requests),
                    "rows": int(batch.x.shape[0]),
                },
            )
            try:
                kind = faults.maybe_serving_fault(
                    "batch", batch=next(self._batch_counter)
                )
                if kind == "replica_kill":
                    replica.broken = True  # persistent until rebuild()
                if kind == "dispatch_wedge":
                    raise RuntimeError(
                        "injected dispatch_wedge fault (transient)"
                    )
                logits = np.asarray(replica.infer(batch.x))  # fetch = fence
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — retried or delivered
                t.end_span(bsp, error=repr(e))
                self._dispatch_failed(replica, batch, e)
                if replica.state == "recovering" and not self._probation(replica):
                    with self._health_lock:
                        survivors = survive_lib.live_survivors(
                            self.pool.replicas, replica
                        )
                    if survivors:
                        return  # peers own the traffic; this thread is done
                    logger.critical(
                        "serving: NO healthy replicas remain after the "
                        "recovery round; failing queued requests with "
                        "reason no_healthy_replica instead of hanging"
                    )
                    self._event({
                        "event": "no_healthy_replica",
                        "replica": replica.index,
                    })
                    if self.flight is not None:
                        # serving dispatch death: the last windows + the
                        # dispatch-error/unhealthy events are in the ring
                        self.flight.dump("serving_dispatch")
                continue
            t.end_span(bsp)
            replica.consecutive_errors = 0
            for r in batch.requests:
                if r.retries:
                    # a retried request made it: return its tokens so a
                    # transient blip never permanently drains the tenant
                    self.retry_budget.refund(r.tenant, r.retries)
                    r.retries = 0
            t_done = time.perf_counter()
            for r, (lo, hi) in zip(batch.requests, batch.slices):
                # copy, don't view: a view would pin the whole padded
                # bucket's logits per result and alias clients to each other
                r.result._deliver(logits[lo:hi].copy())
                if r.trace:
                    t.end_span(r.trace["open"])
                    t.end_span(r.trace["root"], rows=hi - lo)
                    r.trace = None
            self.stats.record_batch(batch, t_dispatch, t_done)

    def _fail_request(self, r, error: BaseException) -> None:
        """Fail one request through its future — refunding any retry
        tokens it consumed first: the budget bounds retries PER REQUEST,
        and a request leaving the system (success or failure alike) must
        not drain the tenant's budget for unrelated future work."""
        if r.retries:
            self.retry_budget.refund(r.tenant, r.retries)
            r.retries = 0
        self._trace_close(r, error)
        r.result._deliver(None, error=error)

    def _dispatch_failed(self, replica: Replica, batch, e: BaseException) -> None:
        """One failed dispatch: retry the batch's requests within the
        per-tenant budget (re-queued at lane front; any replica may pick
        them up), fail the rest, and cross into probation at the
        ``unhealthy_after`` threshold."""
        logger.exception(
            "serving: dispatch failed on replica %d", replica.index
        )
        replica.consecutive_errors += 1
        retried = 0
        for r in batch.requests:
            if self.retry_budget.try_consume(r.tenant):
                r.retries += 1
                retried += 1
                self.stats.record_retry(r.tenant)
                if r.trace:
                    # the failed serve attempt closes; a fresh queue_wait
                    # follows from it — the retry stays one trace
                    failed = r.trace["open"]
                    self.tracer.end_span(
                        failed, error="dispatch_failed", retry=r.retries,
                    )
                    r.trace["open"] = self.tracer.start_span(
                        "queue_wait", trace_lib.KIND_QUEUE_WAIT,
                        parent=r.trace["root"],
                        follows_from=failed.span_id,
                    )
                self.queue.requeue(r)
            else:
                self._fail_request(r, e)
        self._event({
            "event": "serving_dispatch_error",
            "replica": replica.index,
            "error": repr(e),
            "requests": len(batch.requests),
            "retried": retried,
        })
        if (
            self.unhealthy_after
            and replica.state == "healthy"
            and replica.consecutive_errors >= self.unhealthy_after
        ):
            replica.state = "recovering"
            logger.critical(
                "serving: replica %d marked UNHEALTHY after %d consecutive "
                "dispatch errors; entering probation",
                replica.index, replica.consecutive_errors,
            )
            self._event({
                "event": "replica_unhealthy",
                "replica": replica.index,
                "consecutive_errors": replica.consecutive_errors,
            })

    def _probation(self, replica: Replica) -> bool:
        """One probation episode for an unhealthy replica. True = it passed
        (rebuilt, re-warmed, canary served finite logits) and rejoined
        routing; False = it is permanently removed (``max_recoveries``
        lifetime episodes spent, or every in-episode attempt failed)."""

        def recover():
            replica.rebuild()
            replica.warmup(self.scheduler.buckets, self.pool.sample_shape)
            replica.canary(self.pool.sample_shape)

        psp = self.tracer.start_span(
            f"probation replica {replica.index}", trace_lib.KIND_PROBATION,
            tid=f"replica{replica.index}",
            attrs={"recoveries": replica.recoveries},
        )
        ok, event = survive_lib.probation_episode(
            replica,
            name=f"serving replica {replica.index}",
            recover=recover,
            policy=self.survive,
            lock=self._health_lock,
        )
        self.tracer.end_span(psp, outcome="recovered" if ok else "removed")
        if ok:
            replica.consecutive_errors = 0
        self._event(event)
        return ok
