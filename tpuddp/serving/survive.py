"""Serving survivability — probation, retry budgets, deadlines, typed failure.

The serving plane's original failure story was terminal: an unhealthy
replica was removed forever, every in-flight decode stream riding it died,
and overload had no deadline semantics at all. This module holds the shared
policy pieces the two engines (request-granularity ``serving/engine.py``,
token-level ``serving/decode/engine.py``) thread through their dispatch
loops to invert that:

- **Probation & recovery** (:func:`run_probation`): an unhealthy replica is
  not removed — it enters a bounded recovery loop (rebuild its device state,
  re-warm, probe with a canary dispatch) with the jittered exponential
  backoff of ``resilience/retry.py``, and rejoins routing only after the
  canary passes. Permanent removal is the *fallback* (``max_recoveries``
  lifetime episodes exhausted, or every in-episode attempt failed), not the
  policy. The replica state machine is::

      healthy --incident--> recovering --canary ok--> healthy   (rejoin)
                                |
                                +--attempts/max_recoveries exhausted--> removed

- **Retry budgets** (:class:`RetryBudget`): a transient dispatch failure
  costs the affected tenant one retry token and re-enters the queue instead
  of surfacing to the client; a retried request that finally succeeds
  refunds its tokens, so only *sustained* failure exhausts the budget and
  fails through.

- **Deadlines** (:func:`admission_deadline`): every request can carry an
  absolute deadline — the minimum of an admission-time TTL
  (``request_ttl_s``) and an optional per-call client deadline. Expired
  work still *queued* is shed with a machine-readable ``deadline_exceeded``
  rejection before it wastes device time; work already in flight is NEVER
  killed by its deadline (a stream that started is finished).

- **Typed terminal failure** (:class:`NoHealthyReplicaError`): when the
  last replica's recovery is exhausted, queued and parked work fails with
  ``reason == "no_healthy_replica"`` — machine-readable, and never a hang.

Everything here is pure host-side policy: no jax, no devices — the engines
own the device-facing rebuild/canary callables.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
from typing import Callable, Dict, Optional, Tuple

from tpuddp.resilience.retry import RetryError, RetryPolicy, retry

logger = logging.getLogger("tpuddp")

# The machine-readable reason carried by NoHealthyReplicaError and the
# typed event row the engines land when the pool dies.
REASON_NO_HEALTHY_REPLICA = "no_healthy_replica"

# Replica survivability states (Replica.state / DecodeReplica.state).
STATE_HEALTHY = "healthy"
STATE_RECOVERING = "recovering"
STATE_REMOVED = "removed"


class NoHealthyReplicaError(RuntimeError):
    """Terminal serving failure: every replica is removed and at least one
    recovery round was attempted. ``reason`` is machine-readable (clients
    and tests dispatch on it, not the message)."""

    reason = REASON_NO_HEALTHY_REPLICA

    def __init__(self, detail: str):
        super().__init__(f"request failed ({self.reason}): {detail}")


@dataclasses.dataclass(frozen=True)
class SurvivePolicy:
    """The survivability knob block (config keys of the ``serving`` /
    ``serving.decode`` blocks; see README "Serving survivability").

    ``request_ttl_s``: admission-time TTL applied to every request (None =
    no TTL; clients can still pass a per-call deadline).
    ``max_recoveries``: lifetime probation episodes per replica; past it an
    incident removes the replica permanently (0 = legacy remove-on-first).
    ``recovery_attempts``: rebuild+canary tries within one episode.
    ``recovery_backoff_s``: base of the jittered exponential backoff
    between in-episode tries (resilience/retry.py semantics).
    ``retry_budget``: per-tenant transient-dispatch retry tokens for the
    request-granularity engine (0 = off; the decode engine's failover
    journal makes per-request retries redundant there).
    ``max_failovers``: per-SESSION failover episodes (decode): a sequence
    that has already been parked this many times is failed with the
    dispatch error instead of re-parked. This is the poisoned-request
    firewall — a request whose OWN content deterministically kills any
    dispatch must not ride its journal around the pool burning every
    replica's probation budget (0 = never re-park: legacy stream-dies
    behavior)."""

    request_ttl_s: Optional[float] = None
    max_recoveries: int = 2
    recovery_attempts: int = 2
    recovery_backoff_s: float = 0.1
    retry_budget: int = 0
    max_failovers: int = 1

    def __post_init__(self):
        if self.request_ttl_s is not None and self.request_ttl_s <= 0:
            raise ValueError(
                f"request_ttl_s must be > 0 or None, got {self.request_ttl_s}"
            )
        if self.max_recoveries < 0:
            raise ValueError(
                f"max_recoveries must be >= 0, got {self.max_recoveries}"
            )
        if self.recovery_attempts < 1:
            raise ValueError(
                f"recovery_attempts must be >= 1, got {self.recovery_attempts}"
            )
        if self.recovery_backoff_s < 0:
            raise ValueError(
                f"recovery_backoff_s must be >= 0, got {self.recovery_backoff_s}"
            )
        if self.retry_budget < 0:
            raise ValueError(
                f"retry_budget must be >= 0, got {self.retry_budget}"
            )
        if self.max_failovers < 0:
            raise ValueError(
                f"max_failovers must be >= 0, got {self.max_failovers}"
            )

    @classmethod
    def from_config(cls, cfg: dict) -> "SurvivePolicy":
        """Pull the survivability keys out of a resolved ``serving`` /
        ``serving.decode`` block (missing keys take the defaults, so stale
        config dicts built before this layer keep working)."""
        ttl = cfg.get("request_ttl_s")
        return cls(
            request_ttl_s=None if ttl is None else float(ttl),
            max_recoveries=int(cfg.get("max_recoveries", 2)),
            recovery_attempts=int(cfg.get("recovery_attempts", 2)),
            recovery_backoff_s=float(cfg.get("recovery_backoff_s", 0.1)),
            retry_budget=int(cfg.get("retry_budget") or 0),
            max_failovers=int(cfg.get("max_failovers", 1)),
        )

    def meta(self) -> dict:
        """The run_meta ``survivability`` provenance block (schema v7)."""
        return dataclasses.asdict(self)


def admission_deadline(
    t_enqueue: float,
    ttl_s: Optional[float],
    deadline_s: Optional[float],
) -> Optional[float]:
    """Absolute deadline (perf_counter seconds) for a request admitted at
    ``t_enqueue``: the tighter of the engine TTL and the client's own
    deadline, or None when neither applies."""
    bounds = [b for b in (ttl_s, deadline_s) if b is not None]
    if not bounds:
        return None
    if min(bounds) < 0:
        raise ValueError(f"deadline must be >= 0, got {min(bounds)}")
    return t_enqueue + min(bounds)


class RetryBudget:
    """Per-tenant transient-dispatch retry tokens.

    ``try_consume`` takes one token (False when the tenant is exhausted —
    the caller fails the request through instead of retrying);
    ``refund`` returns tokens when a retried request LEAVES the system —
    success or failure-through alike — so the budget bounds how many
    retries any one request may consume, never how many the tenant gets
    for the engine's lifetime (a request that burned its retries and
    failed must not disable retries for the tenant's next, unrelated
    request hours later). ``limit <= 0`` disables retries entirely."""

    def __init__(self, limit: int):
        self.limit = int(limit)
        self._lock = threading.Lock()
        self._used: Dict[str, int] = {}

    def try_consume(self, tenant: str) -> bool:
        if self.limit <= 0:
            return False
        with self._lock:
            used = self._used.get(tenant, 0)
            if used >= self.limit:
                return False
            self._used[tenant] = used + 1
            return True

    def refund(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            used = self._used.get(tenant, 0)
            self._used[tenant] = max(0, used - int(n))

    def used(self, tenant: str) -> int:
        with self._lock:
            return self._used.get(tenant, 0)


def run_probation(
    *,
    name: str,
    recover: Callable[[], None],
    policy: SurvivePolicy,
    sleep=None,
) -> bool:
    """One probation episode: call ``recover()`` (rebuild + canary; raises
    on failure) up to ``policy.recovery_attempts`` times with jittered
    exponential backoff. True = the replica passed probation and may rejoin
    routing; False = the episode is exhausted (the caller decides between
    another episode and permanent removal via ``max_recoveries``)."""
    retry_policy = RetryPolicy(
        max_attempts=policy.recovery_attempts,
        base_delay=policy.recovery_backoff_s,
        max_delay=max(policy.recovery_backoff_s, 5.0),
        jitter=0.5,
    )
    kwargs = {} if sleep is None else {"sleep": sleep}
    try:
        retry(
            recover,
            retry_policy,
            describe=f"{name} probation (rebuild + canary)",
            **kwargs,
        )
        return True
    except RetryError as e:
        logger.critical("%s failed probation: %s", name, e)
        return False


def probation_episode(
    replica,
    *,
    name: str,
    recover: Callable[[], None],
    policy: SurvivePolicy,
    count_recovery: bool = True,
    lock=None,
) -> Tuple[bool, dict]:
    """The whole incident->probation outcome both engines share: check the
    lifetime budget, run one :func:`run_probation` episode, transition
    ``replica.state`` (under ``lock`` when given), and return
    ``(rejoined, event)`` — the typed ``replica_recovered`` /
    ``replica_removed`` record for the caller's history writer.

    ``replica`` is any object with ``index`` / ``state`` / ``recoveries``.
    ``count_recovery=False`` passes probation WITHOUT charging the
    replica's lifetime ``max_recoveries`` budget — the request-attributed
    incident case, where a passed canary proves the device was never the
    problem (the request's own failover budget bounds the culprit)."""
    allowed = replica.recoveries < policy.max_recoveries
    ok = allowed and run_probation(name=name, recover=recover, policy=policy)
    ctx = lock if lock is not None else contextlib.nullcontext()
    if ok:
        if count_recovery:
            replica.recoveries += 1
        with ctx:
            replica.state = STATE_HEALTHY
        logger.warning(
            "%s passed probation (recovery %d/%d); rejoining routing",
            name, replica.recoveries, policy.max_recoveries,
        )
        return True, {
            "event": "replica_recovered",
            "replica": replica.index,
            "recoveries": replica.recoveries,
        }
    with ctx:
        replica.state = STATE_REMOVED
    return False, {
        "event": "replica_removed",
        "replica": replica.index,
        "recoveries": replica.recoveries,
        "reason": "probation_failed" if allowed else "max_recoveries",
    }


def live_survivors(replicas, me) -> bool:
    """True when any OTHER replica can still own traffic: not removed AND
    its loop thread is running (``loop_alive``) — at drain, peers exit
    once the queue looks drained, and handing journals or retried work to
    an exited loop strands the futures forever. Callers hold their own
    health lock."""
    return any(
        r.state != STATE_REMOVED and getattr(r, "loop_alive", False)
        for r in replicas
        if r is not me
    )
