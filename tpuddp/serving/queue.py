"""Request queue + admission control — the front door of the serving engine.

Admission is decided synchronously at ``put`` time against two bounds: a
global queue depth (beyond it the engine is overloaded and honest rejection
beats unbounded latency) and an optional per-tenant quota (one tenant's
flood must not evict everyone else's capacity). Rejections raise
:class:`AdmissionError` carrying a machine-readable ``reason`` so callers
(and the SLO stats) can distinguish "back off" from "you sent garbage".

Fairness: requests are kept in per-tenant FIFO lanes and drained round-robin
— each assembled batch takes at most one head-of-lane request per tenant per
pass, so a tenant queueing 100 requests cannot make another tenant's single
request wait behind all 100. Within a tenant, order is strictly FIFO.

Everything here is plain ``threading`` — dispatch loops (one per replica)
block on the queue's condition variable; device work never holds the lock.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

import numpy as np

from tpuddp.utils import batching

# Machine-readable admission-rejection reasons (the `reason` field of
# AdmissionError and the per-reason reject counters in ServingStats).
REJECT_QUEUE_FULL = "queue_full"  # global max_queue_depth reached
REJECT_TENANT_QUOTA = "tenant_quota"  # this tenant's quota reached
REJECT_DRAINING = "draining"  # engine is shutting down; no new admissions
REJECT_OVERSIZED = "oversized"  # more rows than max_batch_size can ever hold
REJECT_BAD_SHAPE = "bad_shape"  # sample shape/dtype != the served model's
REJECT_DEADLINE = "deadline_exceeded"  # queued past its deadline; shed
# before dispatch (load shedding, tpuddp/serving/survive.py) — work already
# IN FLIGHT is never killed by a deadline

REJECT_REASONS = (
    REJECT_QUEUE_FULL,
    REJECT_TENANT_QUOTA,
    REJECT_DRAINING,
    REJECT_OVERSIZED,
    REJECT_BAD_SHAPE,
    REJECT_DEADLINE,
)


class AdmissionError(RuntimeError):
    """A request the engine refused to admit. ``reason`` is one of
    :data:`REJECT_REASONS`; the message carries the human detail."""

    def __init__(self, reason: str, detail: str):
        assert reason in REJECT_REASONS, reason
        self.reason = reason
        super().__init__(f"request rejected ({reason}): {detail}")


class ServedResult:
    """Future for one request's logits.

    ``result(timeout)`` blocks until the dispatch loop delivers; a failed
    dispatch delivers the exception instead, so a caller never hangs on a
    batch that died. ``done_at`` (perf_counter seconds) is stamped at
    delivery — the timestamp load generators difference against their own
    submit time for end-to-end latency without a callback in the hot path."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self.done_at: Optional[float] = None

    def _deliver(self, value: Optional[np.ndarray], error=None) -> None:
        self._value = value
        self._error = error
        self.done_at = time.perf_counter()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within the timeout")
        if self._error is not None:
            raise self._error
        return self._value


_ids = itertools.count()


class Request:
    """One admitted inference request: ``x`` is a ``(rows, *sample_shape)``
    host batch (rows >= 1, variable per request); results arrive on
    ``result``. ``key`` buckets by per-SAMPLE shape+dtype (rows concatenate
    across requests, so the batch axis is not part of the key).

    ``deadline`` (absolute perf_counter seconds, or None) arms load
    shedding: a request still queued past it is shed with reason
    ``deadline_exceeded`` instead of dispatched. ``retries`` counts how
    many times a transient dispatch failure re-queued this request (the
    per-tenant :class:`~tpuddp.serving.survive.RetryBudget` bounds it)."""

    __slots__ = (
        "id", "tenant", "x", "rows", "key", "t_enqueue", "result",
        "deadline", "retries", "resume_tokens", "trace",
    )

    def __init__(self, tenant: str, x: np.ndarray, deadline: Optional[float] = None):
        self.id = next(_ids)
        self.tenant = str(tenant)
        self.x = x
        self.rows = int(x.shape[0])
        self.key = (batching.shape_key(x)[0][1:], str(x.dtype))
        self.t_enqueue = time.perf_counter()
        self.result = ServedResult()
        self.deadline = deadline
        self.retries = 0
        # non-None marks a failover journal (a live session mid-migration,
        # decode engine); journals are in-flight work and are never shed
        self.resume_tokens = None
        # causal-tracing context (observability/trace.py; None = tracing
        # off): the engine hangs the request's root span + the currently
        # open child here so the request's whole life — admission, queue
        # wait, dispatch, retries, failover — stays ONE trace
        self.trace = None


class RequestQueue:
    """Bounded multi-tenant queue with round-robin draining.

    ``take_group(max_rows, top_up_wait)`` is the dispatch-loop primitive:
    block until work exists, then assemble up to ``max_rows`` rows of
    same-key requests round-robin across tenant lanes; optionally linger
    ``top_up_wait`` seconds to coalesce late arrivals into the same batch
    (the latency/occupancy knob). Returns ``None`` only when the queue is
    closed AND empty — the dispatch loop's exit signal."""

    def __init__(self, max_depth: int, per_tenant_quota: Optional[int] = None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if per_tenant_quota is not None and per_tenant_quota < 1:
            raise ValueError(
                f"per_tenant_quota must be >= 1 or None, got {per_tenant_quota}"
            )
        self.max_depth = int(max_depth)
        self.per_tenant_quota = (
            int(per_tenant_quota) if per_tenant_quota is not None else None
        )
        self._lanes: "OrderedDict[str, deque]" = OrderedDict()
        self._rr: int = 0  # round-robin cursor into the lane ordering
        self._depth = 0
        self._closed = False
        self._cond = threading.Condition()
        # load shedding (tpuddp/serving/survive.py): requests whose deadline
        # expired while still queued are dropped at assembly time — their
        # futures get a typed AdmissionError(deadline_exceeded), and the
        # engine's optional handler records the shed in its SLO stats. A
        # request holding a failover journal (resume_tokens set — a live
        # session mid-migration) is never shed: it is in-flight work.
        self.shed_handler = None  # optional callable(request)

    # ---------------------------------------------------------- admission --
    def put(self, request: Request) -> None:
        """Admit or raise :class:`AdmissionError` (synchronously — the caller
        knows the verdict before ``put`` returns)."""
        with self._cond:
            if self._closed:
                raise AdmissionError(
                    REJECT_DRAINING, "the engine is draining; no new admissions"
                )
            if self._depth >= self.max_depth:
                raise AdmissionError(
                    REJECT_QUEUE_FULL,
                    f"queue depth {self._depth} is at max_queue_depth="
                    f"{self.max_depth}",
                )
            lane = self._lanes.get(request.tenant)
            if (
                self.per_tenant_quota is not None
                and lane is not None
                and len(lane) >= self.per_tenant_quota
            ):
                raise AdmissionError(
                    REJECT_TENANT_QUOTA,
                    f"tenant {request.tenant!r} has {len(lane)} queued "
                    f"requests, at per_tenant_quota={self.per_tenant_quota}",
                )
            if lane is None:
                lane = self._lanes[request.tenant] = deque()
            lane.append(request)
            self._depth += 1
            # notify_all, not notify: a single wakeup can land on a thread
            # mid-linger whose batch cannot take this request (rows don't
            # fit), leaving an IDLE replica asleep while admitted work sits
            # queued. Waiter count == replica count, so the broadcast is
            # cheap.
            self._cond.notify_all()

    def requeue(self, request) -> None:
        """Return an already-admitted request to the FRONT of its tenant
        lane — the transient-retry / session-failover path. Bypasses
        admission control entirely (depth bound, quota, and the closed
        flag): the request was admitted once and is owed service, even by a
        draining engine whose replica died mid-stream."""
        with self._cond:
            lane = self._lanes.get(request.tenant)
            if lane is None:
                lane = self._lanes[request.tenant] = deque()
            lane.appendleft(request)
            self._depth += 1
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admissions; queued work still drains. Wakes every waiter."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._cond:
            return self._depth

    def tenant_depth(self, tenant: str) -> int:
        with self._cond:
            lane = self._lanes.get(tenant)
            return len(lane) if lane else 0

    def tenant_depths(self) -> dict:
        """Current queued-request count per tenant lane (the exporter's
        per-tenant queue-depth gauge) — one lock hold for the whole view."""
        with self._cond:
            return {t: len(lane) for t, lane in self._lanes.items() if lane}

    # ------------------------------------------------------------ draining --
    @staticmethod
    def _expired(request, now: float) -> bool:
        """Queued-deadline check. A failover journal (``resume_tokens`` not
        None — a live session awaiting migration) is in-flight work and is
        exempt: deadlines shed queued work only, never kill a stream."""
        return (
            getattr(request, "deadline", None) is not None
            and now > request.deadline
            and getattr(request, "resume_tokens", None) is None
        )

    def _assemble(
        self, max_rows: int, key=None, shed: Optional[List[Request]] = None
    ) -> Tuple[List[Request], Optional[tuple]]:
        """Pop up to ``max_rows`` rows of ``key``-matching requests,
        round-robin across tenant lanes (at most one request per tenant per
        pass). Caller holds the lock. The first pop fixes ``key`` when None.
        A lane whose head doesn't match (different sample shape, or too many
        rows to fit the remaining budget) is skipped, not popped — per-tenant
        FIFO order is never reordered. Expired heads are popped into
        ``shed`` (never dispatched); the caller delivers their typed
        rejections OUTSIDE the lock."""
        taken: List[Request] = []
        rows = 0
        now = time.perf_counter()
        while True:
            lanes = list(self._lanes.keys())
            if not lanes:
                break
            took_this_pass = False
            n = len(lanes)
            start = self._rr % n  # fixed for the pass — the cursor must not
            # move under the iteration, or one tenant gets visited twice
            for i in range(n):
                name = lanes[(start + i) % n]
                lane = self._lanes.get(name)
                if not lane:
                    continue
                # shed expired work before it can cost a dispatch — the
                # deadline contract: queued-expired is rejected, in-flight
                # is untouchable
                while shed is not None and lane and self._expired(lane[0], now):
                    shed.append(lane.popleft())
                    self._depth -= 1
                if not lane:
                    del self._lanes[name]
                    continue
                head = lane[0]
                if key is not None and head.key != key:
                    continue
                if rows + head.rows > max_rows:
                    continue
                lane.popleft()
                self._depth -= 1
                if not lane:
                    del self._lanes[name]
                taken.append(head)
                rows += head.rows
                key = key if key is not None else head.key
                took_this_pass = True
                # the NEXT pass / NEXT batch starts with this tenant's
                # successor (by pass position; lane deletions shift the
                # ordering slightly, which only rotates the start — every
                # still-populated lane is visited exactly once per pass)
                self._rr = (start + i + 1) % n
                if rows >= max_rows:
                    return taken, key
            if not took_this_pass:
                break
        return taken, key

    def take_group(
        self, max_rows: int, top_up_wait: float = 0.0, wait: bool = True
    ) -> Optional[List[Request]]:
        """Block for work, then assemble one same-key group (see class doc).
        ``None`` = closed and fully drained. ``wait=False`` never blocks:
        an open-but-empty queue returns ``[]`` — the decode loop's
        between-steps poll (it must keep stepping its active sequences, not
        sleep on the condition variable, while the queue is empty).
        Expired queued requests encountered during assembly are shed
        (typed ``deadline_exceeded`` delivered to their futures after the
        lock is released — never dispatched). The delivery happens BEFORE
        the loop can re-block on the condition variable: a shed client's
        verdict must not wait for the next arrival (or drain) to wake this
        thread."""
        while True:
            shed: List[Request] = []
            try:
                with self._cond:
                    while self._depth == 0:
                        if self._closed:
                            return None
                        if not wait:
                            return []
                        self._cond.wait()
                    taken, key = self._assemble(max_rows, shed=shed)
                    if not taken:
                        if not shed:
                            # nothing shed AND nothing taken: a queued
                            # request is bigger than max_rows — the engine's
                            # oversized admission check exists precisely so
                            # this cannot happen; fail loudly over spinning
                            raise RuntimeError(
                                f"queued request(s) exceed the {max_rows}-row "
                                "batch budget; admission should have rejected "
                                "them as oversized"
                            )
                        # everything assembled-over was expired — deliver the
                        # shed verdicts (finally), then wait for live work
                        continue
                    # Linger for late arrivals ONLY while the queue is
                    # otherwise empty: under load there is more work right
                    # behind this batch, and a replica idling out the full
                    # linger on every dispatch would throttle saturation
                    # throughput for zero occupancy gain. At light load the
                    # linger is pure win — it coalesces a straggler into the
                    # in-hand batch instead of paying a whole extra dispatch
                    # for it.
                    if top_up_wait > 0 and self._depth == 0:
                        rows = sum(r.rows for r in taken)
                        deadline = time.monotonic() + top_up_wait
                        while rows < max_rows and not self._closed:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0 or not self._cond.wait(remaining):
                                break
                            more, _ = self._assemble(
                                max_rows - rows, key, shed=shed
                            )
                            taken.extend(more)
                            rows += sum(r.rows for r in more)
                    return taken
            finally:
                for request in shed:
                    self._deliver_shed(request)

    def _deliver_shed(self, request) -> None:
        """Fail one expired request's future with the typed rejection and
        notify the engine's shed handler (stats). Called OUTSIDE the queue
        lock — the handler may take the stats lock, which the exporter
        holds while reading queue depth (lock-order safety)."""
        waited = time.perf_counter() - request.t_enqueue
        err = AdmissionError(
            REJECT_DEADLINE,
            f"request {request.id} (tenant {request.tenant!r}) expired after "
            f"{waited:.3f}s in queue; shed before dispatch",
        )
        request.result._deliver(None, error=err)
        if self.shed_handler is not None:
            try:
                self.shed_handler(request)
            except Exception:  # noqa: BLE001 — stats must not kill dispatch
                pass
