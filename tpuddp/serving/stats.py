"""SLO metrics — the serving engine's typed record stream.

Per-request latency decomposes against the three timestamps the engine
already takes (admission, dispatch, delivery):

- ``queue_ms``  — admission -> the request's batch dispatched (scheduling +
  coalescing wait; the overload-visible number);
- ``device_ms`` — dispatch -> logits delivered (compile-warm device time +
  host fetch; shared by every request of a batch);
- ``e2e_ms``    — admission -> delivery (what the client experiences).

Every ``stats_window`` completed requests, one ``serving_stats`` row (schema
v2, tpuddp/observability/schema.py) lands in ``history.jsonl`` with the
window's percentiles, throughput, reject counts, and batch occupancy —
the same typed, validated artifact stream training telemetry uses, so
``tools/tpuddp_inspect.py`` summarizes serving runs with no new format.

All bookkeeping is host-side and lock-guarded; nothing here ever touches a
device or the dispatch hot path beyond list appends.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Optional

from tpuddp.observability import percentiles, schema

# Bound the retained CUMULATIVE per-request latency lists: a long-lived
# server must not grow host memory per request. Only :meth:`summary` /
# :meth:`since` read these — past the cap their percentiles cover the first
# _MAX_SAMPLES requests (reported via ``latency_samples_dropped``). The
# per-WINDOW lists reset every window and are never capped, so the
# serving_stats record stream stays live for the whole run.
_MAX_SAMPLES = 200_000


def _pct_ms(values) -> dict:
    """p50/p95/p99/max of a millisecond series (None-safe on empty)."""
    out = percentiles(values)  # unit-agnostic: ms in, ms out
    return {k: (None if v is None else round(v, 3)) for k, v in out.items()}


class ServingStats:
    """Aggregates request/batch telemetry and emits ``serving_stats`` rows.

    ``writer`` is a ``MetricsWriter`` (or None for in-memory-only use, e.g.
    unit tests and load generators that read :meth:`summary` directly)."""

    def __init__(self, writer=None, window: int = 64):
        self.writer = writer
        self.window = max(0, int(window))
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # cumulative
        self.submitted = 0
        self.completed = 0
        self.completed_rows = 0
        self.rejects = Counter()
        self.per_tenant_completed = Counter()
        self.batches = 0
        self.bucket_rows = 0
        # survivability accounting (tpuddp/serving/survive.py): queued
        # requests shed past their deadline (also counted in
        # rejects["deadline_exceeded"] — a shed IS a rejection) and
        # transient dispatch failures re-queued within the retry budget
        self.shed = 0
        self.retries = 0
        self._queue_ms: list = []
        self._device_ms: list = []
        self._e2e_ms: list = []
        self._lat_dropped = 0  # cumulative samples past _MAX_SAMPLES
        # window-local latency lists: reset at every emit, never capped —
        # the serving_stats stream must stay live on arbitrarily long runs
        self._win_queue_ms: list = []
        self._win_device_ms: list = []
        self._win_e2e_ms: list = []
        self._win_index = 0
        self._win_t0 = self._t0
        self._win_start = dict(
            completed=0, submitted=0, rejected=0, batches=0, rows=0,
            bucket_rows=0, shed=0, retries=0,
        )
        # live-plane state: the last emitted serving_stats record — what a
        # /metrics scrape serves, so live values can never disagree with the
        # flushed history beyond one window
        self.last_window: Optional[dict] = None

    # ------------------------------------------------------------ recording --
    def reset_clock(self) -> None:
        """Restart the run + window wall clocks. The engine calls this when
        it finishes warmup: window 0's throughput must measure serving, not
        bucket compilation."""
        with self._lock:
            now = time.perf_counter()
            self._t0 = now
            self._win_t0 = now

    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_reject(self, tenant: str, reason: str) -> None:
        with self._lock:
            self.rejects[reason] += 1

    def record_shed(self, tenant: str) -> None:
        """One queued request dropped past its deadline (load shedding) —
        a rejection with reason ``deadline_exceeded`` plus the dedicated
        shed counter the autoscaler's shed-rate rule scrapes."""
        with self._lock:
            self.rejects["deadline_exceeded"] += 1
            self.shed += 1

    def record_retry(self, tenant: str) -> None:
        """One transient dispatch failure re-queued within the per-tenant
        retry budget (the request did NOT fail through to its client)."""
        with self._lock:
            self.retries += 1

    def record_batch(self, batch, t_dispatch: float, t_done: float) -> None:
        """One dispatched batch delivered: fan its timing out to every
        request it carried, then maybe emit a window row."""
        device_ms = (t_done - t_dispatch) * 1e3
        with self._lock:
            self.batches += 1
            self.bucket_rows += batch.bucket
            self.completed_rows += batch.rows
            for r in batch.requests:
                self.completed += 1
                self.per_tenant_completed[r.tenant] += 1
                queue_ms = (t_dispatch - r.t_enqueue) * 1e3
                e2e_ms = (t_done - r.t_enqueue) * 1e3
                self._win_queue_ms.append(queue_ms)
                self._win_device_ms.append(device_ms)
                self._win_e2e_ms.append(e2e_ms)
                if len(self._e2e_ms) < _MAX_SAMPLES:
                    self._queue_ms.append(queue_ms)
                    self._device_ms.append(device_ms)
                    self._e2e_ms.append(e2e_ms)
                else:
                    self._lat_dropped += 1
            if (
                self.window
                and self.completed - self._win_start["completed"] >= self.window
            ):
                self._emit_window(final=False)

    # -------------------------------------------------------------- windows --
    def _emit_window(self, final: bool) -> Optional[dict]:
        """Build (and write) one serving_stats row for the current window.
        Caller holds the lock."""
        done = self.completed - self._win_start["completed"]
        if done == 0 and not final:
            return None
        now = time.perf_counter()
        wall = max(now - self._win_t0, 1e-9)
        rejected = sum(self.rejects.values()) - self._win_start["rejected"]
        bucket_rows = self.bucket_rows - self._win_start["bucket_rows"]
        rows = self.completed_rows - self._win_start["rows"]
        record = {
            "window": self._win_index,
            "requests": self.submitted - self._win_start["submitted"],
            "completed": done,
            "rejected": rejected,
            "batches": self.batches - self._win_start["batches"],
            "rows": rows,
            "queue_ms_p50": _pct_ms(self._win_queue_ms)["p50"],
            "device_ms_p50": _pct_ms(self._win_device_ms)["p50"],
            **{
                f"e2e_ms_{k}": v
                for k, v in _pct_ms(self._win_e2e_ms).items()
                if k in ("p50", "p95", "p99")
            },
            "throughput_rps": round(done / wall, 2),
            "rows_per_sec": round(rows / wall, 2),
            "batch_occupancy": (
                round(rows / bucket_rows, 4) if bucket_rows else None
            ),
            # survivability accounting (required at schema v7)
            "shed": self.shed - self._win_start["shed"],
            "retries": self.retries - self._win_start["retries"],
        }
        if self.writer is not None:
            self.writer.write(schema.stamp("serving_stats", record))
        self.last_window = record
        self._win_index += 1
        self._win_t0 = now
        self._win_queue_ms = []
        self._win_device_ms = []
        self._win_e2e_ms = []
        self._win_start = dict(
            completed=self.completed,
            submitted=self.submitted,
            rejected=sum(self.rejects.values()),
            batches=self.batches,
            rows=self.completed_rows,
            bucket_rows=self.bucket_rows,
            shed=self.shed,
            retries=self.retries,
        )
        return record

    def flush_window(self) -> Optional[dict]:
        """Emit whatever the current partial window holds (drain path) —
        the final row of a run must not vanish because it was short."""
        with self._lock:
            done = self.completed - self._win_start["completed"]
            rejected = sum(self.rejects.values()) - self._win_start["rejected"]
            requests = self.submitted - self._win_start["submitted"]
            retries = self.retries - self._win_start["retries"]
            if done == 0 and rejected == 0 and requests == 0 and retries == 0:
                return None
            return self._emit_window(final=True)

    # ------------------------------------------------------------ snapshots --
    def mark(self) -> dict:
        """Opaque cursor into the cumulative counters — pair with
        :meth:`since` to measure one phase (a load generator's per-offered-
        load delta) without resetting anything."""
        with self._lock:
            return dict(
                completed=self.completed,
                submitted=self.submitted,
                rows=self.completed_rows,
                bucket_rows=self.bucket_rows,
                batches=self.batches,
                rejected=sum(self.rejects.values()),
                samples=len(self._e2e_ms),
                dropped=self._lat_dropped,
                t=time.perf_counter(),
            )

    def since(self, mark: dict) -> dict:
        """Aggregate of everything recorded after ``mark`` (same fields as
        :meth:`summary`, minus per-tenant detail). Latency percentiles come
        from the capped cumulative lists: past _MAX_SAMPLES they go None
        while ``latency_samples_dropped`` goes nonzero — null-with-a-reason,
        never silently-frozen numbers."""
        with self._lock:
            sl = slice(mark["samples"], len(self._e2e_ms))
            rows = self.completed_rows - mark["rows"]
            bucket_rows = self.bucket_rows - mark["bucket_rows"]
            wall = max(time.perf_counter() - mark["t"], 1e-9)
            return {
                "completed": self.completed - mark["completed"],
                "submitted": self.submitted - mark["submitted"],
                "rejected": sum(self.rejects.values()) - mark["rejected"],
                "batches": self.batches - mark["batches"],
                "rows": rows,
                "batch_occupancy": (
                    round(rows / bucket_rows, 4) if bucket_rows else None
                ),
                "queue_ms": _pct_ms(self._queue_ms[sl]),
                "device_ms": _pct_ms(self._device_ms[sl]),
                "e2e_ms": _pct_ms(self._e2e_ms[sl]),
                "throughput_rps": round(
                    (self.completed - mark["completed"]) / wall, 2
                ),
                "rows_per_sec": round(rows / wall, 2),
                "wall_s": round(wall, 3),
                "latency_samples_dropped": (
                    self._lat_dropped - mark.get("dropped", 0)
                ),
            }

    # ----------------------------------------------------------- exporter --
    def export_source(self, engine=None):
        """The /metrics exporter's serving source: cumulative counters plus
        the LAST flushed window's latency/throughput/occupancy (exactly the
        serving_stats row history.jsonl holds). ``engine`` (optional) adds
        live queue depth, per-tenant lanes, and healthy-replica gauges. All
        lock-guarded host dict reads — the dispatch hot path is untouched."""
        from tpuddp.observability import exporter as exp

        def source():
            with self._lock:
                completed = self.completed
                submitted = self.submitted
                rejected = sum(self.rejects.values())
                rows = self.completed_rows
                batches = self.batches
                shed = self.shed
                retries = self.retries
                per_tenant = dict(self.per_tenant_completed)
                win = dict(self.last_window) if self.last_window else None
            series = {
                "serving_requests_total": exp.counter(
                    submitted, "requests submitted"
                ),
                "serving_completed_total": exp.counter(
                    completed, "requests completed"
                ),
                "serving_rejected_total": exp.counter(
                    rejected, "requests rejected at admission"
                ),
                "serving_rows_total": exp.counter(rows, "sample rows served"),
                "serving_batches_total": exp.counter(
                    batches, "device batches dispatched"
                ),
                # survivability counters (tpuddp/serving/survive.py) — the
                # autoscaler's shed-rate rule scrapes serving_shed_total
                "serving_shed_total": exp.counter(
                    shed, "queued requests shed past their deadline"
                ),
                "serving_retries_total": exp.counter(
                    retries, "transient dispatch failures retried in-budget"
                ),
            }
            if per_tenant:
                series["serving_tenant_completed_total"] = {
                    "type": "counter",
                    "help": "completed requests by tenant",
                    "values": [
                        ({"tenant": t}, n) for t, n in sorted(per_tenant.items())
                    ],
                }
            if win is not None:
                series.update({
                    "serving_e2e_ms": exp.summary(
                        {
                            "0.5": win.get("e2e_ms_p50"),
                            "0.95": win.get("e2e_ms_p95"),
                            "0.99": win.get("e2e_ms_p99"),
                        },
                        "last-window end-to-end latency",
                        count=win.get("completed"),
                    ),
                    "serving_queue_ms": exp.summary(
                        {"0.5": win.get("queue_ms_p50")},
                        "last-window scheduling + coalescing wait",
                    ),
                    "serving_device_ms": exp.summary(
                        {"0.5": win.get("device_ms_p50")},
                        "last-window device + fetch time",
                    ),
                    "serving_throughput_rps": exp.gauge(
                        win.get("throughput_rps"), "last-window requests/sec"
                    ),
                    "serving_batch_occupancy": exp.gauge(
                        win.get("batch_occupancy"),
                        "last-window real rows / padded bucket rows",
                    ),
                })
            if engine is not None:
                series["serving_queue_depth"] = exp.gauge(
                    engine.queue.depth(), "requests queued right now"
                )
                tenant_depths = engine.queue.tenant_depths()
                if tenant_depths:
                    series["serving_tenant_queue_depth"] = {
                        "type": "gauge",
                        "help": "queued requests by tenant lane",
                        "values": [
                            ({"tenant": t}, n)
                            for t, n in sorted(tenant_depths.items())
                        ],
                    }
                series["serving_replicas_healthy"] = exp.gauge(
                    sum(1 for r in engine.pool.replicas if r.healthy),
                    "replicas still routed to",
                )
                series["serving_replica_recoveries_total"] = exp.counter(
                    sum(r.recoveries for r in engine.pool.replicas),
                    "probation episodes passed (replicas rejoined routing)",
                )
            return series

        return source

    # -------------------------------------------------------------- summary --
    def summary(self) -> dict:
        """Whole-run aggregate (host dict): totals, overall percentiles,
        throughput over the run wall clock, occupancy, rejects by reason."""
        with self._lock:
            wall = max(time.perf_counter() - self._t0, 1e-9)
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "completed_rows": self.completed_rows,
                "rejected": dict(self.rejects),
                "shed": self.shed,
                "retries": self.retries,
                "per_tenant_completed": dict(self.per_tenant_completed),
                "batches": self.batches,
                "batch_occupancy": (
                    round(self.completed_rows / self.bucket_rows, 4)
                    if self.bucket_rows
                    else None
                ),
                "queue_ms": _pct_ms(self._queue_ms),
                "device_ms": _pct_ms(self._device_ms),
                "e2e_ms": _pct_ms(self._e2e_ms),
                "throughput_rps": round(self.completed / wall, 2),
                "rows_per_sec": round(self.completed_rows / wall, 2),
                "wall_s": round(wall, 3),
                # whole-run percentiles cover the first _MAX_SAMPLES requests
                # only; a nonzero drop count says the tail is not in them
                "latency_samples_dropped": self._lat_dropped,
            }
