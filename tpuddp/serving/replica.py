"""Replica pool — N independent model replicas across the local devices.

Training runs the mesh as ONE lockstep program; serving inverts that: each
local device holds a full parameter copy and runs its own dispatch loop, so
the mesh behaves as a pool of independently schedulable replicas (the MPMD
view of PAPERS.md arxiv 2412.14374). Parameters are committed per device
with ``jax.device_put``; a replica's jitted forward then follows its
committed arguments, so concurrent dispatch loops land on distinct chips
with no cross-replica coordination at all.

Checkpoints come through the existing integrity-manifest path
(``training/checkpoint.restore_latest``): sha256-verified, corrupt files
skipped newest-first. Both checkpoint families restore — native
``ckpt_{e}.npz`` files (TrainState attribute-keyed leaves) and managed
``state_{e}.npz`` files (dict-keyed) — via a template whose pytree paths
match the writer's; serving only reads the ``params``/``model_state``
leaves, optimizer state stays untouched on disk.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp.models import load_model
from tpuddp.nn.core import Context, Module
from tpuddp.training import checkpoint as ckpt

logger = logging.getLogger("tpuddp")


@dataclasses.dataclass
class _NativeSlice:
    """Template matching the leading fields of the native ``TrainState``
    checkpoint: attribute-keyed paths (``.params[...]``), so ``ckpt.load``
    finds the same leaf names ``save_on_main`` wrote, while the optimizer
    state / RNG / counters the serving path doesn't need are simply absent
    from the template (extra stored keys are ignored by design)."""

    params: Any
    model_state: Any


jax.tree_util.register_dataclass(
    _NativeSlice, data_fields=["params", "model_state"], meta_fields=[]
)


def _restore_variables(
    save_dir: str, prefix: str, params, model_state
) -> Tuple[Any, Any, int]:
    """Restore (params, model_state, epoch) from the newest intact
    checkpoint. ``prefix="auto"`` picks whichever family ("ckpt" native /
    "state" managed) has the newest intact file. Raises when nothing intact
    exists — serving random weights because a directory was empty or corrupt
    would be a silent catastrophe, unlike training's fresh-start resume."""
    prefixes = ("ckpt", "state") if prefix == "auto" else (prefix,)
    found = []
    for p in prefixes:
        hit = ckpt.latest(save_dir, prefix=p)
        if hit is not None:
            found.append((hit[1], p, hit[0]))
    if not found:
        raise FileNotFoundError(
            f"no intact checkpoint with prefix(es) {prefixes} in {save_dir!r}"
        )
    epoch, pfx, path = max(found)
    if pfx == "ckpt":
        like: Any = _NativeSlice(params=params, model_state=model_state)
        tree = ckpt.load(path, like)
        out = (tree.params, tree.model_state)
    else:
        like = {"params": params, "model_state": model_state}
        tree = ckpt.load(path, like)
        out = (tree["params"], tree["model_state"])
    logger.info("serving: restored %s (epoch %d)", path, epoch)
    return out[0], out[1], epoch


class Replica:
    """One device's copy of the model: committed parameters + a private
    jitted eval forward (one compiled program per batch bucket).

    Survivability state machine (tpuddp/serving/survive.py): ``state`` is
    ``healthy`` (routed to), ``recovering`` (in probation — the engine is
    rebuilding it off the serving path), or ``removed`` (probation
    exhausted; permanently out of routing). ``recoveries`` counts lifetime
    probation rejoins (bounded by the policy's ``max_recoveries``);
    ``broken`` simulates device death for chaos injection
    (``replica_kill`` — every dispatch raises until :meth:`rebuild`)."""

    def __init__(self, index: int, device, module: Module, params, model_state):
        self.index = index
        self.device = device
        self.module = module
        self.params = jax.device_put(params, device)
        self.model_state = jax.device_put(model_state, device)
        self._fwd = jax.jit(self._make_fwd())
        self.dispatches = 0
        # graceful degradation (ISSUE 7 satellite, survivability layer): the
        # engine marks a replica unhealthy after K consecutive dispatch
        # errors; it then enters probation instead of dying forever. A
        # successful dispatch resets the streak.
        self.state = "healthy"
        self.consecutive_errors = 0
        self.recoveries = 0
        self.broken = False
        # True while this replica's dispatch-loop THREAD is running — the
        # survivor check must not hand retried/queued traffic to a peer
        # whose loop already exited at drain (state alone cannot tell)
        self.loop_alive = False

    @property
    def healthy(self) -> bool:
        return self.state == "healthy"

    def _make_fwd(self):
        module = self.module

        def fwd(p, s, x):
            # eval-mode forward, the FusedEvaluator's exact context: no
            # dropout, BatchNorm on running stats, fixed throwaway key —
            # rows are independent, so served logits are bitwise those of a
            # direct forward over the same padded batch
            ctx = Context(train=False, rng=jax.random.key(0), axis_name=None)
            logits, _ = module.apply(p, s, x, ctx)
            return logits

        return fwd

    def infer(self, x) -> jax.Array:
        """Dispatch one padded batch; returns device logits (async — the
        caller fences when it fetches rows)."""
        if self.broken:
            raise RuntimeError(
                f"replica {self.index} is down (injected replica_kill)"
            )
        self.dispatches += 1
        return self._fwd(self.params, self.model_state, x)

    def warmup(self, buckets, sample_shape, dtype=np.float32) -> None:
        """Compile every bucket program now, so the first real request never
        pays a compile in its latency."""
        for b in buckets:
            x = np.zeros((b,) + tuple(sample_shape), dtype)
            jax.block_until_ready(self.infer(x))
        self.dispatches = 0

    # ---------------------------------------------------------- recovery --
    def rebuild(self) -> None:
        """Probation step 1: rebuild the replica's device state — recommit
        the parameters and re-jit the forward (the moral equivalent of
        restarting the device's program state). Clears an injected
        ``replica_kill``: a restart is exactly what fixes a killed device."""
        self.params = jax.device_put(self.params, self.device)
        self.model_state = jax.device_put(self.model_state, self.device)
        self._fwd = jax.jit(self._make_fwd())
        self.broken = False

    def canary(self, sample_shape, dtype=np.float32) -> None:
        """Probation step 2: probe with one real (smallest-bucket) dispatch
        and require finite logits — a replica that cannot serve the canary
        does not rejoin routing."""
        x = np.zeros((1,) + tuple(sample_shape), dtype)
        out = np.asarray(self.infer(x))
        if not np.all(np.isfinite(out)):
            raise RuntimeError(
                f"replica {self.index} canary produced non-finite logits"
            )


class ReplicaPool:
    """The model replicas a :class:`ServingEngine` dispatches onto."""

    def __init__(
        self,
        module: Module,
        params,
        model_state,
        devices: List,
        sample_shape: Tuple[int, ...],
        restored_epoch: Optional[int] = None,
    ):
        self.module = module
        self.sample_shape = tuple(int(d) for d in sample_shape)
        self.restored_epoch = restored_epoch
        self.replicas = [
            Replica(i, d, module, params, model_state)
            for i, d in enumerate(devices)
        ]

    def __len__(self) -> int:
        return len(self.replicas)

    @property
    def devices(self):
        return [r.device for r in self.replicas]

    def warmup(self, buckets) -> None:
        for r in self.replicas:
            r.warmup(buckets, self.sample_shape)

    @classmethod
    def from_config(cls, cfg: dict, devices=None) -> "ReplicaPool":
        """Build the pool from a ``serving`` config block
        (tpuddp/config.py:SERVING_DEFAULTS): model zoo lookup, fresh seeded
        init, then optional checkpoint restore over it."""
        sample_shape = tuple(int(d) for d in cfg["input_shape"])
        module = load_model(cfg["model"], num_classes=int(cfg["num_classes"]))
        sample = jnp.zeros((1,) + sample_shape, jnp.float32)
        params, model_state = module.init(
            jax.random.key(int(cfg.get("seed") or 0)), sample
        )
        restored_epoch = None
        if cfg.get("checkpoint_dir"):
            params, model_state, restored_epoch = _restore_variables(
                cfg["checkpoint_dir"],
                str(cfg.get("checkpoint_prefix") or "auto"),
                params,
                model_state,
            )
        if devices is None:
            devices = jax.local_devices()
        n = cfg.get("num_replicas", "auto")
        if n != "auto":
            n = int(n)
            if n < 1:
                raise ValueError(f"num_replicas must be >= 1, got {n}")
            if n > len(devices):
                raise ValueError(
                    f"num_replicas={n} exceeds the {len(devices)} available "
                    "local devices"
                )
            devices = devices[:n]
        return cls(
            module, params, model_state, list(devices), sample_shape,
            restored_epoch,
        )
