"""``python -m tpuddp.serving`` — stand the engine up from a settings file.

Reads the same YAML settings file the training entrypoints use; the
``serving`` block (tpuddp/config.py:SERVING_DEFAULTS, unknown keys refused)
configures the engine, ``out_dir`` receives ``history.jsonl`` (run_meta +
serving_stats + events — `tools/tpuddp_inspect.py` summarizes/validates it).

Modes:

- ``--demo N``  — drive N synthetic requests from ``--tenants`` tenants
  in-process, wait for every result, print the SLO summary, exit 0. The
  zero-dependency smoke proof (the gate's serving leg uses tools/loadgen.py
  for the real curves).
- ``--decode``  — stand the TOKEN-level engine up instead
  (tpuddp/serving/decode/, configured by the ``serving.decode`` block; the
  settings file must carry one). Demo traffic becomes synthetic token
  prompts; with ``--serve`` the demo prompts are submitted WITHOUT waiting,
  so a SIGTERM lands mid-decode and the drain must let every in-flight
  sequence finish streaming before exit 75 — the gate's decode-drain leg
  asserts exactly that.
- ``--serve S`` — serve until SIGTERM/SIGINT or S seconds (0 = forever).
  SIGTERM drains: admission closes (new submits rejected with reason
  "draining"), in-flight and queued work completes, stats flush, and the
  process exits 75 (``EXIT_PREEMPTED``) — the resilience exit-code contract,
  so schedulers requeue a drained server exactly like a drained trainer.
  Combined with ``--demo N``, the demo traffic runs FIRST and the engine
  then stays up for the serve window — the live-ops shape: populate the SLO
  windows, then scrape ``/metrics`` against a running engine (the
  ``observability.exporter`` block arms the endpoint; the bound port lands
  in ``<out_dir>/exporter.port``).

Stdout contract: the LAST line is one compact JSON object (the SLO summary)
for driver parsing, mirroring bench.py's output contract.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from tpuddp import config as config_lib
from tpuddp.observability import json_sanitize
from tpuddp.resilience import preemption
from tpuddp.serving.engine import ServingEngine


def _demo_prompts(engine, n: int, tenants: int, seed: int = 0):
    """N variable-length synthetic token prompts round-robin over tenants;
    returns the streaming results in submission order (not waited)."""
    rng = np.random.RandomState(seed)
    max_prompt = min(16, engine.max_prompt_len)
    results = []
    for i in range(n):
        prompt = rng.randint(
            0, engine.vocab_size, size=int(rng.randint(1, max_prompt + 1))
        ).astype(np.int32)
        results.append(engine.submit(f"tenant{i % tenants}", prompt))
    return results


def _demo_requests(engine: ServingEngine, n: int, tenants: int, seed: int = 0):
    """N variable-size requests round-robin over synthetic tenants; returns
    (results, rows) in submission order."""
    rng = np.random.RandomState(seed)
    shape = engine.pool.sample_shape
    max_rows = max(1, min(4, engine.scheduler.max_batch_size))
    results = []
    for i in range(n):
        rows = int(rng.randint(1, max_rows + 1))
        x = rng.randn(rows, *shape).astype(np.float32)
        results.append(engine.submit(f"tenant{i % tenants}", x))
    return results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpuddp.serving",
        description="tpuddp continuous-batching inference engine",
    )
    parser.add_argument("--settings", required=True, help="YAML settings file")
    parser.add_argument(
        "--demo", type=int, default=None, metavar="N",
        help="drive N synthetic requests, print the summary, exit",
    )
    parser.add_argument(
        "--serve", type=float, default=None, metavar="S",
        help="serve until SIGTERM or S seconds (0 = forever)",
    )
    parser.add_argument(
        "--tenants", type=int, default=2, help="demo-mode tenant count",
    )
    parser.add_argument(
        "--decode", action="store_true",
        help="token-level autoregressive engine (the serving.decode block)",
    )
    args = parser.parse_args(argv)
    if args.demo is None and args.serve is None:
        parser.error("at least one of --demo N / --serve S is required")

    settings = config_lib.load_settings(args.settings)
    serving = config_lib.serving_config(settings)
    observability = config_lib.observability_config(settings)
    out_dir = settings.get("out_dir")
    if out_dir:
        out_dir = config_lib.prepare_out_dir(settings, args.settings)

    if args.decode:
        from tpuddp.serving.decode import DecodeEngine

        decode_cfg = config_lib.decode_config(serving)
        if decode_cfg is None:
            parser.error("--decode needs a serving.decode block in the settings")
        engine = DecodeEngine.from_config(
            decode_cfg, out_dir=out_dir, observability=observability
        )
    else:
        engine = ServingEngine.from_config(
            serving, out_dir=out_dir, observability=observability
        )
    engine.start()

    if args.demo is not None:
        if args.decode:
            results = _demo_prompts(engine, args.demo, max(1, args.tenants))
        else:
            results = _demo_requests(engine, args.demo, max(1, args.tenants))
        if args.serve is None:
            for r in results:
                r.result(timeout=120)
            summary = engine.drain(reason="demo_complete")
            print(json.dumps(json_sanitize(summary), allow_nan=False))
            return 0
        # --demo + --serve: keep the warm, traffic-populated engine up for
        # the serve window (the live-ops scrape target). Decode demo traffic
        # is deliberately NOT waited on — a SIGTERM in the serve window
        # lands mid-decode, and the drain contract (in-flight sequences
        # finish streaming) is what the gate's drain leg verifies.
        if not args.decode:
            for r in results:
                r.result(timeout=120)
        print("demo traffic complete; serving", flush=True)

    # --serve: SIGTERM/SIGINT -> resilience drain contract (exit 75)
    preemption.install_preemption_handler()
    print("serving: ready", flush=True)
    deadline = time.monotonic() + args.serve if args.serve else None
    while not preemption.preemption_requested():
        if deadline is not None and time.monotonic() >= deadline:
            summary = engine.drain(reason="serve_window_elapsed")
            print(json.dumps(json_sanitize(summary), allow_nan=False))
            return 0
        time.sleep(0.05)
    summary = engine.drain(reason="sigterm_drain")
    print(json.dumps(json_sanitize(summary), allow_nan=False))
    return preemption.EXIT_PREEMPTED


if __name__ == "__main__":
    sys.exit(main())
