"""Token-level SLO metrics — the decode engine's typed record stream.

Request-granularity latency percentiles say nothing useful about a token
stream; the three numbers token traffic lives by are:

- **TTFT** (time to first token) — submit -> the prefill's first sampled
  token delivered: what "the model started answering" feels like;
- **ITL** (inter-token latency) — the gap between consecutive streamed
  tokens of one sequence: what "the answer is flowing" feels like;
- **tokens/sec** — aggregate generation throughput across the running batch.

Every ``stats_window`` generated tokens, one ``decode_stats`` row (schema
v6, tpuddp/observability/schema.py) lands in ``history.jsonl`` with the
window's TTFT/ITL percentiles, throughput, reject counts, KV-pool occupancy
and active-sequence count — the same typed artifact stream every other
subsystem uses, so ``tools/tpuddp_inspect.py`` summarizes decode runs with
no new format.

All bookkeeping is host-side; the decode loop calls in with plain floats.
Lock-guarded because the exporter scrapes from its own thread.
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Callable, Optional

from tpuddp.observability import percentiles, schema

# Cap on the retained CUMULATIVE latency sample lists (the ServingStats
# convention): summaries past the cap report the first _MAX_SAMPLES with a
# nonzero dropped count, while the per-window lists reset every window and
# keep the record stream live forever.
_MAX_SAMPLES = 200_000


def _pct_ms(values) -> dict:
    out = percentiles(values)
    return {k: (None if v is None else round(v, 3)) for k, v in out.items()}


class DecodeStats:
    """Aggregates token telemetry and emits ``decode_stats`` rows.

    ``gauges`` is an optional zero-arg callable returning ``(kv_occupancy,
    active_sequences)`` sampled at window-flush time (the engine wires its
    replica pool in); without it those fields are null, never absent."""

    def __init__(
        self,
        writer=None,
        window: int = 64,
        gauges: Optional[Callable[[], tuple]] = None,
    ):
        self.writer = writer
        self.window = max(0, int(window))
        self.gauges = gauges
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # cumulative
        self.submitted = 0
        self.completed = 0  # sequences finished
        self.tokens = 0  # tokens generated (delivered to clients)
        self.prompt_tokens = 0
        self.rejects = Counter()
        self.per_tenant_completed = Counter()
        # survivability accounting (tpuddp/serving/survive.py): queued
        # requests shed past their deadline, and live sessions migrated
        # off a dead replica (their streams continued bitwise elsewhere)
        self.shed = 0
        self.failovers = 0
        self._ttft_ms: list = []
        self._itl_ms: list = []
        self._lat_dropped = 0
        # window-local
        self._win_ttft: list = []
        self._win_itl: list = []
        self._win_index = 0
        self._win_t0 = self._t0
        self._win_start = dict(
            tokens=0, completed=0, submitted=0, rejected=0, shed=0,
            failovers=0,
        )
        self.last_window: Optional[dict] = None

    # ------------------------------------------------------------ recording --
    def reset_clock(self) -> None:
        """Restart the run + window wall clocks (post-warmup, so window 0's
        tokens/sec measures decoding, not prefill/step compiles)."""
        with self._lock:
            now = time.perf_counter()
            self._t0 = now
            self._win_t0 = now

    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_reject(self, tenant: str, reason: str) -> None:
        with self._lock:
            self.rejects[reason] += 1

    def record_shed(self, tenant: str) -> None:
        """One queued request dropped past its deadline (load shedding) —
        a rejection with reason ``deadline_exceeded`` plus the dedicated
        shed counter the autoscaler's shed-rate rule scrapes."""
        with self._lock:
            self.rejects["deadline_exceeded"] += 1
            self.shed += 1

    def record_failover(self, tenant: str) -> None:
        """One live session migrated off a dead replica (its stream
        continues bitwise on the new one)."""
        with self._lock:
            self.failovers += 1

    def record_first_token(self, ttft_ms: float, prompt_tokens: int) -> None:
        """The prefill's sampled token delivered — TTFT's clock stops."""
        with self._lock:
            self.tokens += 1
            self.prompt_tokens += int(prompt_tokens)
            self._win_ttft.append(ttft_ms)
            if len(self._ttft_ms) < _MAX_SAMPLES:
                self._ttft_ms.append(ttft_ms)
            else:
                self._lat_dropped += 1
            self._maybe_emit()

    def record_token(self, itl_ms: float) -> None:
        """One decode-step token delivered to its stream."""
        with self._lock:
            self.tokens += 1
            self._win_itl.append(itl_ms)
            if len(self._itl_ms) < _MAX_SAMPLES:
                self._itl_ms.append(itl_ms)
            else:
                self._lat_dropped += 1
            self._maybe_emit()

    def record_finish(self, tenant: str) -> None:
        with self._lock:
            self.completed += 1
            self.per_tenant_completed[tenant] += 1

    # -------------------------------------------------------------- windows --
    def _maybe_emit(self) -> None:
        if self.window and self.tokens - self._win_start["tokens"] >= self.window:
            self._emit_window()

    def _emit_window(self) -> Optional[dict]:
        """Caller holds the lock."""
        done_tokens = self.tokens - self._win_start["tokens"]
        now = time.perf_counter()
        wall = max(now - self._win_t0, 1e-9)
        kv_occ, active = (None, None)
        if self.gauges is not None:
            try:
                kv_occ, active = self.gauges()
            except Exception:  # pragma: no cover — a dead gauge is null, not a crash
                kv_occ, active = (None, None)
        record = {
            "window": self._win_index,
            "tokens": done_tokens,
            "completed": self.completed - self._win_start["completed"],
            "requests": self.submitted - self._win_start["submitted"],
            "rejected": sum(self.rejects.values()) - self._win_start["rejected"],
            "tokens_per_sec": round(done_tokens / wall, 2),
            **{f"ttft_ms_{k}": v for k, v in _pct_ms(self._win_ttft).items()
               if k in ("p50", "p95", "p99")},
            **{f"itl_ms_{k}": v for k, v in _pct_ms(self._win_itl).items()
               if k in ("p50", "p95", "p99")},
            "kv_occupancy": None if kv_occ is None else round(kv_occ, 4),
            "active_sequences": active,
            # survivability accounting (required at schema v7)
            "shed": self.shed - self._win_start["shed"],
            "failovers": self.failovers - self._win_start["failovers"],
        }
        if self.writer is not None:
            self.writer.write(schema.stamp("decode_stats", record))
        self.last_window = record
        self._win_index += 1
        self._win_t0 = now
        self._win_ttft = []
        self._win_itl = []
        self._win_start = dict(
            tokens=self.tokens,
            completed=self.completed,
            submitted=self.submitted,
            rejected=sum(self.rejects.values()),
            shed=self.shed,
            failovers=self.failovers,
        )
        return record

    def flush_window(self) -> Optional[dict]:
        """Emit the current partial window (drain path)."""
        with self._lock:
            if (
                self.tokens == self._win_start["tokens"]
                and self.submitted == self._win_start["submitted"]
                and sum(self.rejects.values()) == self._win_start["rejected"]
                and self.failovers == self._win_start["failovers"]
            ):
                return None
            return self._emit_window()

    # ------------------------------------------------------------ snapshots --
    def mark(self) -> dict:
        """Cursor for :meth:`since` — the load generator's per-phase delta."""
        with self._lock:
            return dict(
                tokens=self.tokens,
                completed=self.completed,
                submitted=self.submitted,
                rejected=sum(self.rejects.values()),
                shed=self.shed,
                failovers=self.failovers,
                ttft_samples=len(self._ttft_ms),
                itl_samples=len(self._itl_ms),
                dropped=self._lat_dropped,
                t=time.perf_counter(),
            )

    def since(self, mark: dict) -> dict:
        with self._lock:
            wall = max(time.perf_counter() - mark["t"], 1e-9)
            tokens = self.tokens - mark["tokens"]
            return {
                "tokens": tokens,
                "completed": self.completed - mark["completed"],
                "submitted": self.submitted - mark["submitted"],
                "rejected": sum(self.rejects.values()) - mark["rejected"],
                "shed": self.shed - mark.get("shed", 0),
                "failovers": self.failovers - mark.get("failovers", 0),
                "tokens_per_sec": round(tokens / wall, 2),
                "ttft_ms": _pct_ms(self._ttft_ms[mark["ttft_samples"]:]),
                "itl_ms": _pct_ms(self._itl_ms[mark["itl_samples"]:]),
                "wall_s": round(wall, 3),
                "latency_samples_dropped": (
                    self._lat_dropped - mark.get("dropped", 0)
                ),
            }

    # ------------------------------------------------------------- exporter --
    def export_source(self, engine=None):
        """The /metrics decode source: cumulative token/sequence counters,
        the LAST flushed window's throughput + TTFT/ITL summaries, and —
        with ``engine`` — the live KV-occupancy / active-sequence / queue
        gauges. Host dict reads only; the decode loop is untouched."""
        from tpuddp.observability import exporter as exp

        def source():
            with self._lock:
                tokens = self.tokens
                completed = self.completed
                submitted = self.submitted
                rejected = sum(self.rejects.values())
                shed = self.shed
                failovers = self.failovers
                win = dict(self.last_window) if self.last_window else None
            series = {
                "decode_tokens_total": exp.counter(
                    tokens, "tokens generated and streamed"
                ),
                "decode_sequences_completed_total": exp.counter(
                    completed, "sequences decoded to termination"
                ),
                "decode_requests_total": exp.counter(
                    submitted, "decode requests submitted"
                ),
                "decode_rejected_total": exp.counter(
                    rejected, "decode requests rejected at admission"
                ),
                # survivability counters (tpuddp/serving/survive.py) — the
                # autoscaler's shed-rate rule reads decode_shed_total on
                # decode jobs the way it reads serving_shed_total
                "decode_shed_total": exp.counter(
                    shed, "queued decode requests shed past their deadline"
                ),
                "decode_session_failovers_total": exp.counter(
                    failovers,
                    "live sessions migrated off a dead replica",
                ),
            }
            if win is not None:
                series.update({
                    "decode_tokens_per_sec": exp.gauge(
                        win.get("tokens_per_sec"),
                        "last-window generation throughput",
                    ),
                    "decode_ttft_ms": exp.summary(
                        {
                            "0.5": win.get("ttft_ms_p50"),
                            "0.95": win.get("ttft_ms_p95"),
                        },
                        "last-window time to first token",
                    ),
                    "decode_itl_ms": exp.summary(
                        {
                            "0.5": win.get("itl_ms_p50"),
                            "0.95": win.get("itl_ms_p95"),
                            "0.99": win.get("itl_ms_p99"),
                        },
                        "last-window inter-token latency",
                    ),
                })
            if engine is not None:
                series["decode_kv_occupancy"] = exp.gauge(
                    engine.kv_occupancy(),
                    "allocated fraction of the paged KV pool",
                )
                series["decode_active_sequences"] = exp.gauge(
                    engine.active_sequences(),
                    "sequences occupying decode slots right now",
                )
                series["decode_queue_depth"] = exp.gauge(
                    engine.queue.depth(), "decode requests queued right now"
                )
                series["decode_replicas_healthy"] = exp.gauge(
                    sum(1 for r in engine.replicas if r.healthy),
                    "decode replicas still routed to",
                )
                series["decode_replica_recoveries_total"] = exp.counter(
                    sum(r.recoveries for r in engine.replicas),
                    "probation episodes passed (replicas rejoined routing)",
                )
            return series

        return source

    # -------------------------------------------------------------- summary --
    def summary(self) -> dict:
        with self._lock:
            wall = max(time.perf_counter() - self._t0, 1e-9)
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "tokens": self.tokens,
                "prompt_tokens": self.prompt_tokens,
                "rejected": dict(self.rejects),
                "shed": self.shed,
                "failovers": self.failovers,
                "per_tenant_completed": dict(self.per_tenant_completed),
                "tokens_per_sec": round(self.tokens / wall, 2),
                "ttft_ms": _pct_ms(self._ttft_ms),
                "itl_ms": _pct_ms(self._itl_ms),
                "wall_s": round(wall, 3),
                "latency_samples_dropped": self._lat_dropped,
            }
