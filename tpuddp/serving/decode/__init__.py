"""tpuddp.serving.decode — token-level autoregressive serving.

The request-granularity engine (tpuddp/serving/engine.py) serves CNN-style
one-shot forwards; real "millions of users" traffic is token streams
(ROADMAP open item 3). This package decodes them:

- :mod:`cache`  — the paged KV-cache pool: one device-resident
  ``(layers, blocks, block_size, heads, head_dim)`` K/V pool per replica,
  per-sequence fixed-size block tables, free-list allocation/free
  accounting, and the occupancy gauge;
- :mod:`stats`  — token-level SLO metrics (tokens/sec, time-to-first-token,
  inter-token latency percentiles, KV occupancy) emitted as typed
  ``decode_stats`` rows (schema v6) through ``tpuddp/observability``;
- :mod:`engine` — :class:`DecodeEngine`: continuous batching at TOKEN
  granularity (sequences join and leave the running batch every step),
  prefill/decode split scheduling (bucketed prompt prefill + ONE
  fixed-shape ``(max_slots, 1)`` step program), host-side deterministic
  sampling, per-token streaming on :class:`StreamedResult`, and the drain
  contract shared with the rest of the stack.

The model side lives in ``tpuddp/models/transformer.py`` (the decoder-only
family whose partition rules follow SNIPPETS.md [2]); the config side is
the ``serving.decode`` block (tpuddp/config.py:DECODE_DEFAULTS).
"""

from tpuddp.serving.decode.cache import PagedKVCache  # noqa: F401
from tpuddp.serving.decode.engine import (  # noqa: F401
    DecodeEngine,
    DecodeReplica,
    DecodeRequest,
    StreamedResult,
)
from tpuddp.serving.decode.stats import DecodeStats  # noqa: F401

__all__ = [
    "DecodeEngine",
    "DecodeReplica",
    "DecodeRequest",
    "DecodeStats",
    "PagedKVCache",
    "StreamedResult",
]
