"""Autoregressive decode engine — token-level continuous batching.

The request-granularity engine (tpuddp/serving/engine.py) batches whole
requests: a request joins a batch once and leaves when the batch returns.
Token traffic inverts the granularity: a sequence occupies a *slot* in the
running batch for its whole generation, and the batch's membership changes
**every decode step** — a sequence that samples its stop token frees its KV
blocks immediately and a queued request prefills into the vacated slot
before the next step. Throughput never drains to zero waiting for the
longest sequence of a "batch", because there is no such thing as a batch
boundary.

Two-program scheduling (the prefill/decode split):

- **prefill** — one prompt at a time, padded to a power-of-two length
  bucket (the serving coalescer's ladder applied to the sequence axis): at
  most ``log2(max_prompt) + 1`` compiled prefill programs. The prompt's K/V
  is committed into the paged pool and its last position's logits sample
  the FIRST generated token — TTFT's clock stops here.
- **decode** — ONE fixed-shape ``(max_slots, 1)`` program for every step,
  whatever subset of slots is live: per-slot block tables and lengths are
  ordinary int32 inputs, so sequences joining and leaving never change the
  compiled shape. Compile storms are structurally impossible on the hot
  path.

Sampling runs on the host from the step's logits: greedy argmax, or
temperature softmax drawn from a per-sequence deterministic stream (seeded
by the request's seed and its own token index — never by batch
composition). Combined with per-slot-independent device math, this makes
continuous batching **numerically invisible**: a sequence decodes to
bitwise-identical tokens whether it ran alone or packed among strangers —
the end-to-end acceptance test's contract.

Streaming: ``submit`` returns a :class:`StreamedResult`; every sampled
token is delivered to it as generated (``for tok in result.stream():``),
and ``result()`` still blocks for the full sequence (the ServedResult
contract, so non-streaming callers and load generators work unchanged).

Lifecycle mirrors the request engine: ``start()`` warms every program,
``drain()`` closes admission and lets in-flight sequences finish, and the
``python -m tpuddp.serving --decode`` entrypoint maps SIGTERM onto drain +
exit 75 (the resilience contract).
"""

from __future__ import annotations

import itertools
import logging
import queue as queue_lib
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp.models import load_model
from tpuddp.models.transformer import TransformerLM, prefill_buckets
from tpuddp.observability import MetricsWriter, schema
from tpuddp.serving import queue as queue_mod
from tpuddp.serving.decode.cache import PagedKVCache
from tpuddp.serving.decode.stats import DecodeStats
from tpuddp.serving.queue import AdmissionError, RequestQueue, ServedResult
from tpuddp.utils import batching

logger = logging.getLogger("tpuddp")

_ids = itertools.count()
_STREAM_END = object()


class StreamedResult(ServedResult):
    """Future for one sequence's tokens, streamed as generated.

    ``stream()`` yields ints the moment the decode loop samples them;
    ``result(timeout)`` (inherited) blocks for the FULL sequence and returns
    it as an int32 array. A failed sequence raises through both paths."""

    def __init__(self):
        super().__init__()
        self._stream: "queue_lib.Queue" = queue_lib.Queue()
        self.first_token_at: Optional[float] = None

    def _deliver_token(self, token: int) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.perf_counter()
        self._stream.put(int(token))

    def _deliver(self, value, error=None) -> None:
        super()._deliver(value, error=error)
        self._stream.put(_STREAM_END)

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are generated; raises the sequence's error
        (or TimeoutError on a stalled stream, matching ``result()``'s
        contract) instead of hanging."""
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue_lib.Empty:
                raise TimeoutError(
                    f"decode stream stalled: no token within {timeout}s"
                ) from None
            if item is _STREAM_END:
                if self._error is not None:
                    raise self._error
                return
            yield item


class DecodeRequest:
    """One admitted decode request. Duck-types the queue's ``Request``
    protocol (tenant / rows / key / id / t_enqueue) so :class:`RequestQueue`
    admission, per-tenant lanes, and round-robin fairness apply unchanged —
    every request is one row of the same key, so any group assembles."""

    __slots__ = (
        "id", "tenant", "tokens", "max_new_tokens", "temperature", "seed",
        "stop_token", "rows", "key", "t_enqueue", "result",
    )

    def __init__(
        self, tenant: str, tokens: np.ndarray, max_new_tokens: int,
        temperature: float, seed: int, stop_token: Optional[int],
    ):
        self.id = next(_ids)
        self.tenant = str(tenant)
        self.tokens = tokens
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.stop_token = stop_token
        self.rows = 1
        self.key = ("decode",)
        self.t_enqueue = time.perf_counter()
        self.result = StreamedResult()

    @property
    def total_tokens(self) -> int:
        """Worst-case lifetime length — the KV budget reserved up front."""
        return len(self.tokens) + self.max_new_tokens


class _Active:
    """One sequence occupying a decode slot."""

    __slots__ = ("req", "slot", "last_token", "n_generated", "out", "t_last")

    def __init__(self, req: DecodeRequest, slot: int, first_token: int):
        self.req = req
        self.slot = slot
        self.last_token = first_token
        self.n_generated = 1
        self.out = [first_token]
        self.t_last = time.perf_counter()


def _sample(logits_row: np.ndarray, temperature: float, seed: int, index: int) -> int:
    """Host-side sampling. Greedy at temperature 0; otherwise softmax with a
    stream keyed by (request seed, token index) ONLY — two decodes of the
    same request sample identically whatever else shares their batch."""
    if temperature <= 0.0:
        return int(np.argmax(logits_row))
    rng = np.random.RandomState((seed * 1000003 + index * 7919 + 0x5D) & 0x7FFFFFFF)
    z = logits_row.astype(np.float64) / temperature
    z -= z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


class DecodeReplica:
    """One device's decode lane: committed params, the jitted prefill (one
    program per prompt bucket) and fixed-shape step programs, and a private
    :class:`PagedKVCache` + K/V pool pair."""

    def __init__(self, index: int, device, model: TransformerLM, params, cfg: dict):
        self.index = index
        self.device = device
        self.model = model
        self.params = jax.device_put(params, device)
        self.cache = PagedKVCache(
            layers=model.n_layers,
            heads=model.n_heads,
            head_dim=model.head_dim,
            num_blocks=int(cfg["kv_blocks"]),
            block_size=int(cfg["kv_block_size"]),
            max_slots=int(cfg["max_slots"]),
            max_seq_len=int(cfg["max_seq_len"]),
        )
        shape = self.cache.pool_shape()
        self.kpool = jax.device_put(jnp.zeros(shape, jnp.float32), device)
        self.vpool = jax.device_put(jnp.zeros(shape, jnp.float32), device)
        # the pools are threaded through and the old buffers donated (cache
        # module doc): without donation every token step would COPY both
        # full K/V pools — doubling cache memory and adding a pool-sized
        # transfer per step. Args: (params, kpool, vpool, ...) -> donate 1, 2.
        # (XLA:CPU ignores donation with a note; the TPU path is the point.)
        self._prefill = jax.jit(model.prefill, donate_argnums=(1, 2))
        self._step = jax.jit(model.decode_step, donate_argnums=(1, 2))
        self.steps = 0

    def warmup(self, buckets: List[int]) -> None:
        """Compile every prefill bucket + the step program now. Warmup
        traffic writes only into reserved garbage block 0 (all-zero table
        rows), so the allocatable pool is untouched."""
        zrow = jnp.zeros((self.cache.max_blocks,), jnp.int32)
        for P in buckets:
            toks = jnp.zeros((1, P), jnp.int32)
            out, self.kpool, self.vpool = self._prefill(
                self.params, self.kpool, self.vpool, zrow, toks,
                jnp.asarray(1, jnp.int32),
            )
            jax.block_until_ready(out)
        S = self.cache.max_slots
        out, self.kpool, self.vpool = self._step(
            self.params, self.kpool, self.vpool,
            jnp.zeros((S, self.cache.max_blocks), jnp.int32),
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        )
        jax.block_until_ready(out)
        self.steps = 0


class DecodeEngine:
    """Token-level continuous-batching engine over N decode replicas."""

    def __init__(
        self,
        cfg: dict,
        out_dir: Optional[str] = None,
        devices=None,
        observability: Optional[dict] = None,
    ):
        from tpuddp import config as cfg_lib
        from tpuddp.observability import exporter as exp_lib
        from tpuddp.observability import flight as flight_lib
        from tpuddp.serving.replica import _restore_variables

        self.cfg = dict(cfg)
        self.vocab_size = int(cfg["vocab_size"])
        self.max_seq_len = int(cfg["max_seq_len"])
        self.max_new_tokens = int(cfg["max_new_tokens"])
        self.max_prompt_len = self.max_seq_len - 1  # >= 1 generated token
        self.stop_token = (
            None if cfg.get("stop_token") is None else int(cfg["stop_token"])
        )
        self.temperature = float(cfg.get("temperature") or 0.0)
        self.buckets = prefill_buckets(self.max_prompt_len)

        model = load_model(str(cfg["model"]), num_classes=self.vocab_size)
        if not isinstance(model, TransformerLM):
            raise ValueError(
                f"decode.model {cfg['model']!r} is not a TransformerLM — the "
                "decode engine needs the prefill/decode_step protocol"
            )
        if model.max_seq_len < self.max_seq_len:
            raise ValueError(
                f"decode.max_seq_len={self.max_seq_len} exceeds the model's "
                f"position table ({model.max_seq_len})"
            )
        self.model = model
        sample = jnp.zeros((1, 2), jnp.int32)
        params, model_state = model.init(
            jax.random.key(int(cfg.get("seed") or 0)), sample
        )
        self.restored_epoch = None
        if cfg.get("checkpoint_dir"):
            params, model_state, self.restored_epoch = _restore_variables(
                cfg["checkpoint_dir"],
                str(cfg.get("checkpoint_prefix") or "auto"),
                params,
                model_state,
            )

        if devices is None:
            devices = jax.local_devices()
        n = cfg.get("num_replicas", 1)
        n = len(devices) if n == "auto" else int(n)
        if n < 1 or n > len(devices):
            raise ValueError(
                f"num_replicas={n} outside [1, {len(devices)} local devices]"
            )
        self.replicas = [
            DecodeReplica(i, d, model, params, cfg)
            for i, d in enumerate(devices[:n])
        ]

        quota = cfg.get("per_tenant_quota")
        self.queue = RequestQueue(
            int(cfg["max_queue_depth"]),
            None if quota is None else int(quota),
        )
        self._obs_cfg = cfg_lib.resolve_observability(observability)
        self.flight = None
        if self._obs_cfg["flight_recorder"] and out_dir:
            self.flight = flight_lib.install(flight_lib.FlightRecorder(
                out_dir, capacity=int(self._obs_cfg["flight_capacity"]),
            ))
        self.writer = (
            MetricsWriter(out_dir, flight=self.flight) if out_dir else None
        )
        self.stats = DecodeStats(
            self.writer,
            window=int(cfg["stats_window"]),
            gauges=lambda: (self.kv_occupancy(), self.active_sequences()),
        )
        self.exporter = exp_lib.exporter_from_config(
            self._obs_cfg, run_dir=out_dir
        )
        self._threads: List[threading.Thread] = []
        self._active_counts = [0] * len(self.replicas)
        self._started = False
        self._drained = False
        self._in_flight_at_drain: Optional[int] = None

    @classmethod
    def from_config(
        cls, cfg: dict, out_dir: Optional[str] = None, devices=None,
        observability: Optional[dict] = None,
    ) -> "DecodeEngine":
        """``cfg`` is a resolved ``serving.decode`` block
        (tpuddp/config.py:DECODE_DEFAULTS / decode_config)."""
        return cls(cfg, out_dir=out_dir, devices=devices,
                   observability=observability)

    # -------------------------------------------------------------- gauges --
    def kv_occupancy(self) -> float:
        return sum(r.cache.occupancy() for r in self.replicas) / len(self.replicas)

    def active_sequences(self) -> int:
        return sum(self._active_counts)

    def decode_meta(self) -> dict:
        """The run_meta ``decode`` provenance block (schema v6)."""
        cfg = self.cfg
        return {
            "model": cfg["model"],
            "vocab_size": self.vocab_size,
            "num_replicas": len(self.replicas),
            "max_slots": int(cfg["max_slots"]),
            "kv_blocks": int(cfg["kv_blocks"]),
            "kv_block_size": int(cfg["kv_block_size"]),
            "max_seq_len": self.max_seq_len,
            "max_new_tokens": self.max_new_tokens,
            "stop_token": self.stop_token,
            "temperature": self.temperature,
            "prefill_buckets": list(self.buckets),
        }

    # ----------------------------------------------------------- lifecycle --
    def start(self, warmup: bool = True) -> "DecodeEngine":
        if self._started:
            return self
        if self.exporter is not None:
            self.exporter.start()
            self.exporter.register_source(
                "decode", self.stats.export_source(engine=self)
            )
        if self.writer is not None:
            self.writer.write(schema.make_run_meta(
                world_size=len(self.replicas),
                comm_hook=None,
                guard=None,
                observability={
                    "exporter": (
                        self.exporter.describe()
                        if self.exporter is not None else False
                    ),
                    "aggregate": False,
                    "flight_recorder": (
                        self.flight.describe()
                        if self.flight is not None else False
                    ),
                },
                decode=self.decode_meta(),
                extra={
                    "api": "serving_decode",
                    "model": self.cfg.get("model"),
                    "num_replicas": len(self.replicas),
                    "max_queue_depth": self.queue.max_depth,
                    "per_tenant_quota": self.queue.per_tenant_quota,
                    "buckets": list(self.buckets),
                    "restored_epoch": self.restored_epoch,
                    "checkpoint_dir": self.cfg.get("checkpoint_dir"),
                    "config_hash": schema.config_hash(self.cfg or None),
                },
            ))
        if warmup:
            t0 = time.perf_counter()
            for r in self.replicas:
                r.warmup(self.buckets)
            logger.info(
                "decode: %d replica(s) warm over prefill buckets %s + the "
                "(%d, 1) step in %.1fs",
                len(self.replicas), self.buckets,
                self.replicas[0].cache.max_slots, time.perf_counter() - t0,
            )
        self.stats.reset_clock()
        for replica in self.replicas:
            t = threading.Thread(
                target=self._decode_loop,
                args=(replica,),
                name=f"tpuddp-decode-r{replica.index}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def drain(self, reason: str = "shutdown", timeout: Optional[float] = None) -> dict:
        """Close admission, let in-flight sequences decode to termination,
        flush stats. Idempotent; returns the final summary, which carries
        ``in_flight_at_drain`` — the active + queued sequence count at the
        FIRST drain call, so a drain test can prove the signal landed
        mid-decode rather than against an already-idle engine."""
        if self._in_flight_at_drain is None:
            self._in_flight_at_drain = (
                self.active_sequences() + self.queue.depth()
            )
        self.queue.close()
        for t in self._threads:
            t.join(timeout)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "decode: loop(s) %s still running after the drain timeout; "
                "stats not finalized yet", alive,
            )
            return self._summary()
        if not self._drained:
            self._drained = True
            self.stats.flush_window()
            if self.writer is not None:
                summary = self.stats.summary()
                self.writer.write(schema.stamp("event", {
                    "event": "decode_drain",
                    "reason": reason,
                    **{k: summary[k] for k in (
                        "submitted", "completed", "tokens", "tokens_per_sec",
                    )},
                }))
                self.writer.close()
            if self.exporter is not None:
                self.exporter.stop()
            if self.flight is not None:
                from tpuddp.observability import flight as flight_lib

                flight_lib.uninstall(self.flight)
        return self._summary()

    def _summary(self) -> dict:
        out = self.stats.summary()
        out["in_flight_at_drain"] = self._in_flight_at_drain
        return out

    # -------------------------------------------------------------- client --
    def submit(
        self,
        tenant: str,
        tokens,
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        seed: int = 0,
        stop_token="default",
    ) -> StreamedResult:
        """Admit one prompt (1-D int token ids). Raises
        :class:`AdmissionError` (bad_shape / oversized / queue_full /
        tenant_quota / draining) or returns the streaming future."""
        tokens = np.asarray(tokens)
        self.stats.record_submit()
        try:
            if tokens.ndim != 1 or tokens.shape[0] < 1:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"prompt must be a non-empty 1-D token vector, got shape "
                    f"{tuple(tokens.shape)}",
                )
            if tokens.dtype.kind not in "iu":
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"prompt dtype {tokens.dtype} is not integer token ids",
                )
            if tokens.min() < 0 or tokens.max() >= self.vocab_size:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"token ids outside [0, {self.vocab_size})",
                )
            if tokens.shape[0] > self.max_prompt_len:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"{tokens.shape[0]}-token prompt > max_prompt_len="
                    f"{self.max_prompt_len}",
                )
            mnt = self.max_new_tokens if max_new_tokens is None else int(max_new_tokens)
            if mnt < 1 or mnt > self.max_new_tokens:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"max_new_tokens={mnt} outside [1, {self.max_new_tokens}]",
                )
            if tokens.shape[0] + mnt > self.max_seq_len:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"prompt ({tokens.shape[0]}) + max_new_tokens ({mnt}) > "
                    f"max_seq_len={self.max_seq_len}",
                )
            request = DecodeRequest(
                tenant,
                np.array(tokens, dtype=np.int32, copy=True),  # own the prompt
                mnt,
                self.temperature if temperature is None else float(temperature),
                seed,
                self.stop_token if stop_token == "default" else stop_token,
            )
            self.queue.put(request)
        except AdmissionError as e:
            self.stats.record_reject(tenant, e.reason)
            raise
        return request.result

    # ------------------------------------------------------------- decoding --
    def _finish(self, cache: PagedKVCache, seq: _Active) -> None:
        """Terminate one sequence: free its KV blocks (capacity visible to
        the very next admission pass) and deliver the final array."""
        cache.free(seq.slot)
        seq.req.result._deliver(np.asarray(seq.out, np.int32))
        self.stats.record_finish(seq.req.tenant)

    def _prefill_one(
        self, replica: DecodeReplica, slot: int, req: DecodeRequest
    ) -> Optional[_Active]:
        """Prefill one prompt into its slot and sample the first token.
        Returns the active sequence, or None when it terminated at birth
        (first sample hit the stop token, or max_new_tokens == 1)."""
        cache = replica.cache
        n = len(req.tokens)
        P = batching.bucket_for(n, self.max_prompt_len)
        buf = np.zeros((1, P), np.int32)
        buf[0, :n] = req.tokens
        logits, replica.kpool, replica.vpool = replica._prefill(
            replica.params, replica.kpool, replica.vpool,
            jnp.asarray(cache.tables[slot]), jnp.asarray(buf),
            jnp.asarray(n, jnp.int32),
        )
        cache.lengths[slot] = n
        tok = _sample(np.asarray(logits), req.temperature, req.seed, 0)
        if req.stop_token is not None and tok == req.stop_token:
            # terminated before emitting anything: an empty (but successful)
            # stream — the stop token is consumed, never delivered
            seq = _Active(req, slot, tok)
            seq.out = []
            self._finish(cache, seq)
            return None
        req.result._deliver_token(tok)
        self.stats.record_first_token(
            (time.perf_counter() - req.t_enqueue) * 1e3, n
        )
        seq = _Active(req, slot, tok)
        if seq.n_generated >= req.max_new_tokens:
            self._finish(cache, seq)
            return None
        return seq

    def _recover_pools(
        self, replica: DecodeReplica, active: Dict[int, "_Active"]
    ) -> None:
        """A dispatch that failed AFTER consuming its donated K/V pool
        buffers (donate_argnums — real on an accelerator, ignored by
        XLA:CPU) leaves ``replica.kpool/vpool`` bound to deleted arrays, so
        every later prefill/step on the replica would raise forever. Probe
        for that and rebuild from empty pools; any KV state the surviving
        sequences held lived in the lost buffers, so they are failed too."""
        try:
            poisoned = (
                replica.kpool.is_deleted() or replica.vpool.is_deleted()
            )
        except Exception:  # noqa: BLE001 — treat an unprobeable pool as lost
            poisoned = True
        if not poisoned:
            return
        cache = replica.cache
        err = RuntimeError(
            f"decode replica {replica.index}: KV pools consumed by a failed "
            "donated dispatch; in-flight sequences reset"
        )
        for seq in list(active.values()):
            cache.free(seq.slot)
            seq.req.result._deliver(None, error=err)
        active.clear()
        self._active_counts[replica.index] = 0
        shape = cache.pool_shape()
        replica.kpool = jax.device_put(
            jnp.zeros(shape, jnp.float32), replica.device
        )
        replica.vpool = jax.device_put(
            jnp.zeros(shape, jnp.float32), replica.device
        )
        logger.warning(
            "decode: replica %d KV pools rebuilt after a failed donated "
            "dispatch", replica.index,
        )

    def _decode_loop(self, replica: DecodeReplica) -> None:
        """One replica's life: admit -> prefill -> step -> deliver -> retire,
        every iteration. Exits when the queue closes and drains AND every
        in-flight sequence has terminated (the drain contract: SIGTERM never
        truncates a stream). A failed prefill rejects only its own request;
        a failed step fails the sequences that were in flight on this
        replica (their streams raise), frees their slots, and the loop keeps
        serving — the request engine's failure-isolation contract."""
        cache = replica.cache
        pending: List[DecodeRequest] = []
        active: Dict[int, _Active] = {}
        S = cache.max_slots
        while True:
            # -- admit: pull queued requests round-robin into free capacity.
            # Capacity counts BLOCKS as well as slots, at worst-case lifetime
            # budget (max_blocks per sequence): a block-starved replica must
            # not pull work into its private pending list that an idle
            # sibling could place immediately — requests it cannot yet hold
            # stay in the shared queue where any replica can take them.
            capacity = min(
                cache.free_slots, cache.free_blocks // cache.max_blocks
            )
            if not active and not pending:
                group = self.queue.take_group(max(1, capacity), wait=True)
                if group is None:
                    return
            else:
                room = capacity - len(pending)
                group = (
                    self.queue.take_group(room, wait=False) if room > 0 else []
                )
                group = group or []  # None (closed) -> finish what we hold
            pending.extend(group)
            # -- place: strict arrival order; stop at the first request the
            # pool cannot hold yet, so nobody is starved by a smaller
            # latecomer jumping the block queue
            while pending and cache.can_admit(pending[0].total_tokens):
                req = pending.pop(0)
                slot = cache.allocate(req.total_tokens)
                try:
                    seq = self._prefill_one(replica, slot, req)
                except BaseException as e:  # noqa: BLE001 — delivered to the client
                    logger.exception(
                        "decode: prefill failed on replica %d", replica.index
                    )
                    cache.free(slot)
                    req.result._deliver(None, error=e)
                    self._recover_pools(replica, active)
                    continue
                if seq is not None:
                    active[seq.slot] = seq
            self._active_counts[replica.index] = len(active)
            if not active:
                if pending or not self.queue.closed:
                    continue
                if self.queue.depth() == 0:
                    return
                continue
            # -- step: the one fixed-shape (max_slots, 1) program
            tokens = np.zeros((S,), np.int32)
            for slot, seq in active.items():
                tokens[slot] = seq.last_token
            try:
                logits, replica.kpool, replica.vpool = replica._step(
                    replica.params, replica.kpool, replica.vpool,
                    jnp.asarray(cache.tables), jnp.asarray(cache.lengths),
                    jnp.asarray(tokens),
                )
                logits = np.asarray(logits)  # fetch = fence
            except BaseException as e:  # noqa: BLE001
                logger.exception(
                    "decode: step failed on replica %d", replica.index
                )
                for seq in list(active.values()):
                    cache.free(seq.slot)
                    seq.req.result._deliver(None, error=e)
                active.clear()
                self._active_counts[replica.index] = 0
                self._recover_pools(replica, active)
                continue
            replica.steps += 1
            now = time.perf_counter()
            for slot, seq in list(active.items()):
                cache.lengths[slot] += 1  # the step committed last_token's KV
                tok = _sample(
                    logits[slot], seq.req.temperature, seq.req.seed,
                    seq.n_generated,
                )
                if seq.req.stop_token is not None and tok == seq.req.stop_token:
                    del active[slot]
                    self._finish(cache, seq)
                    continue
                seq.out.append(tok)
                seq.n_generated += 1
                seq.req.result._deliver_token(tok)
                self.stats.record_token((now - seq.t_last) * 1e3)
                seq.t_last = now
                seq.last_token = tok
                if seq.n_generated >= seq.req.max_new_tokens:
                    del active[slot]
                    self._finish(cache, seq)
            self._active_counts[replica.index] = len(active)
