"""Autoregressive decode engine — token-level continuous batching.

The request-granularity engine (tpuddp/serving/engine.py) batches whole
requests: a request joins a batch once and leaves when the batch returns.
Token traffic inverts the granularity: a sequence occupies a *slot* in the
running batch for its whole generation, and the batch's membership changes
**every decode step** — a sequence that samples its stop token frees its KV
blocks immediately and a queued request prefills into the vacated slot
before the next step. Throughput never drains to zero waiting for the
longest sequence of a "batch", because there is no such thing as a batch
boundary.

Two-program scheduling (the prefill/decode split):

- **prefill** — one prompt at a time, padded to a power-of-two length
  bucket (the serving coalescer's ladder applied to the sequence axis): at
  most ``log2(max_prompt) + 1`` compiled prefill programs. The prompt's K/V
  is committed into the paged pool and its last position's logits sample
  the FIRST generated token — TTFT's clock stops here.
- **decode** — ONE fixed-shape ``(max_slots, 1)`` program for every step,
  whatever subset of slots is live: per-slot block tables and lengths are
  ordinary int32 inputs, so sequences joining and leaving never change the
  compiled shape. Compile storms are structurally impossible on the hot
  path.

Sampling runs on the host from the step's logits: greedy argmax, or
temperature softmax drawn from a per-sequence deterministic stream (seeded
by the request's seed and its own token index — never by batch
composition). Combined with per-slot-independent device math, this makes
continuous batching **numerically invisible**: a sequence decodes to
bitwise-identical tokens whether it ran alone or packed among strangers —
the end-to-end acceptance test's contract.

Streaming: ``submit`` returns a :class:`StreamedResult`; every sampled
token is delivered to it as generated (``for tok in result.stream():``),
and ``result()`` still blocks for the full sequence (the ServedResult
contract, so non-streaming callers and load generators work unchanged).

Lifecycle mirrors the request engine: ``start()`` warms every program,
``drain()`` closes admission and lets in-flight sequences finish, and the
``python -m tpuddp.serving --decode`` entrypoint maps SIGTERM onto drain +
exit 75 (the resilience contract).
"""

from __future__ import annotations

import itertools
import logging
import queue as queue_lib
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp.models import load_model
from tpuddp.models.transformer import TransformerLM, prefill_buckets
from tpuddp.observability import MetricsWriter, schema
from tpuddp.observability import trace as trace_lib
from tpuddp.resilience import faults
from tpuddp.serving import queue as queue_mod
from tpuddp.serving import survive as survive_lib
from tpuddp.serving.decode.cache import PagedKVCache
from tpuddp.serving.decode.stats import DecodeStats
from tpuddp.serving.queue import AdmissionError, RequestQueue, ServedResult
from tpuddp.serving.survive import NoHealthyReplicaError, SurvivePolicy
from tpuddp.utils import batching

logger = logging.getLogger("tpuddp")

_ids = itertools.count()
_STREAM_END = object()


class StreamedResult(ServedResult):
    """Future for one sequence's tokens, streamed as generated.

    ``stream()`` yields ints the moment the decode loop samples them;
    ``result(timeout)`` (inherited) blocks for the FULL sequence and returns
    it as an int32 array. A failed sequence raises through both paths."""

    def __init__(self):
        super().__init__()
        self._stream: "queue_lib.Queue" = queue_lib.Queue()
        self.first_token_at: Optional[float] = None

    def _deliver_token(self, token: int) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.perf_counter()
        self._stream.put(int(token))

    def _deliver(self, value, error=None) -> None:
        super()._deliver(value, error=error)
        self._stream.put(_STREAM_END)

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are generated; raises the sequence's error
        (or TimeoutError on a stalled stream, matching ``result()``'s
        contract) instead of hanging."""
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue_lib.Empty:
                raise TimeoutError(
                    f"decode stream stalled: no token within {timeout}s"
                ) from None
            if item is _STREAM_END:
                if self._error is not None:
                    raise self._error
                return
            yield item


class DecodeRequest:
    """One admitted decode request. Duck-types the queue's ``Request``
    protocol (tenant / rows / key / id / t_enqueue) so :class:`RequestQueue`
    admission, per-tenant lanes, and round-robin fairness apply unchanged —
    every request is one row of the same key, so any group assembles.

    Survivability fields: ``deadline`` (absolute; a request still QUEUED
    past it is shed — an in-flight stream is never deadline-killed);
    ``resume_tokens`` is the session-failover journal — None for a fresh
    request, a list of the tokens already streamed to the client when the
    request is re-queued after its replica died (``[]`` = it died during
    prefill, before the first token); ``failed_from`` names the dead
    replica for the ``session_failover`` event."""

    __slots__ = (
        "id", "tenant", "tokens", "max_new_tokens", "temperature", "seed",
        "stop_token", "rows", "key", "t_enqueue", "result",
        "deadline", "resume_tokens", "failed_from", "failovers", "trace",
    )

    def __init__(
        self, tenant: str, tokens: np.ndarray, max_new_tokens: int,
        temperature: float, seed: int, stop_token: Optional[int],
        deadline: Optional[float] = None,
    ):
        self.id = next(_ids)
        self.tenant = str(tenant)
        self.tokens = tokens
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.seed = int(seed)
        self.stop_token = stop_token
        self.rows = 1
        self.key = ("decode",)
        self.t_enqueue = time.perf_counter()
        self.result = StreamedResult()
        self.deadline = deadline
        self.resume_tokens: Optional[List[int]] = None
        self.failed_from: Optional[int] = None
        # times this session was parked into its journal by a replica
        # incident; bounded by SurvivePolicy.max_failovers (the
        # poisoned-request firewall)
        self.failovers = 0
        # causal-tracing context (observability/trace.py; None = off):
        # {"root": Span, "open": Span|None, "last_id": int|None} — the one
        # tree this session keeps across queueing, prefill, AND failover,
        # so a resumed stream is a single trace with a follows_from edge
        self.trace = None

    @property
    def total_tokens(self) -> int:
        """Worst-case lifetime length — the KV budget reserved up front."""
        return len(self.tokens) + self.max_new_tokens


class _Active:
    """One sequence occupying a decode slot.

    ``replay`` (session failover): tokens this sequence already streamed to
    its client whose K/V must be re-committed on the new replica. While
    non-empty, each decode step feeds the next recorded token instead of
    sampling and delivers NOTHING (the client saw these tokens already);
    once the replay drains, live sampling resumes at token index
    ``n_generated`` — and because every K/V position was rebuilt by the
    same program kind that wrote it originally (prompt by prefill, replay
    tokens by the step) and sampling is keyed by ``(seed, index)`` only,
    the continued stream is bitwise the undisturbed one."""

    __slots__ = (
        "req", "slot", "last_token", "n_generated", "out", "t_last", "replay",
    )

    def __init__(self, req: DecodeRequest, slot: int, first_token: int):
        self.req = req
        self.slot = slot
        self.last_token = first_token
        self.n_generated = 1
        self.out = [first_token]
        self.t_last = time.perf_counter()
        self.replay: List[int] = []


def _sample(logits_row: np.ndarray, temperature: float, seed: int, index: int) -> int:
    """Host-side sampling. Greedy at temperature 0; otherwise softmax with a
    stream keyed by (request seed, token index) ONLY — two decodes of the
    same request sample identically whatever else shares their batch."""
    if temperature <= 0.0:
        return int(np.argmax(logits_row))
    rng = np.random.RandomState((seed * 1000003 + index * 7919 + 0x5D) & 0x7FFFFFFF)
    z = logits_row.astype(np.float64) / temperature
    z -= z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


class DecodeReplica:
    """One device's decode lane: committed params, the jitted prefill (one
    program per prompt bucket) and fixed-shape step programs, and a private
    :class:`PagedKVCache` + K/V pool pair."""

    def __init__(self, index: int, device, model: TransformerLM, params, cfg: dict):
        self.index = index
        self.device = device
        self.model = model
        self.params = jax.device_put(params, device)
        self.cache = PagedKVCache(
            layers=model.n_layers,
            heads=model.n_heads,
            head_dim=model.head_dim,
            num_blocks=int(cfg["kv_blocks"]),
            block_size=int(cfg["kv_block_size"]),
            max_slots=int(cfg["max_slots"]),
            max_seq_len=int(cfg["max_seq_len"]),
        )
        shape = self.cache.pool_shape()
        self.kpool = jax.device_put(jnp.zeros(shape, jnp.float32), device)
        self.vpool = jax.device_put(jnp.zeros(shape, jnp.float32), device)
        # the pools are threaded through and the old buffers donated (cache
        # module doc): without donation every token step would COPY both
        # full K/V pools — doubling cache memory and adding a pool-sized
        # transfer per step. Args: (params, kpool, vpool, ...) -> donate 1, 2.
        # (XLA:CPU ignores donation with a note; the TPU path is the point.)
        self._prefill = jax.jit(model.prefill, donate_argnums=(1, 2))
        self._step = jax.jit(model.decode_step, donate_argnums=(1, 2))
        self.steps = 0
        # survivability state machine (tpuddp/serving/survive.py):
        # healthy -> recovering (probation) -> healthy | removed. ``broken``
        # simulates device death (replica_kill chaos): every dispatch
        # raises until rebuild() clears it. ``recoveries`` counts lifetime
        # probation rejoins, bounded by the policy's max_recoveries.
        self.state = "healthy"
        self.recoveries = 0
        self.broken = False
        # True while this replica's decode-loop THREAD is running — the
        # survivor check must not hand failover journals to a peer whose
        # loop already exited at drain (state alone cannot tell)
        self.loop_alive = False

    @property
    def healthy(self) -> bool:
        return self.state == "healthy"

    def check_broken(self) -> None:
        if self.broken:
            raise RuntimeError(
                f"decode replica {self.index} is down (injected replica_kill)"
            )

    def rebuild(self) -> None:
        """Probation step 1: fresh KV pool + block-table allocator (every
        sequence that lived here has been parked into the failover journal)
        and cleared kill flag — the restarted-device state."""
        self.cache = PagedKVCache(
            layers=self.cache.layers,
            heads=self.cache.heads,
            head_dim=self.cache.head_dim,
            num_blocks=self.cache.num_blocks,
            block_size=self.cache.block_size,
            max_slots=self.cache.max_slots,
            max_seq_len=self.cache.max_seq_len,
        )
        shape = self.cache.pool_shape()
        self.kpool = jax.device_put(jnp.zeros(shape, jnp.float32), self.device)
        self.vpool = jax.device_put(jnp.zeros(shape, jnp.float32), self.device)
        self.broken = False

    def canary(self, buckets: List[int]) -> None:
        """Probation step 2: re-warm (the bucket ladder + step program are
        already compiled; this re-executes them against the fresh pools)
        and require a finite canary step — a replica that cannot decode the
        canary does not rejoin routing."""
        self.check_broken()
        self.warmup(buckets)
        S = self.cache.max_slots
        out, self.kpool, self.vpool = self._step(
            self.params, self.kpool, self.vpool,
            jnp.zeros((S, self.cache.max_blocks), jnp.int32),
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        )
        if not np.all(np.isfinite(np.asarray(out))):
            raise RuntimeError(
                f"decode replica {self.index} canary produced non-finite "
                "logits"
            )

    def warmup(self, buckets: List[int]) -> None:
        """Compile every prefill bucket + the step program now. Warmup
        traffic writes only into reserved garbage block 0 (all-zero table
        rows), so the allocatable pool is untouched."""
        zrow = jnp.zeros((self.cache.max_blocks,), jnp.int32)
        for P in buckets:
            toks = jnp.zeros((1, P), jnp.int32)
            out, self.kpool, self.vpool = self._prefill(
                self.params, self.kpool, self.vpool, zrow, toks,
                jnp.asarray(1, jnp.int32),
            )
            jax.block_until_ready(out)
        S = self.cache.max_slots
        out, self.kpool, self.vpool = self._step(
            self.params, self.kpool, self.vpool,
            jnp.zeros((S, self.cache.max_blocks), jnp.int32),
            jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
        )
        jax.block_until_ready(out)
        self.steps = 0


class DecodeEngine:
    """Token-level continuous-batching engine over N decode replicas."""

    def __init__(
        self,
        cfg: dict,
        out_dir: Optional[str] = None,
        devices=None,
        observability: Optional[dict] = None,
    ):
        from tpuddp import config as cfg_lib
        from tpuddp.observability import exporter as exp_lib
        from tpuddp.observability import flight as flight_lib
        from tpuddp.serving.replica import _restore_variables

        self.cfg = dict(cfg)
        self.vocab_size = int(cfg["vocab_size"])
        self.max_seq_len = int(cfg["max_seq_len"])
        self.max_new_tokens = int(cfg["max_new_tokens"])
        self.max_prompt_len = self.max_seq_len - 1  # >= 1 generated token
        self.stop_token = (
            None if cfg.get("stop_token") is None else int(cfg["stop_token"])
        )
        self.temperature = float(cfg.get("temperature") or 0.0)
        self.buckets = prefill_buckets(self.max_prompt_len)

        model = load_model(str(cfg["model"]), num_classes=self.vocab_size)
        if not isinstance(model, TransformerLM):
            raise ValueError(
                f"decode.model {cfg['model']!r} is not a TransformerLM — the "
                "decode engine needs the prefill/decode_step protocol"
            )
        if model.max_seq_len < self.max_seq_len:
            raise ValueError(
                f"decode.max_seq_len={self.max_seq_len} exceeds the model's "
                f"position table ({model.max_seq_len})"
            )
        self.model = model
        sample = jnp.zeros((1, 2), jnp.int32)
        params, model_state = model.init(
            jax.random.key(int(cfg.get("seed") or 0)), sample
        )
        self.restored_epoch = None
        if cfg.get("checkpoint_dir"):
            params, model_state, self.restored_epoch = _restore_variables(
                cfg["checkpoint_dir"],
                str(cfg.get("checkpoint_prefix") or "auto"),
                params,
                model_state,
            )

        if devices is None:
            devices = jax.local_devices()
        n = cfg.get("num_replicas", 1)
        n = len(devices) if n == "auto" else int(n)
        if n < 1 or n > len(devices):
            raise ValueError(
                f"num_replicas={n} outside [1, {len(devices)} local devices]"
            )
        self.replicas = [
            DecodeReplica(i, d, model, params, cfg)
            for i, d in enumerate(devices[:n])
        ]

        quota = cfg.get("per_tenant_quota")
        self.queue = RequestQueue(
            int(cfg["max_queue_depth"]),
            None if quota is None else int(quota),
        )
        self.survive = SurvivePolicy.from_config(cfg)
        self.queue.shed_handler = self._on_shed
        self._health_lock = threading.Lock()
        self._step_counter = itertools.count(1)  # chaos site step=N
        self._obs_cfg = cfg_lib.resolve_observability(observability)
        # causal tracing plane (observability/trace.py, default OFF): one
        # tree per session (request -> admission -> queue_wait -> prefill,
        # failover episodes linked follows_from so a resumed stream stays
        # ONE trace) plus per-replica decode_step rows; trace_decode.json
        # at drain, live on /trace
        self.tracer = trace_lib.tracer_from_config(
            self._obs_cfg, "decode", run_dir=out_dir
        )
        self._engine_trace = None  # the decode_step timeline's trace id
        self.flight = None
        if self._obs_cfg["flight_recorder"] and out_dir:
            self.flight = flight_lib.install(flight_lib.FlightRecorder(
                out_dir, capacity=int(self._obs_cfg["flight_capacity"]),
            ))
            if self.tracer.enabled:
                self.flight.add_context(
                    "open_spans", self.tracer.open_span_summaries
                )
        self.writer = (
            MetricsWriter(out_dir, flight=self.flight) if out_dir else None
        )
        self.stats = DecodeStats(
            self.writer,
            window=int(cfg["stats_window"]),
            gauges=lambda: (self.kv_occupancy(), self.active_sequences()),
        )
        self.exporter = exp_lib.exporter_from_config(
            self._obs_cfg, run_dir=out_dir
        )
        self._threads: List[threading.Thread] = []
        self._active_counts = [0] * len(self.replicas)
        self._started = False
        self._drained = False
        self._in_flight_at_drain: Optional[int] = None

    @classmethod
    def from_config(
        cls, cfg: dict, out_dir: Optional[str] = None, devices=None,
        observability: Optional[dict] = None,
    ) -> "DecodeEngine":
        """``cfg`` is a resolved ``serving.decode`` block
        (tpuddp/config.py:DECODE_DEFAULTS / decode_config)."""
        return cls(cfg, out_dir=out_dir, devices=devices,
                   observability=observability)

    # -------------------------------------------------------------- gauges --
    def _event(self, record: dict) -> None:
        if self.writer is not None:
            self.writer.write(schema.stamp("event", record))

    def _on_shed(self, request) -> None:
        """Queue shed callback: one queued decode request expired past its
        deadline and was dropped before prefill (its future already carries
        the typed ``deadline_exceeded`` rejection)."""
        self._trace_fail(request, "deadline_exceeded")
        self.stats.record_shed(request.tenant)

    def kv_occupancy(self) -> float:
        """Mean KV-pool occupancy across replicas still IN routing. A
        removed replica's cache is stale garbage (its parked sessions'
        slots were never freed — probation's rebuild never ran), and
        counting it would pin the exported gauge high forever, feeding the
        autoscaler's occupancy rule sustained phantom pressure."""
        live = [r for r in self.replicas if r.state != "removed"]
        if not live:
            return 0.0
        return sum(r.cache.occupancy() for r in live) / len(live)

    def active_sequences(self) -> int:
        return sum(self._active_counts)

    def decode_meta(self) -> dict:
        """The run_meta ``decode`` provenance block (schema v6)."""
        cfg = self.cfg
        return {
            "model": cfg["model"],
            "vocab_size": self.vocab_size,
            "num_replicas": len(self.replicas),
            "max_slots": int(cfg["max_slots"]),
            "kv_blocks": int(cfg["kv_blocks"]),
            "kv_block_size": int(cfg["kv_block_size"]),
            "max_seq_len": self.max_seq_len,
            "max_new_tokens": self.max_new_tokens,
            "stop_token": self.stop_token,
            "temperature": self.temperature,
            "prefill_buckets": list(self.buckets),
        }

    # ----------------------------------------------------------- lifecycle --
    def start(self, warmup: bool = True) -> "DecodeEngine":
        if self._started:
            return self
        if self.exporter is not None:
            self.exporter.start()
            self.exporter.register_source(
                "decode", self.stats.export_source(engine=self)
            )
            if self.tracer.enabled:
                self.exporter.set_trace_source(self.tracer.endpoint_payload)
        self._engine_trace = self.tracer.new_trace()
        if self.writer is not None:
            self.writer.write(schema.make_run_meta(
                world_size=len(self.replicas),
                comm_hook=None,
                guard=None,
                observability={
                    "exporter": (
                        self.exporter.describe()
                        if self.exporter is not None else False
                    ),
                    "aggregate": False,
                    "flight_recorder": (
                        self.flight.describe()
                        if self.flight is not None else False
                    ),
                },
                decode=self.decode_meta(),
                survivability=self.survive.meta(),
                tracing=self.tracer.describe(),
                extra={
                    "api": "serving_decode",
                    "model": self.cfg.get("model"),
                    "num_replicas": len(self.replicas),
                    "max_queue_depth": self.queue.max_depth,
                    "per_tenant_quota": self.queue.per_tenant_quota,
                    "buckets": list(self.buckets),
                    "restored_epoch": self.restored_epoch,
                    "checkpoint_dir": self.cfg.get("checkpoint_dir"),
                    "config_hash": schema.config_hash(self.cfg or None),
                },
            ))
        if warmup:
            t0 = time.perf_counter()
            for r in self.replicas:
                r.warmup(self.buckets)
            logger.info(
                "decode: %d replica(s) warm over prefill buckets %s + the "
                "(%d, 1) step in %.1fs",
                len(self.replicas), self.buckets,
                self.replicas[0].cache.max_slots, time.perf_counter() - t0,
            )
        self.stats.reset_clock()
        for replica in self.replicas:
            t = threading.Thread(
                target=self._decode_loop,
                args=(replica,),
                name=f"tpuddp-decode-r{replica.index}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._started = True
        return self

    def drain(self, reason: str = "shutdown", timeout: Optional[float] = None) -> dict:
        """Close admission, let in-flight sequences decode to termination,
        flush stats. Idempotent; returns the final summary, which carries
        ``in_flight_at_drain`` — the active + queued sequence count at the
        FIRST drain call, so a drain test can prove the signal landed
        mid-decode rather than against an already-idle engine."""
        if self._in_flight_at_drain is None:
            self._in_flight_at_drain = (
                self.active_sequences() + self.queue.depth()
            )
        self.queue.close()
        for t in self._threads:
            t.join(timeout)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            logger.warning(
                "decode: loop(s) %s still running after the drain timeout; "
                "stats not finalized yet", alive,
            )
            return self._summary()
        if not self._drained:
            self._drained = True
            self.stats.flush_window()
            if self.tracer.enabled:
                if self.writer is not None:
                    self.writer.write(schema.stamp(
                        "trace_summary", self.tracer.summary_record()
                    ))
                self.tracer.export()
            if self.writer is not None:
                summary = self.stats.summary()
                self.writer.write(schema.stamp("event", {
                    "event": "decode_drain",
                    "reason": reason,
                    **{k: summary[k] for k in (
                        "submitted", "completed", "tokens", "tokens_per_sec",
                    )},
                }))
                self.writer.close()
            if self.exporter is not None:
                self.exporter.stop()
            if self.flight is not None:
                from tpuddp.observability import flight as flight_lib

                flight_lib.uninstall(self.flight)
        return self._summary()

    def _summary(self) -> dict:
        out = self.stats.summary()
        out["in_flight_at_drain"] = self._in_flight_at_drain
        return out

    # -------------------------------------------------------------- client --
    def submit(
        self,
        tenant: str,
        tokens,
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        seed: int = 0,
        stop_token="default",
        deadline_s: Optional[float] = None,
    ) -> StreamedResult:
        """Admit one prompt (1-D int token ids). Raises
        :class:`AdmissionError` (bad_shape / oversized / queue_full /
        tenant_quota / draining) or returns the streaming future.

        ``deadline_s``: optional client deadline (seconds from now),
        combined with the engine's ``request_ttl_s``: a request still
        QUEUED past the tighter bound is shed with a ``deadline_exceeded``
        rejection through the future; a sequence that started decoding is
        NEVER killed by its deadline."""
        tokens = np.asarray(tokens)
        self.stats.record_submit()
        t = self.tracer
        root = t.start_span(
            "request", trace_lib.KIND_REQUEST, tid="client",
            attrs={"tenant": str(tenant)},
        )
        adm = t.start_span("admission", trace_lib.KIND_ADMISSION, parent=root)
        request = None
        try:
            if tokens.ndim != 1 or tokens.shape[0] < 1:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"prompt must be a non-empty 1-D token vector, got shape "
                    f"{tuple(tokens.shape)}",
                )
            if tokens.dtype.kind not in "iu":
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"prompt dtype {tokens.dtype} is not integer token ids",
                )
            if tokens.min() < 0 or tokens.max() >= self.vocab_size:
                raise AdmissionError(
                    queue_mod.REJECT_BAD_SHAPE,
                    f"token ids outside [0, {self.vocab_size})",
                )
            if tokens.shape[0] > self.max_prompt_len:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"{tokens.shape[0]}-token prompt > max_prompt_len="
                    f"{self.max_prompt_len}",
                )
            mnt = self.max_new_tokens if max_new_tokens is None else int(max_new_tokens)
            if mnt < 1 or mnt > self.max_new_tokens:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"max_new_tokens={mnt} outside [1, {self.max_new_tokens}]",
                )
            if tokens.shape[0] + mnt > self.max_seq_len:
                raise AdmissionError(
                    queue_mod.REJECT_OVERSIZED,
                    f"prompt ({tokens.shape[0]}) + max_new_tokens ({mnt}) > "
                    f"max_seq_len={self.max_seq_len}",
                )
            request = DecodeRequest(
                tenant,
                np.array(tokens, dtype=np.int32, copy=True),  # own the prompt
                mnt,
                self.temperature if temperature is None else float(temperature),
                seed,
                self.stop_token if stop_token == "default" else stop_token,
                deadline=survive_lib.admission_deadline(
                    time.perf_counter(), self.survive.request_ttl_s, deadline_s
                ),
            )
            t.end_span(
                adm, prompt_len=int(tokens.shape[0]), request=request.id
            )
            if t.enabled:
                # attach BEFORE put (the request-engine rule): once put()
                # publishes the request a decode loop may place it, and a
                # trace attached after would race the prefill and leak a
                # never-closed queue_wait
                request.trace = {
                    "root": root,
                    "open": t.start_span(
                        "queue_wait", trace_lib.KIND_QUEUE_WAIT, parent=root,
                    ),
                    "last_id": None,
                }
            self.queue.put(request)
        except AdmissionError as e:
            if request is not None and request.trace:
                t.end_span(request.trace["open"], error=e.reason)
                request.trace = None
            t.end_span(adm, rejected=e.reason)
            t.end_span(root, error=e.reason)
            self.stats.record_reject(tenant, e.reason)
            raise
        return request.result

    # ------------------------------------------------------------- decoding --
    def _finish(self, cache: PagedKVCache, seq: _Active) -> None:
        """Terminate one sequence: free its KV blocks (capacity visible to
        the very next admission pass) and deliver the final array."""
        cache.free(seq.slot)
        seq.req.result._deliver(np.asarray(seq.out, np.int32))
        if seq.req.trace:
            self.tracer.end_span(
                seq.req.trace["root"], tokens=len(seq.out),
                failovers=seq.req.failovers,
            )
            seq.req.trace = None
        self.stats.record_finish(seq.req.tenant)

    def _trace_fail(self, req: DecodeRequest, error) -> None:
        """Close a failed session's trace (the shared
        :func:`~tpuddp.observability.trace.end_request_trace` sequence —
        every failure path: shed, max-failovers, mortuary)."""
        trace_lib.end_request_trace(self.tracer, req, error)

    def _prefill_dispatch(
        self, replica: DecodeReplica, slot: int, req: DecodeRequest
    ):
        """The ONE prompt-prefill dispatch both the fresh path and the
        failover-resume path run: bucket the prompt, commit its K/V into
        the slot, return the last position's logits. Bitwise-critical
        single source — a resume must prefill exactly as the undisturbed
        run did, or the continuation guarantee breaks."""
        cache = replica.cache
        n = len(req.tokens)
        P = batching.bucket_for(n, self.max_prompt_len)
        t = self.tracer
        if req.trace and req.trace.get("open") is not None:
            t.end_span(req.trace["open"])  # queue wait ends at placement
            req.trace["open"] = None
        resuming = req.failed_from is not None
        psp = t.start_span(
            "prefill", trace_lib.KIND_PREFILL,
            parent=req.trace["root"] if req.trace else None,
            # the failover edge: a resume's prefill follows causally from
            # the session's last span on the dead replica — one trace, one
            # stream, across the migration
            follows_from=(
                req.trace.get("last_id") if (req.trace and resuming) else None
            ),
            attrs={
                "replica": replica.index, "prompt_len": n, "bucket": P,
                **({"resume": True} if resuming else {}),
            },
        )
        buf = np.zeros((1, P), np.int32)
        buf[0, :n] = req.tokens
        try:
            logits, replica.kpool, replica.vpool = replica._prefill(
                replica.params, replica.kpool, replica.vpool,
                jnp.asarray(cache.tables[slot]), jnp.asarray(buf),
                jnp.asarray(n, jnp.int32),
            )
        except BaseException as e:
            t.end_span(psp, error=repr(e))
            if req.trace:
                # the errored prefill IS the session's last span: a later
                # resume must follows_from it or the trace loses the episode
                req.trace["last_id"] = psp.span_id
            raise
        t.end_span(psp)
        if req.trace:
            req.trace["last_id"] = psp.span_id
        cache.lengths[slot] = n
        return logits

    def _prefill_one(
        self, replica: DecodeReplica, slot: int, req: DecodeRequest
    ) -> Optional[_Active]:
        """Prefill one prompt into its slot and sample the first token.
        Returns the active sequence, or None when it terminated at birth
        (first sample hit the stop token, or max_new_tokens == 1)."""
        cache = replica.cache
        n = len(req.tokens)
        logits = self._prefill_dispatch(replica, slot, req)
        tok = _sample(np.asarray(logits), req.temperature, req.seed, 0)
        if req.stop_token is not None and tok == req.stop_token:
            # terminated before emitting anything: an empty (but successful)
            # stream — the stop token is consumed, never delivered
            seq = _Active(req, slot, tok)
            seq.out = []
            self._finish(cache, seq)
            return None
        req.result._deliver_token(tok)
        self.stats.record_first_token(
            (time.perf_counter() - req.t_enqueue) * 1e3, n
        )
        seq = _Active(req, slot, tok)
        if seq.n_generated >= req.max_new_tokens:
            self._finish(cache, seq)
            return None
        return seq

    def _resume_one(
        self, replica: DecodeReplica, slot: int, req: DecodeRequest
    ) -> Optional[_Active]:
        """Session failover re-admission: continue a sequence whose replica
        died, **bitwise-equal** to an undisturbed run.

        The journal (``req.resume_tokens``) holds every token already
        streamed to the client. The original prompt is re-prefilled through
        the SAME prefill program the undisturbed run used (its sampled
        logits are discarded — those tokens are known), and the generated
        prefix is queued for REPLAY through the step program: each replay
        step re-commits one recorded token's K/V exactly the way the
        original run committed it, delivering nothing. Every K/V position
        is therefore rebuilt by the same program kind that wrote it
        originally, and host sampling is keyed by ``(seed, token index)``
        alone — so when live decoding resumes at the journal's cursor, the
        continuation is bitwise the stream the dead replica would have
        produced."""
        journal = list(req.resume_tokens)
        if not journal:
            # died during prefill, before its first token: a fresh prefill
            # IS the bitwise resume (token index 0 samples identically)
            req.resume_tokens = None
            try:
                seq = self._prefill_one(replica, slot, req)
            except BaseException:
                req.resume_tokens = []  # keep the journal for the next try
                raise
            self._record_failover(replica, req, 0)
            return seq
        self._prefill_dispatch(replica, slot, req)  # sampled logits
        # discarded: the journal already knows these tokens
        req.resume_tokens = None
        self._record_failover(replica, req, len(journal))
        seq = _Active(req, slot, journal[0])
        seq.out = list(journal)
        seq.n_generated = len(journal)
        seq.replay = list(journal[1:])
        return seq

    def _record_failover(
        self, replica: DecodeReplica, req: DecodeRequest, tokens: int
    ) -> None:
        if req.trace:
            # the episode marker (zero-length annotation in the session's
            # own trace — the resume prefill carries the follows_from edge)
            self.tracer.end_span(self.tracer.start_span(
                "failover", trace_lib.KIND_FAILOVER, parent=req.trace["root"],
                attrs={
                    "from_replica": req.failed_from,
                    "to_replica": replica.index,
                    "tokens_journaled": tokens,
                },
            ))
        self.stats.record_failover(req.tenant)
        self._event({
            "event": "session_failover",
            "request": req.id,
            "tenant": req.tenant,
            "from_replica": req.failed_from,
            "to_replica": replica.index,
            "tokens_generated": tokens,
        })
        logger.warning(
            "decode: session %d (tenant %s) failed over from replica %s to "
            "%d with %d token(s) journaled",
            req.id, req.tenant, req.failed_from, replica.index, tokens,
        )

    def _replica_incident(
        self,
        replica: DecodeReplica,
        pending: List[DecodeRequest],
        active: Dict[int, "_Active"],
        error: BaseException,
    ) -> bool:
        """A dispatch on ``replica`` died (step/prefill raised — possibly
        after consuming the donated K/V pools). Park every live session
        into its failover journal and re-queue it at lane front (immune to
        deadline shedding and the closed flag — a draining engine still
        owes its streams), return untouched pending work to the shared
        queue, then run one probation episode (rebuild pools + canary,
        jittered backoff, bounded by the policy). True = the replica
        recovered and rejoins routing; False = it is permanently removed
        (the caller decides between exiting to surviving peers and the
        typed no-healthy-replica fallback).

        Attribution: a place-phase failure tags its CULPRIT on the
        exception. Only the culprit is charged a failover episode (the
        poisoned-request firewall — innocent sessions parked by someone
        else's incident ride free), and a culprit-attributed incident
        whose canary then passes does not charge the replica's lifetime
        ``max_recoveries`` budget either: the device was provably never
        the problem. Unattributed (step) failures are device evidence —
        they charge the replica, and park every session for free."""
        culprit = getattr(error, "_tpuddp_culprit", None)
        logger.exception(
            "decode: dispatch failed on replica %d; parking %d session(s), "
            "returning %d pending request(s)",
            replica.index, len(active), len(pending),
        )
        with self._health_lock:
            replica.state = "recovering"
        self._event({
            "event": "replica_unhealthy",
            "replica": replica.index,
            "error": repr(error),
            "sessions": len(active),
        })
        # requeue is appendleft: push pending in reverse to preserve FIFO,
        # then the journals, so live sessions land ahead of untouched work
        for req in reversed(pending):
            if req is culprit:
                if not self._park(req, error):
                    continue
                # the parked culprit resumes like any other session: name
                # the replica it died on (the failover event's from_replica,
                # and what marks its next prefill a resume) and reopen a
                # queue_wait in its trace — its original one closed when the
                # failed prefill began, and without this the second wait
                # renders as an unexplained gap with no follows_from edge
                req.failed_from = replica.index
                if req.trace and req.trace.get("open") is None:
                    req.trace["open"] = self.tracer.start_span(
                        "queue_wait", trace_lib.KIND_QUEUE_WAIT,
                        parent=req.trace["root"],
                        follows_from=req.trace.get("last_id"),
                        attrs={"parked_from": replica.index},
                    )
            self.queue.requeue(req)
        pending.clear()
        for slot in sorted(active.keys(), reverse=True):
            seq = active[slot]
            seq.req.resume_tokens = list(seq.out)
            seq.req.failed_from = replica.index
            if seq.req.trace:
                # parked: back to waiting — a fresh queue_wait in the SAME
                # trace, linked to the session's last pre-death span
                seq.req.trace["open"] = self.tracer.start_span(
                    "queue_wait", trace_lib.KIND_QUEUE_WAIT,
                    parent=seq.req.trace["root"],
                    follows_from=seq.req.trace.get("last_id"),
                    attrs={"parked_from": replica.index},
                )
            self.queue.requeue(seq.req)
        active.clear()
        self._active_counts[replica.index] = 0

        def recover():
            replica.rebuild()
            replica.canary(self.buckets)

        psp = self.tracer.start_span(
            f"probation replica {replica.index}", trace_lib.KIND_PROBATION,
            trace_id=self._engine_trace, tid=f"replica{replica.index}",
            attrs={"recoveries": replica.recoveries},
        )
        ok, event = survive_lib.probation_episode(
            replica,
            name=f"decode replica {replica.index}",
            recover=recover,
            policy=self.survive,
            count_recovery=culprit is None,
            lock=self._health_lock,
        )
        self.tracer.end_span(psp, outcome="recovered" if ok else "removed")
        self._event(event)
        return ok

    def _park(self, req: DecodeRequest, error: BaseException) -> bool:
        """Charge one failover episode to the CULPRIT of a place-phase
        incident. True = within the budget (the caller journals + requeues
        it); False = the budget is spent — the request is failed through
        with the dispatch error (delivered here) instead of re-parked, so
        a request whose own content kills any dispatch cannot ride its
        journal around the pool forever."""
        req.failovers += 1
        if req.failovers <= self.survive.max_failovers:
            return True
        logger.error(
            "decode: session %d (tenant %s) exceeded max_failovers=%d — "
            "failing it with the dispatch error instead of re-parking "
            "(poisoned-request firewall)",
            req.id, req.tenant, self.survive.max_failovers,
        )
        self._trace_fail(req, error)
        req.result._deliver(None, error=error)
        return False

    def _shed_expired_pending(self, pending: List[DecodeRequest]) -> None:
        """Deadline shedding for the loop's private pending list: a pulled-
        but-never-prefilled request is still queued work. Journals
        (in-flight sessions mid-migration) are exempt."""
        if not pending:
            return
        now = time.perf_counter()
        keep = []
        for req in pending:
            if (
                req.resume_tokens is None
                and req.deadline is not None
                and now > req.deadline
            ):
                self.queue._deliver_shed(req)
            else:
                keep.append(req)
        pending[:] = keep

    def _serve_once(
        self,
        replica: DecodeReplica,
        pending: List[DecodeRequest],
        active: Dict[int, "_Active"],
    ) -> bool:
        """One admit -> place -> step -> deliver iteration. True = the
        queue is closed and fully drained (the loop's exit signal). Any
        dispatch failure raises to the caller's incident handler."""
        cache = replica.cache
        S = cache.max_slots
        self._shed_expired_pending(pending)
        # -- admit: pull queued requests round-robin into free capacity.
        # Capacity counts BLOCKS as well as slots, at worst-case lifetime
        # budget (max_blocks per sequence): a block-starved replica must
        # not pull work into its private pending list that an idle
        # sibling could place immediately — requests it cannot yet hold
        # stay in the shared queue where any replica can take them.
        capacity = min(
            cache.free_slots, cache.free_blocks // cache.max_blocks
        )
        if not active and not pending:
            group = self.queue.take_group(max(1, capacity), wait=True)
            if group is None:
                return True
        else:
            room = capacity - len(pending)
            group = (
                self.queue.take_group(room, wait=False) if room > 0 else []
            )
            group = group or []  # None (closed) -> finish what we hold
        pending.extend(group)
        # -- place: strict arrival order; stop at the first request the
        # pool cannot hold yet, so nobody is starved by a smaller
        # latecomer jumping the block queue
        while pending and cache.can_admit(pending[0].total_tokens):
            req = pending.pop(0)
            slot = cache.allocate(req.total_tokens)
            try:
                if req.resume_tokens is not None:
                    seq = self._resume_one(replica, slot, req)
                else:
                    seq = self._prefill_one(replica, slot, req)
            except BaseException as e:
                # the request mid-prefill becomes a live session with an
                # empty journal (it was admitted and dispatched); put it
                # back at the head so the incident handler parks it. Tag
                # it as the incident's CULPRIT: a place-phase failure is
                # attributable to the one request being placed, and only
                # the culprit is charged a failover episode (innocent
                # parked sessions ride free) or can spare the replica's
                # lifetime probation budget.
                if req.resume_tokens is None:
                    req.resume_tokens = []
                pending.insert(0, req)
                try:
                    e._tpuddp_culprit = req
                except Exception:  # noqa: BLE001 — exotic exception types
                    pass
                raise
            if seq is not None:
                active[seq.slot] = seq
        self._active_counts[replica.index] = len(active)
        if not active:
            if pending or not self.queue.closed:
                return False
            if self.queue.depth() == 0:
                return True
            return False
        # -- step: the one fixed-shape (max_slots, 1) program
        tokens = np.zeros((S,), np.int32)
        for slot, seq in active.items():
            tokens[slot] = seq.last_token
        ssp = self.tracer.start_span(
            "decode_step", trace_lib.KIND_DECODE_STEP,
            trace_id=self._engine_trace, tid=f"replica{replica.index}",
            attrs={"step": replica.steps, "active": len(active)},
        )
        try:
            kind = faults.maybe_serving_fault(
                "step", step=next(self._step_counter)
            )
            if kind == "replica_kill":
                replica.broken = True  # persistent until rebuild()
            if kind == "pool_poison":
                # the donated-buffer death: the pools are gone mid-sweep
                replica.kpool.delete()
                replica.vpool.delete()
                raise RuntimeError("injected pool_poison fault: KV pools lost")
            if kind == "dispatch_wedge":
                raise RuntimeError("injected dispatch_wedge fault (transient)")
            replica.check_broken()
            logits, replica.kpool, replica.vpool = replica._step(
                replica.params, replica.kpool, replica.vpool,
                jnp.asarray(cache.tables), jnp.asarray(cache.lengths),
                jnp.asarray(tokens),
            )
            logits = np.asarray(logits)  # fetch = fence
        except BaseException as e:
            self.tracer.end_span(ssp, error=repr(e))
            raise
        self.tracer.end_span(ssp)
        replica.steps += 1
        now = time.perf_counter()
        for slot, seq in list(active.items()):
            cache.lengths[slot] += 1  # the step committed last_token's KV
            if seq.replay:
                # failover replay: the step re-committed a recorded token's
                # K/V; the client already has every replayed token, so
                # nothing is sampled, delivered, or counted
                seq.last_token = seq.replay.pop(0)
                seq.t_last = now
                continue
            tok = _sample(
                logits[slot], seq.req.temperature, seq.req.seed,
                seq.n_generated,
            )
            if seq.req.stop_token is not None and tok == seq.req.stop_token:
                del active[slot]
                self._finish(cache, seq)
                continue
            seq.out.append(tok)
            seq.n_generated += 1
            seq.req.result._deliver_token(tok)
            self.stats.record_token((now - seq.t_last) * 1e3)
            seq.t_last = now
            seq.last_token = tok
            if seq.n_generated >= seq.req.max_new_tokens:
                del active[slot]
                self._finish(cache, seq)
        self._active_counts[replica.index] = len(active)
        return False

    def _decode_loop(self, replica: DecodeReplica) -> None:
        """One replica's life: admit -> prefill -> step -> deliver -> retire,
        every iteration. Exits when the queue closes and drains AND every
        in-flight sequence has terminated (the drain contract: SIGTERM never
        truncates a stream).

        Survivability: a failed dispatch no longer kills its streams — the
        incident handler parks every live session into a failover journal
        (re-queued at lane front for ANY replica to resume bitwise) and
        runs probation on this replica. Recovered -> rejoin; removed with
        surviving peers -> this thread exits and the peers own the
        journals; removed as the LAST replica -> queued and parked work
        fails with the typed ``no_healthy_replica`` reason and the loop
        keeps failing new arrivals fast until drain — never a hang."""
        pending: List[DecodeRequest] = []
        active: Dict[int, _Active] = {}
        replica.loop_alive = True
        try:
            self._decode_loop_body(replica, pending, active)
        finally:
            replica.loop_alive = False

    def _decode_loop_body(
        self,
        replica: DecodeReplica,
        pending: List[DecodeRequest],
        active: Dict[int, "_Active"],
    ) -> None:
        while True:
            if replica.state == "removed":
                # mortuary mode: no servable replica remains and the
                # recovery round already failed — fail queued work fast
                # with the machine-readable terminal reason
                group = self.queue.take_group(1, wait=True)
                if group is None:
                    return
                err = NoHealthyReplicaError(
                    "all decode replicas removed after failed recovery"
                )
                for req in group:
                    self._trace_fail(req, err)
                    req.result._deliver(None, error=err)
                continue
            try:
                if self._serve_once(replica, pending, active):
                    return
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — the incident path
                if self._replica_incident(replica, pending, active, e):
                    continue  # recovered; rejoin routing
                with self._health_lock:
                    survivors = survive_lib.live_survivors(
                        self.replicas, replica
                    )
                if survivors:
                    return  # peers own the journals; this thread is done
                logger.critical(
                    "decode: NO healthy replicas remain after the recovery "
                    "round; failing queued work with reason "
                    "no_healthy_replica instead of hanging"
                )
                self._event({
                    "event": "no_healthy_replica",
                    "replica": replica.index,
                })
                if self.flight is not None:
                    # decode dispatch death: the last windows + the
                    # unhealthy/removed events are in the ring
                    self.flight.dump("serving_dispatch")
                continue  # -> mortuary branch
