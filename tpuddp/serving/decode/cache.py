"""Paged KV-cache pool — fixed-size block tables over one device-resident pool.

The decode engine's memory problem is the classic one: sequences have wildly
different lengths and lifetimes, but device arrays must be static-shaped. A
naive per-slot ``(max_slots, max_seq_len)`` cache wastes
``max_seq_len - length`` positions per sequence; the paged answer (vLLM's
PagedAttention, here in plain XLA gathers) carves ONE pool of
``kv_blocks x kv_block_size`` token positions per layer and maps each
sequence onto it through a per-slot block table — allocation is
block-granular, fragmentation is bounded by one block per sequence, and a
finishing sequence returns its blocks to the free list immediately, so a
queued request can join the running batch on the very next step.

Device side: ``kpool``/``vpool`` are ``(layers, kv_blocks, kv_block_size,
heads, head_dim)`` arrays updated functionally by the jitted prefill/step
programs (the engine threads them through and donates the old buffers).
**Block 0 is reserved as the garbage block**: inactive slots and padded
prefill positions redirect their writes there, so every scatter in the
compiled programs is total — no dynamic shapes, no masking branches — and
nothing an active sequence reads is ever aliased to it.

Host side: this class is pure bookkeeping — free-list allocation, per-slot
block tables and lengths (the int32 arrays the step program consumes), and
the occupancy accounting the SLO stats and the /metrics gauge report. It is
single-threaded by design (one decode loop owns one cache); no locks.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class PagedKVCache:
    """Block-table allocator + the host mirrors of the device pool geometry.

    ``num_blocks`` counts the WHOLE pool including reserved garbage block 0,
    so ``num_blocks - 1`` blocks are allocatable — sized so that
    ``max_slots`` concurrent sequences of worst-case length fit, or smaller
    when the operator accepts admission waits under pressure."""

    def __init__(
        self,
        layers: int,
        heads: int,
        head_dim: int,
        num_blocks: int,
        block_size: int,
        max_slots: int,
        max_seq_len: int,
    ):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is reserved), got {num_blocks}"
            )
        if block_size < 1 or max_slots < 1 or max_seq_len < 1:
            raise ValueError(
                f"block_size/max_slots/max_seq_len must be >= 1, got "
                f"{block_size}/{max_slots}/{max_seq_len}"
            )
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len)
        # max blocks any sequence can span — the block-table width, a static
        # shape of the compiled decode step
        self.max_blocks = -(-self.max_seq_len // self.block_size)
        if self.allocatable < self.max_blocks:
            raise ValueError(
                f"kv_blocks={num_blocks} cannot hold even one max-length "
                f"sequence ({self.max_blocks} blocks of {block_size})"
            )
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        # host mirrors the step program consumes every iteration
        self.tables = np.zeros((self.max_slots, self.max_blocks), np.int32)
        self.lengths = np.zeros((self.max_slots,), np.int32)
        self._slot_blocks: List[Optional[List[int]]] = [None] * self.max_slots
        self._free_slots: List[int] = list(range(self.max_slots - 1, -1, -1))

    def pool_shape(self):
        """The device K/V pool shape (one array each for K and V)."""
        return (
            self.layers, self.num_blocks, self.block_size, self.heads,
            self.head_dim,
        )

    # --------------------------------------------------------- accounting --
    @property
    def allocatable(self) -> int:
        return self.num_blocks - 1  # block 0 reserved

    @property
    def used_blocks(self) -> int:
        return self.allocatable - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def free_slots(self) -> int:
        return len(self._free_slots)

    @property
    def active_slots(self) -> int:
        return self.max_slots - len(self._free_slots)

    def occupancy(self) -> float:
        """Allocated fraction of the allocatable pool — the KV-pressure
        gauge (/metrics + decode_stats windows)."""
        return self.used_blocks / self.allocatable

    def blocks_needed(self, total_tokens: int) -> int:
        return -(-int(total_tokens) // self.block_size)

    def can_admit(self, total_tokens: int) -> bool:
        """Whether a sequence of ``total_tokens`` worst-case length (prompt +
        max_new_tokens) can be placed RIGHT NOW: a free slot and enough free
        blocks for its whole lifetime — blocks are reserved up front so a
        running sequence can never hit pool exhaustion mid-decode."""
        return (
            bool(self._free_slots)
            and self.blocks_needed(total_tokens) <= len(self._free)
        )

    # --------------------------------------------------------- allocation --
    def allocate(self, total_tokens: int) -> int:
        """Reserve a slot + its lifetime block budget; returns the slot id.
        The slot starts at length 0 — the prefill commit advances it."""
        if total_tokens < 1 or total_tokens > self.max_seq_len:
            raise ValueError(
                f"sequence of {total_tokens} tokens outside [1, "
                f"{self.max_seq_len}]"
            )
        if not self.can_admit(total_tokens):
            raise RuntimeError(
                f"cannot admit a {total_tokens}-token sequence: "
                f"{self.free_slots} free slots, {self.free_blocks} free "
                f"blocks (need {self.blocks_needed(total_tokens)})"
            )
        slot = self._free_slots.pop()
        blocks = [self._free.pop() for _ in range(self.blocks_needed(total_tokens))]
        self._slot_blocks[slot] = blocks
        row = np.zeros((self.max_blocks,), np.int32)
        row[: len(blocks)] = blocks
        self.tables[slot] = row
        self.lengths[slot] = 0
        return slot

    def free(self, slot: int) -> None:
        """Return a finished sequence's blocks to the pool and its slot to
        the free set — the next step's admission sees the capacity."""
        blocks = self._slot_blocks[slot]
        if blocks is None:
            raise ValueError(f"slot {slot} is not allocated")
        self._free.extend(reversed(blocks))
        self._slot_blocks[slot] = None
        self.tables[slot] = 0
        self.lengths[slot] = 0
        self._free_slots.append(slot)
