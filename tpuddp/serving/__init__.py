"""tpuddp.serving — continuous-batching multi-tenant inference engine.

The ROADMAP's "millions of users" north star needs an inference path, not
just epochs (open item 3). This package serves checkpoints produced by the
training stack on the same mesh the training stack runs on, treating the
local devices as a pool of independently schedulable model replicas (the
MPMD program-partitioning view of PAPERS.md arxiv 2412.14374) instead of one
lockstep program:

- :mod:`queue`     — thread-safe bounded request queue with per-tenant
  quotas, round-robin fairness, and reject-with-reason admission control;
- :mod:`scheduler` — coalesces variable-size requests into padded,
  power-of-two-bucketed device batches (the shared shape-key bucketing and
  staging-budget policy of ``tpuddp/utils/batching.py`` — the same machinery
  whose scan-fused eval measured ~85x the per-batch facade in BENCH_r04/r05
  — so the compile cache stays warm and compile storms are impossible);
- :mod:`replica`   — N independent model replicas across the local devices,
  loaded from a training checkpoint via the existing sha256-verified
  ``restore_latest`` path;
- :mod:`stats`     — SLO metrics (queue/device/end-to-end latency
  percentiles, throughput, batch occupancy, rejects) emitted as typed
  ``serving_stats``/``event`` rows through ``tpuddp/observability``;
- :mod:`engine`    — :class:`ServingEngine`, tying the above together with
  one dispatch loop per replica and a drain path reusing the resilience
  exit-code contract (SIGTERM -> finish in-flight work -> exit 75).

Token traffic has its own sub-package: :mod:`tpuddp.serving.decode` is the
autoregressive engine — paged KV-cache pool, continuous batching at TOKEN
granularity (sequences join/leave the running batch every decode step),
prefill/decode split scheduling, and per-token streaming — over the
transformer family of ``tpuddp/models/transformer.py``.

``python -m tpuddp.serving --settings <yaml>`` stands the engine up from a
settings file's ``serving`` block (``--decode`` for the token engine from
its ``serving.decode`` block); ``tools/loadgen.py`` drives it with
closed/open-loop load and writes latency-vs-throughput curves in the bench
artifact format (``--decode`` for tokens/sec + TTFT curves).
"""

from tpuddp.serving.decode import (  # noqa: F401
    DecodeEngine,
    DecodeRequest,
    DecodeStats,
    PagedKVCache,
    StreamedResult,
)
from tpuddp.serving.engine import ServingEngine  # noqa: F401
from tpuddp.serving.queue import (  # noqa: F401
    AdmissionError,
    Request,
    RequestQueue,
    ServedResult,
)
from tpuddp.serving.replica import Replica, ReplicaPool  # noqa: F401
from tpuddp.serving.scheduler import Batch, BatchScheduler  # noqa: F401
from tpuddp.serving.stats import ServingStats  # noqa: F401
from tpuddp.serving.survive import (  # noqa: F401
    NoHealthyReplicaError,
    RetryBudget,
    SurvivePolicy,
)

__all__ = [
    "AdmissionError",
    "NoHealthyReplicaError",
    "RetryBudget",
    "SurvivePolicy",
    "Batch",
    "BatchScheduler",
    "DecodeEngine",
    "DecodeRequest",
    "DecodeStats",
    "PagedKVCache",
    "StreamedResult",
    "Replica",
    "ReplicaPool",
    "Request",
    "RequestQueue",
    "ServedResult",
    "ServingEngine",
    "ServingStats",
]
