"""tpuddp — a TPU-native distributed data-parallel training framework.

A brand-new JAX/XLA framework with the capabilities of the
`tutorial-torch-distributed-data-parallel` reference, redesigned TPU-first:

- ``tpuddp.parallel``  — distributed runtime: backend ladder (TPU -> CPU -> error,
  mirroring the reference's NCCL -> Gloo -> error ladder at
  multi-GPU-training-torch.py:34-42), device mesh with a named ``"data"`` axis,
  XLA collectives over ICI/DCN, an exact-semantics ``DistributedSampler``, and a
  ``DistributedDataParallel`` wrapper whose gradient averaging is ``lax.pmean``
  inside a ``shard_map``-ped, jitted train step.
- ``tpuddp.nn``        — a functional neural-net layer library (Linear, Conv2d,
  BatchNorm with cross-replica statistic sync = the SyncBatchNorm contract from
  the reference README.md:79-81, pooling, dropout, losses).
- ``tpuddp.optim``     — native optimizers (Adam, SGD) as pure pytree transforms.
- ``tpuddp.models``    — model zoo: toy MLP, toy CNN (+SyncBN), AlexNet-class CNN
  (reference data_and_toy_model.py:41-45), ResNet-18.
- ``tpuddp.data``      — CIFAR-10 pipeline with *device-side* augmentation
  (uint8 32x32 is shipped to HBM; resize/flip/normalize run fused on-chip),
  synthetic datasets for CI.
- ``tpuddp.training``  — jitted DP train/eval steps, the epoch driver
  (reference run_training_loop, multi-GPU-training-torch.py:156-225), and
  checkpoint/resume.
- ``tpuddp.accelerate``— a managed ``Accelerator`` facade (HuggingFace-accelerate
  API shape: prepare/backward/is_local_main_process/wait_for_everyone/save_model)
  routed through the same XLA backend as the explicit API.
"""

__version__ = "0.1.0"

from tpuddp import parallel  # noqa: F401
from tpuddp import seeding  # noqa: F401

__all__ = ["parallel", "seeding", "__version__"]
