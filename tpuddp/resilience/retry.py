"""Retry with jittered exponential backoff — the transient-failure primitive.

Used where the framework touches the world outside its own process and a
one-shot failure is routinely recoverable: ``jax.distributed.initialize``
rendezvous (peers race to come up), the CIFAR-10 download (flaky egress), and
multi-host barrier entry.  Jitter decorrelates the retry storms of N hosts
that all saw the same transient (the classic thundering-herd fix).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger("tpuddp")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``delay(attempt) = min(max_delay, base_delay * 2**(attempt-1))``, then
    multiplied by ``uniform(1 - jitter, 1 + jitter)``. ``retry_on`` bounds
    which exception types count as transient."""

    max_attempts: int = 3
    base_delay: float = 0.5
    max_delay: float = 30.0
    jitter: float = 0.5  # fraction of the delay, in [0, 1]
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay(self, attempt: int, rng: Optional[random.Random] = None) -> float:
        base = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        r = rng.uniform if rng is not None else random.uniform
        return base * r(1.0 - self.jitter, 1.0 + self.jitter)


class RetryError(RuntimeError):
    """All attempts exhausted. ``__cause__`` is the final attempt's exception;
    the message names the operation and attempt count so the terminal error is
    actionable, not just the last traceback."""


def retry(
    fn: Callable,
    policy: Optional[RetryPolicy] = None,
    *,
    describe: str = "operation",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` up to ``policy.max_attempts`` times. Non-``retry_on``
    exceptions (and KeyboardInterrupt/SystemExit, which are never transient)
    propagate immediately; exhaustion raises :class:`RetryError` chaining the
    last failure."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except policy.retry_on as e:
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            last = e
            if attempt == policy.max_attempts:
                break
            d = policy.delay(attempt)
            logger.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.1fs",
                describe,
                attempt,
                policy.max_attempts,
                e,
                d,
            )
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(d)
    raise RetryError(
        f"{describe} failed after {policy.max_attempts} attempt(s): {last}"
    ) from last
