"""Checkpoint integrity — sha256 sidecar manifests.

A preempted or crashed writer can leave a torn file even past the atomic
``os.replace`` (e.g. a node dies mid-flush on a network filesystem, or a
chaos ``corrupt@ckpt_N`` fault fires).  Every checkpoint save publishes a
``<file>.sha256`` sidecar (digest + size, written atomically *after* the data
file); ``checkpoint.latest()`` verifies before trusting a candidate and falls
back to the next-newest instead of crashing the resume path.

Manifest format is the ``sha256sum``-compatible line ``<hex>  <basename>``
with an optional ``# size=<bytes>`` second line, so operators can verify with
coreutils.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Optional

logger = logging.getLogger("tpuddp")

_CHUNK = 1024 * 1024


def manifest_path(path: str) -> str:
    return path + ".sha256"


def _digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(path: str) -> str:
    """Write ``<path>.sha256`` (atomically: tmp + replace). Returns its path."""
    mpath = manifest_path(path)
    digest = _digest(path)
    size = os.path.getsize(path)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{digest}  {os.path.basename(path)}\n# size={size}\n")
    os.replace(tmp, mpath)
    return mpath


def read_manifest(path: str) -> Optional[dict]:
    """Parse ``<path>.sha256`` -> {"digest", "size"}; None if absent/garbled."""
    mpath = manifest_path(path)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            lines = f.read().splitlines()
        digest = lines[0].split()[0]
        size = None
        for line in lines[1:]:
            if line.startswith("# size="):
                size = int(line[len("# size=") :])
        return {"digest": digest, "size": size}
    except (OSError, IndexError, ValueError):
        return None


def verify_file(path: str, require_manifest: bool = False) -> bool:
    """True when ``path`` exists and matches its manifest. Without a manifest
    (pre-resilience checkpoints): a cheap structural check — non-empty and
    zip-magic-prefixed (every .npz is a zip) — unless ``require_manifest``."""
    if not os.path.exists(path):
        return False
    manifest = read_manifest(path)
    if manifest is None:
        if require_manifest:
            return False
        try:
            if os.path.getsize(path) == 0:
                return False
            with open(path, "rb") as f:
                return f.read(2) == b"PK"  # zip local-file-header magic
        except OSError:
            return False
    try:
        if manifest["size"] is not None and os.path.getsize(path) != manifest["size"]:
            logger.warning(
                "integrity: %s size %d != manifest size %d (truncated?)",
                path,
                os.path.getsize(path),
                manifest["size"],
            )
            return False
        if _digest(path) != manifest["digest"]:
            logger.warning("integrity: %s sha256 mismatch vs manifest", path)
            return False
    except OSError as e:
        logger.warning("integrity: cannot verify %s (%s)", path, e)
        return False
    return True
