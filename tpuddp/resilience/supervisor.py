"""Restart supervisor — the exit-code-contract interpreter (ISSUE 7).

The resilience layer speaks in exit codes (resilience/preemption.py, README
"Fault tolerance"): 75 = drained after preemption (requeue + auto-resume),
76 = a peer's heartbeat went stale, 77 = replica desync, 113 = injected
chaos crash. Until now something OUTSIDE the repo (HTCondor, a k8s operator,
an engineer) had to read them. :class:`RestartSupervisor` is that something:
it runs the training command as a child process, interprets the code it
exits with, and restarts it under the right policy —

- ``0``      — done; the supervisor exits 0.
- ``75``     — a clean preemption drain: the emergency checkpoint is on
  disk, so resume IMMEDIATELY (``$TPUDDP_AUTO_RESUME=1``, no backoff — the
  scheduler already paid the drain latency).
- ``76``/``77`` and anything else non-zero — restart with **jittered
  exponential backoff** (the resilience/retry.py discipline: decorrelate N
  supervisors stampeding a shared rendezvous) and auto-resume from the
  newest intact checkpoint.
- **negative codes** — the child was killed by a signal (subprocess reports
  signal N as ``-N``: an OOM SIGKILL, a node reclaim, the fleet
  controller's drain escalation). Classified as backoff-restartable with
  the signal NAMED in the log line (:func:`classify_exit`) — never as a
  peer-death streak, so a SIGKILLed child cannot shrink the world.
- repeated ``76`` (peer death keeps recurring — the pod genuinely lost
  capacity, it is not a transart): **degrade gracefully** instead of dying —
  shrink the world size by ``shrink_factor`` (``$TPUDDP_WORLD_SIZE``, which
  both entrypoints honor via ``config.world_size_from``) and resume through
  the elastic v2 restore path (training/checkpoint.py reshards the
  checkpoint onto the smaller world).

Mesh-aware failover (ISSUE 16): ``model_size`` pins the child's
tensor-parallel width (``$TPUDDP_MODEL_SIZE``, honored by
``config.resolve_parallel`` the way ``$TPUDDP_WORLD_SIZE`` is by
``world_size_from``). The shrink then picks the next FEASIBLE smaller mesh
from the surviving devices: the DATA axis halves first (model shards keep
the geometry that was validated for their width, and data-axis checkpoint
resharding is the sum-preserving direction); only at data=1 does the MODEL
axis shrink (when the factor divides it). The relaunched child derives
``data = world / model`` and — with ``training.reshard_on_mismatch`` on —
reshards the checkpoint onto the smaller mesh (training/reshard.py) instead
of dying on the typed TopologyMismatch.

Every restart is bounded by ``max_restarts``; exhaustion returns the child's
last exit code so the wrapping scheduler still sees the truth.

``runner`` is injectable (tests drive the policy with a fake child);
``first_attempt_env`` applies extra env ONLY to attempt 0 and is stripped
from every restart — the chaos suite injects its ``$TPUDDP_FAULT`` there so
the fault cannot re-fire in the resumed process.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal as signal_lib
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpuddp.resilience.preemption import (
    EXIT_DESYNC,
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
)

logger = logging.getLogger("tpuddp")

WORLD_ENV = "TPUDDP_WORLD_SIZE"
MODEL_ENV = "TPUDDP_MODEL_SIZE"
_AUTO_RESUME_ENV = "TPUDDP_AUTO_RESUME"
_SPAWNED_ENV = "TPUDDP_SPAWNED"


def classify_exit(rc: int) -> str:
    """Human label for a child exit code, incl. signal deaths: subprocess
    reports a child killed by signal N as rc == -N (an OOM SIGKILL, a
    scheduler's hard stop, the fleet controller's drain escalation). A
    signal death is a crash-shaped restartable failure — never a peer-death
    (76) streak — and the label names the signal so the log line says
    'killed by SIGKILL', not 'exited -9'."""
    if rc < 0:
        try:
            name = signal_lib.Signals(-rc).name
        except ValueError:
            name = f"signal {-rc}"
        return f"killed by {name}"
    return {
        EXIT_PREEMPTED: "preemption drain",
        EXIT_WATCHDOG: "stale peer",
        EXIT_DESYNC: "replica desync",
    }.get(rc, "crash")


@dataclasses.dataclass(frozen=True)
class SupervisorPolicy:
    """Restart policy knobs (tools/supervise.py exposes them as flags).

    ``shrink_after`` consecutive watchdog deaths (exit 76) shrink the world
    by ``shrink_factor`` — but never below ``min_world``; once unshrinkable,
    peer deaths fall back to plain bounded restarts. ``backoff_base``/
    ``backoff_cap``/``jitter`` follow the retry.py delay shape."""

    max_restarts: int = 8
    backoff_base: float = 1.0
    backoff_cap: float = 60.0
    jitter: float = 0.5
    shrink_after: int = 2
    shrink_factor: int = 2
    min_world: int = 1

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {self.max_restarts}")
        if self.shrink_factor < 2:
            raise ValueError(f"shrink_factor must be >= 2, got {self.shrink_factor}")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay(self, consecutive_failures: int, rng: random.Random) -> float:
        base = min(
            self.backoff_cap,
            self.backoff_base * (2.0 ** max(0, consecutive_failures - 1)),
        )
        return base * rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)


class RestartSupervisor:
    """Supervise one training command through the exit-code contract.

    ``world_size=None`` leaves the child's own world-size resolution alone
    (no elastic shrink possible — the supervisor cannot shrink a world it
    does not control); an int pins ``$TPUDDP_WORLD_SIZE`` and arms the
    shrink policy."""

    def __init__(
        self,
        argv: Sequence[str],
        policy: Optional[SupervisorPolicy] = None,
        world_size: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        first_attempt_env: Optional[Dict[str, str]] = None,
        auto_resume_first: bool = False,
        runner: Optional[Callable[[Sequence[str], Dict[str, str]], int]] = None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        flight_dir: Optional[str] = None,
        world_env_var: str = WORLD_ENV,
        model_size: Optional[int] = None,
    ):
        """``flight_dir``: where the supervised run dumps its crash flight
        recordings (``flightrec_<reason>.json`` — usually the run's
        out_dir). When set, the supervisor summarizes the newest recording
        at startup (a previous run's post-mortem) and after every abnormal
        child exit, BEFORE deciding restart/shrink — the operator sees what
        the child was doing when it died, not just the exit code.

        ``world_env_var``: which env var carries the world size to the
        child. Training jobs use the default ``$TPUDDP_WORLD_SIZE``;
        serving jobs under the fleet controller use
        ``$TPUDDP_SERVING_REPLICAS`` (config.serving_config honors it), so
        ONE drain -> resume contract resizes both kinds.

        ``model_size``: the child's tensor-parallel width, pinned via
        ``$TPUDDP_MODEL_SIZE`` on every attempt. Arms the MESH-aware shrink:
        data axis first, model axis only at data=1 (module doc). None =
        the supervisor treats the world as pure-DP (today's behavior)."""
        self.argv = list(argv)
        self.policy = policy or SupervisorPolicy()
        self.world_size = int(world_size) if world_size else None
        self.model_size = int(model_size) if model_size else None
        if (
            self.model_size
            and self.world_size
            and self.world_size % self.model_size
        ):
            raise ValueError(
                f"world_size {self.world_size} is not a multiple of "
                f"model_size {self.model_size}: no (data, model) mesh exists"
            )
        self.env = dict(env or {})
        self.first_attempt_env = dict(first_attempt_env or {})
        self.auto_resume_first = bool(auto_resume_first)
        self.runner = runner or self._popen_runner
        self.sleep = sleep
        self._rng = rng or random.Random()
        self.flight_dir = flight_dir
        self.world_env_var = world_env_var
        self._summarized: set = set()  # (path, mtime) pairs already logged
        # (attempt_index, exit_code, world_size) per child run — the
        # supervisor's own post-mortem trail (tests assert against it)
        self.history: List[Tuple[int, int, Optional[int]]] = []
        # the live child (default popen runner only) — the fleet controller
        # signals it to drain (SIGTERM) or escalate (SIGKILL after grace)
        self.child: Optional[subprocess.Popen] = None
        self._current_world: Optional[int] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- fleet API --
    def _popen_runner(self, argv: Sequence[str], env: Dict[str, str]) -> int:
        """Default runner: like ``subprocess.call`` but keeps the live Popen
        on ``self.child`` so an external controller can deliver signals.
        Like ``call``, an exception while waiting (KeyboardInterrupt on the
        supervising terminal) kills the child before propagating — a
        supervisor dying must not orphan a trainer that keeps the run dir,
        heartbeats, and exporter port."""
        proc = subprocess.Popen(list(argv), env=env)
        self.child = proc
        try:
            return proc.wait()
        except BaseException:
            try:
                proc.kill()
            except OSError:
                pass
            proc.wait()
            raise
        finally:
            self.child = None

    @property
    def current_world(self) -> Optional[int]:
        """The world the LIVE (or most recent) child was launched at — what
        it actually holds on the pool, as opposed to ``world_size`` (the
        target of the NEXT attempt, which ``set_world`` may have already
        retargeted mid-drain). The fleet controller gates new starts on the
        sum of these so a drain window cannot oversubscribe the pool."""
        return self._current_world

    def set_world(self, world_size: Optional[int]) -> None:
        """Retarget the NEXT attempt's world (the fleet rebalance lever):
        the controller sets the new world, then SIGTERMs the live child —
        its exit-75 drain makes the supervisor relaunch immediately with
        the updated ``world_env_var``, resuming through the elastic path."""
        self.world_size = int(world_size) if world_size else None

    def request_stop(self) -> None:
        """Stop supervising after the CURRENT child exits (no restart).
        Set this BEFORE signalling the child, or the supervisor may win the
        race and relaunch a job the fleet controller just preempted."""
        self._stop.set()

    @property
    def stop_requested(self) -> bool:
        return self._stop.is_set()

    def signal_child(self, sig: int) -> bool:
        """Deliver ``sig`` to the live child; False when no child is
        running (e.g. the supervisor is between attempts in backoff)."""
        child = self.child
        if child is None or child.poll() is not None:
            return False
        try:
            child.send_signal(sig)
            return True
        except (ProcessLookupError, OSError):
            return False

    # ------------------------------------------------------------------ env --
    def _child_env(
        self, attempt: int, world: Optional[int] = None
    ) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.env)
        # the child must be free to re-exec for ITS world size (a shrunk
        # world needs a different virtual-device count on the CPU rung)
        env.pop(_SPAWNED_ENV, None)
        if attempt == 0:
            env.update(self.first_attempt_env)
            if self.auto_resume_first:
                env[_AUTO_RESUME_ENV] = "1"
        else:
            # a restart is ALWAYS a resume — and never re-fires the first
            # attempt's injected chaos
            for k in self.first_attempt_env:
                env.pop(k, None)
            env[_AUTO_RESUME_ENV] = "1"
        world = self.world_size if world is None else world
        if world:
            env[self.world_env_var] = str(world)
        if self.model_size:
            # pin the tensor-parallel width; the child derives
            # data = world // model (config.resolve_parallel honors this)
            env[MODEL_ENV] = str(self.model_size)
        return env

    # ----------------------------------------------------------- shrink --
    def _shrunk_mesh(self) -> Optional[tuple]:
        """The next-smaller feasible ``(world, model)`` mesh after sustained
        capacity loss, or None when no shrink is possible.

        Data axis shrinks first (replicas are interchangeable; a data
        shrink is the cheap reshard — model shards keep their width). Only
        at data=1 does the model axis shrink, and only when shrink_factor
        divides it; the reshaper re-splits the model-axis leaves on
        restore. ``min_world`` floors the TOTAL chip count either way."""
        f = self.policy.shrink_factor
        floor = max(1, self.policy.min_world)
        world = self.world_size
        if not world:
            return None
        model = self.model_size or 1
        if model <= 1:
            new_world = world // f
            return (new_world, None) if new_world >= floor else None
        data = world // model
        if data // f >= 1 and (data // f) * model >= floor:
            return ((data // f) * model, model)
        if data == 1 and model % f == 0 and model // f >= floor:
            return (model // f, model // f)
        return None

    # ---------------------------------------------------------- flight --
    def summarize_flight(self) -> int:
        """Log the crash flight recordings in ``flight_dir`` not yet
        summarized (newest first); returns how many were. Best-effort: a
        missing dir or corrupt recording logs and moves on — the restart
        decision never blocks on the post-mortem."""
        if self.flight_dir is None:
            return 0
        from tpuddp.observability import flight as flight_lib

        summarized = 0
        for path in flight_lib.find_recordings(self.flight_dir):
            try:
                key = (path, os.path.getmtime(path))
            except OSError:
                continue
            if key in self._summarized:
                continue
            self._summarized.add(key)
            summarized += 1
            for line in flight_lib.summarize_recording(path):
                logger.warning("supervisor: %s", line)
        return summarized

    # ------------------------------------------------------------------ run --
    def run(self) -> int:
        restarts = 0
        consecutive_failures = 0  # backoff exponent (resets on 75)
        consecutive_peer_deaths = 0  # shrink trigger (exit-76 streak)
        attempt = 0
        # a previous (unsupervised) run may have left its post-mortem here —
        # surface it before the first attempt
        self.summarize_flight()
        while True:
            if self._stop.is_set():
                # stopped before this attempt launched — incl. a preemption
                # that lands before the FIRST child ever spawns: preempted
                # work must not run even once
                return self.history[-1][1] if self.history else 0
            # snapshot the launched world BEFORE running: set_world may
            # retarget world_size mid-drain, and both current_world and the
            # history row must name what this child actually held
            launched = self.world_size
            self._current_world = launched
            rc = self.runner(self.argv, self._child_env(attempt, launched))
            self.history.append((attempt, rc, launched))
            attempt += 1
            if rc == 0:
                logger.info("supervisor: child finished cleanly")
                return 0
            # the child died abnormally: read its flight recording(s) before
            # deciding how (and at what world size) to restart
            self.summarize_flight()
            if self._stop.is_set():
                # the controller preempted/stopped this job: the drain (or
                # its escalation) ended the child; surface the code, never
                # relaunch preempted work
                logger.warning(
                    "supervisor: stop requested; child exited %d (%s), not "
                    "restarting", rc, classify_exit(rc),
                )
                return rc
            restarts += 1
            if restarts > self.policy.max_restarts:
                logger.critical(
                    "supervisor: restart budget (%d) exhausted; surfacing the "
                    "child's exit code %d",
                    self.policy.max_restarts, rc,
                )
                return rc
            if rc == EXIT_PREEMPTED:
                # clean drain: the emergency checkpoint exists; resume now
                consecutive_failures = 0
                consecutive_peer_deaths = 0
                logger.warning(
                    "supervisor: child drained after preemption (exit %d); "
                    "resuming immediately (restart %d/%d)",
                    rc, restarts, self.policy.max_restarts,
                )
                continue
            consecutive_failures += 1
            if rc == EXIT_WATCHDOG:
                consecutive_peer_deaths += 1
                shrunk = (
                    self._shrunk_mesh()
                    if consecutive_peer_deaths >= self.policy.shrink_after
                    else None
                )
                if shrunk is not None:
                    new_world, new_model = shrunk
                    logger.critical(
                        "supervisor: %d consecutive peer deaths (exit %d) — "
                        "the pod lost capacity, not a transient. Shrinking "
                        "mesh %d (model=%s) -> %d (model=%s) and resuming "
                        "through the elastic restore path.",
                        consecutive_peer_deaths, rc,
                        self.world_size, self.model_size or 1,
                        new_world, (new_model or self.model_size or 1),
                    )
                    self.world_size = new_world
                    if new_model is not None:
                        self.model_size = new_model
                    consecutive_peer_deaths = 0
                    consecutive_failures = 0
                    continue
            else:
                consecutive_peer_deaths = 0
            delay = self.policy.delay(consecutive_failures, self._rng)
            logger.warning(
                "supervisor: child exited %d (%s); restart %d/%d with "
                "auto-resume in %.1fs",
                rc, classify_exit(rc),
                restarts, self.policy.max_restarts, delay,
            )
            self.sleep(delay)


def supervise(argv: Sequence[str], **kwargs) -> int:
    """One-call form: ``supervise(cmd, world_size=8, ...) -> exit code``."""
    return RestartSupervisor(argv, **kwargs).run()
