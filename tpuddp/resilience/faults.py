"""Fault injection — ``$TPUDDP_FAULT`` chaos hooks.

The chaos suite (tests/test_chaos.py) needs to place a failure at an exact
point in a *subprocess* training run; env-driven injection is the only channel
that crosses the process boundary without patching code.  Grammar::

    TPUDDP_FAULT=<kind>@<site>[,<kind>@<site>...]

    kinds:  crash    os._exit(EXIT_INJECTED_CRASH) — the unclean kill
            preempt  SIGTERM to self — drives the real drain path
            hang     stop heartbeating and sleep forever — the dead peer
            corrupt  garbage the just-written checkpoint file
            nan      poison one train micro-batch so its loss/gradient go
                     non-finite — exercises the numerical-guard firewall
                     (resilience/guard.py) end to end
            replica_kill    [serving] mark the dispatching replica
                     persistently dead (every dispatch raises until its
                     probation rebuild) — the in-process SIGKILL analog
                     that drives decode-session failover + recovery
            pool_poison     [serving/decode] delete the replica's donated
                     K/V pool buffers mid-sweep and fail the dispatch —
                     the donated-buffer death real accelerators produce
            dispatch_wedge  [serving] fail exactly one dispatch
                     transiently — the retry-budget / single-incident
                     exercise (the next dispatch succeeds)

    sites:  epoch=N  checked by the epoch driver at the start of epoch N
            barrier  checked on entry to collectives.barrier
            ckpt_N   checked after checkpoint ``ckpt_N.npz`` is published
            step=N   checked per train micro-batch (global index from run
                     start): ``nan`` poisons that batch (the batch-level
                     injection point); ``crash``/``preempt`` kill the run
                     MID-epoch — the elastic-resume resize scenarios
                     (tests/test_chaos.py), where the drain's emergency
                     checkpoint carries mid-epoch state and the resumed run
                     (possibly on a different world size) redoes the epoch.
                     The DECODE engine checks the same site per decode step
                     (its own global step counter) for the serving kinds —
                     ``replica_kill@step=N`` / ``pool_poison@step=N`` /
                     ``dispatch_wedge@step=N`` land mid-token-sweep
            batch=N  checked by the request-granularity serving engine per
                     dispatched batch (engine-global index): accepts the
                     serving kinds (``replica_kill@batch=N``,
                     ``dispatch_wedge@batch=N``)

Examples: ``crash@epoch=2``, ``preempt@epoch=1``, ``hang@barrier``,
``corrupt@ckpt_1``, ``nan@step=5``, ``preempt@step=12``,
``replica_kill@batch=3``, ``pool_poison@step=40``.  Each spec fires at
most once per
process.  Parsing is lazy and cached; :func:`reload_faults` re-reads the env
(test isolation).  Production runs without the env variable pay one cached
dict lookup per hook.  Training hooks (:func:`maybe_fire`) never consume
the serving kinds and the serving hook (:func:`maybe_serving_fault`) never
consumes the training kinds, so one env spec can target either plane
unambiguously.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import List, Optional

from tpuddp.resilience.preemption import EXIT_INJECTED_CRASH

logger = logging.getLogger("tpuddp")

_FAULT_ENV = "TPUDDP_FAULT"
# serving-side kinds (tpuddp/serving/): consumed ONLY by
# maybe_serving_fault — the training hooks skip them entirely
SERVING_KINDS = ("replica_kill", "pool_poison", "dispatch_wedge")
_KINDS = ("crash", "preempt", "hang", "corrupt", "nan") + SERVING_KINDS

_cache = {"raw": None, "specs": None}
_hung = {"active": False}


@dataclasses.dataclass
class FaultSpec:
    kind: str  # one of _KINDS
    site: str  # "epoch" | "barrier" | "ckpt"
    arg: Optional[str]  # epoch number / checkpoint stem, None for barrier
    fired: bool = False

    def matches(self, site: str, **ctx) -> bool:
        if self.fired or site != self.site:
            return False
        if self.site == "epoch":
            return str(ctx.get("epoch")) == self.arg
        if self.site == "ckpt":
            return ctx.get("name") == self.arg
        if self.site == "step":
            return str(ctx.get("step")) == self.arg
        if self.site == "batch":
            return str(ctx.get("batch")) == self.arg
        return True  # barrier (and other argless sites)


def parse_fault_specs(raw: str) -> List[FaultSpec]:
    specs = []
    for part in filter(None, (p.strip() for p in raw.split(","))):
        try:
            kind, point = part.split("@", 1)
        except ValueError:
            raise ValueError(
                f"bad {_FAULT_ENV} spec {part!r}: expected <kind>@<site>"
            ) from None
        if kind not in _KINDS:
            raise ValueError(
                f"bad {_FAULT_ENV} kind {kind!r}; one of {_KINDS}"
            )
        if point.startswith("epoch="):
            specs.append(FaultSpec(kind, "epoch", point[len("epoch=") :]))
        elif point == "barrier":
            specs.append(FaultSpec(kind, "barrier", None))
        elif point.startswith("ckpt"):
            specs.append(FaultSpec(kind, "ckpt", point))
        elif point.startswith("step="):
            specs.append(FaultSpec(kind, "step", point[len("step=") :]))
        elif point.startswith("batch="):
            specs.append(FaultSpec(kind, "batch", point[len("batch=") :]))
        else:
            raise ValueError(
                f"bad {_FAULT_ENV} site {point!r}; expected epoch=N, barrier, "
                "ckpt_N, step=N, or batch=N"
            )
        # kind/site pairing: nan only makes sense at the batch-level step
        # site; the step site accepts nan (batch poisoning), the
        # process-killing kinds crash/preempt (mid-epoch kills for the
        # elastic chaos matrix), and the serving kinds (the decode engine
        # checks step=N per decode step). batch=N is the request-serving
        # dispatch site and takes serving kinds only (pool_poison needs a
        # KV pool, so it stays on the decode step site). Anything else at
        # these sites would be a typo — refuse it loudly.
        spec = specs[-1]
        if spec.kind == "nan" and spec.site != "step":
            raise ValueError(
                f"bad {_FAULT_ENV} spec {part!r}: kind 'nan' pairs with site "
                "step=N"
            )
        step_kinds = ("nan", "crash", "preempt") + SERVING_KINDS
        if spec.site == "step" and spec.kind not in step_kinds:
            raise ValueError(
                f"bad {_FAULT_ENV} spec {part!r}: site step=N accepts kinds "
                f"{step_kinds}"
            )
        batch_kinds = ("replica_kill", "dispatch_wedge")
        if spec.site == "batch" and spec.kind not in batch_kinds:
            raise ValueError(
                f"bad {_FAULT_ENV} spec {part!r}: site batch=N accepts kinds "
                f"{batch_kinds}"
            )
        if spec.kind in SERVING_KINDS and spec.site not in ("step", "batch"):
            raise ValueError(
                f"bad {_FAULT_ENV} spec {part!r}: serving kind "
                f"{spec.kind!r} pairs with the dispatch sites step=N/batch=N"
            )
    return specs


def active_faults() -> List[FaultSpec]:
    raw = os.environ.get(_FAULT_ENV, "")
    if raw != _cache["raw"]:
        _cache["raw"] = raw
        _cache["specs"] = parse_fault_specs(raw) if raw else []
    return _cache["specs"]


def reload_faults() -> None:
    _cache.update(raw=None, specs=None)
    _hung["active"] = False


def is_hung() -> bool:
    """True once a ``hang`` fault fired — the heartbeat thread checks this and
    stops beating, so the hang is visible to peer watchdogs as a dead process
    would be."""
    return _hung["active"]


def has_nan_fault() -> bool:
    """True while an un-fired ``nan@step=N`` spec is armed — the epoch driver
    wires the per-batch poison hook only then, so fault-free runs pay
    nothing per batch."""
    return any(
        s.kind == "nan" and not s.fired for s in active_faults()
    )


def has_step_fault() -> bool:
    """True while ANY un-fired TRAINING step-site spec is armed (nan poison
    or a mid-epoch crash/preempt kill) — the epoch driver wires its
    per-batch injection hook only then. Serving kinds at step=N belong to
    the decode engine's hook, not the trainer's."""
    return any(
        s.site == "step" and not s.fired and s.kind not in SERVING_KINDS
        for s in active_faults()
    )


def maybe_serving_fault(site: str, **ctx) -> Optional[str]:
    """The serving engines' injection hook: returns the serving fault kind
    that fired at this site (``replica_kill`` / ``pool_poison`` /
    ``dispatch_wedge``) or None. Only serving kinds are considered — a
    training spec sharing the env never gets consumed here — and each spec
    fires at most once, like every other fault. The engine interprets the
    kind (mark the replica broken / delete its pools / raise once); this
    function only decides and logs."""
    for spec in active_faults():
        if spec.kind not in SERVING_KINDS:
            continue
        if not spec.matches(site, **ctx):
            continue
        spec.fired = True
        logger.critical(
            "fault injection: %s@%s fired (ctx=%s)", spec.kind, site, ctx
        )
        return spec.kind
    return None


def maybe_corrupt_batch(batch, step: int):
    """The ``nan@step=N`` injection point: poison one element of the host
    micro-batch whose global train-step index matches, so its loss and
    gradient go non-finite inside the compiled step — the failure the
    numerical-guard firewall must turn into a bitwise no-op. Floating inputs
    take the NaN in ``x``; integer/uint8 inputs fall back to a NaN sample
    weight (same non-finite loss/grad, different carrier). Fires once."""
    import numpy as np

    for spec in active_faults():
        if spec.kind == "nan" and spec.matches("step", step=step):
            spec.fired = True
            x, y, w = batch
            x = np.array(x, copy=True)
            if np.issubdtype(x.dtype, np.floating):
                x.flat[0] = np.nan
            else:
                w = np.array(w, copy=True)
                w.flat[0] = np.nan
            logger.critical(
                "fault injection: nan@step=%d fired (poisoned one train "
                "micro-batch)", step,
            )
            return x, y, w
    return batch


def _corrupt_file(path: str) -> None:
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(0)
        f.write(b"\x00CHAOS\x00" * 4)
        f.truncate(max(32, size // 2))  # torn write: header garbage + tail gone


def maybe_fire(site: str, **ctx) -> None:
    """Injection hook. No-op unless an un-fired ``$TPUDDP_FAULT`` spec matches
    ``site`` (+``ctx``); called from the epoch driver, barrier entry, and the
    checkpoint writer."""
    for spec in active_faults():
        if spec.kind == "nan":
            continue  # batch poisoning is maybe_corrupt_batch's job — firing
            # it here would mark the spec consumed without poisoning anything
        if spec.kind in SERVING_KINDS:
            continue  # the serving engines' hook (maybe_serving_fault) owns
            # these — firing one here would consume it without injecting
        if not spec.matches(site, **ctx):
            continue
        spec.fired = True
        logger.critical("fault injection: %s@%s fired (ctx=%s)", spec.kind, site, ctx)
        if spec.kind == "crash":
            os._exit(EXIT_INJECTED_CRASH)
        elif spec.kind == "preempt":
            os.kill(os.getpid(), signal.SIGTERM)
        elif spec.kind == "hang":
            _hung["active"] = True
            while True:  # a peer's watchdog (or the test harness) must kill us
                time.sleep(1.0)
        elif spec.kind == "corrupt":
            path = ctx.get("path")
            if path and os.path.exists(path):
                _corrupt_file(path)
