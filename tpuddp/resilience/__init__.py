"""Resilience — the fault-tolerance layer the reference (and its HTCondor
habitat) needs but never builds (ISSUE 1; PAPER.md §1).

Under HTCondor — and on preemptible TPU pods — interruption is the *normal*
failure mode, yet the reference's only recovery story is "rank 0 saves every N
epochs".  This package makes survivable interruption a first-class subsystem,
the way MLPerf-scale DDP work treats it (arxiv 1909.09756, 2509.07003):

- ``preemption``  — SIGTERM/SIGINT -> flag -> emergency checkpoint -> exit 75
                    (``$TPUDDP_PREEMPT_GRACE`` bounds the drain window); the
                    epoch driver polls the flag at batch-group boundaries and
                    ``run_training_loop(auto_resume=True)`` continues from the
                    recorded epoch on restart.
- ``integrity``   — sha256 sidecar manifests for checkpoints; ``latest()``
                    verifies and *skips* corrupt/truncated files instead of
                    crashing on them, and ``keep_last`` pruning bounds disk.
- ``retry``       — jittered-exponential-backoff ``retry(fn, policy)`` used by
                    backend init, the CIFAR-10 download, and barrier entry.
- ``faults``      — ``$TPUDDP_FAULT`` chaos-injection hooks (``crash@epoch=2``,
                    ``preempt@epoch=1``, ``hang@barrier``, ``corrupt@ckpt_1``)
                    that the chaos test suite drives via subprocess kills.
- ``watchdog``    — heartbeat files + a stale-peer watchdog thread for the
                    multi-host path (``$TPUDDP_WATCHDOG_TIMEOUT``), so a dead
                    peer surfaces as a logged exit instead of a silent hang in
                    a collective.
- ``guard``       — the numerical layer (ISSUE 3): the in-step non-finite
                    gradient firewall (``training.guard``), the cross-replica
                    desync auditor (``pmax - pmin`` fingerprints ->
                    exit 77 / rollback), and the skip counters the epoch
                    driver's rollback-to-last-good policy watches.
- ``supervisor``  — the restart supervisor (ISSUE 7): runs the training
                    command as a child, interprets the exit-code contract
                    (75 -> resume now, 76/77/crash -> bounded
                    jittered-backoff restart), and on repeated peer death
                    shrinks ``$TPUDDP_WORLD_SIZE`` and resumes through the
                    elastic v2 checkpoint restore instead of dying.
                    CLI: ``tools/supervise.py``.
"""

from tpuddp.resilience.preemption import (  # noqa: F401
    EXIT_INJECTED_CRASH,
    auto_resume_requested,
    EXIT_PREEMPTED,
    EXIT_WATCHDOG,
    EXIT_DESYNC,
    TrainingPreempted,
    install_preemption_handler,
    preemption_grace_seconds,
    preemption_requested,
    request_preemption,
    reset_preemption,
    uninstall_preemption_handler,
)
from tpuddp.resilience.retry import RetryError, RetryPolicy, retry  # noqa: F401
from tpuddp.resilience.faults import (  # noqa: F401
    FaultSpec,
    active_faults,
    maybe_fire,
    parse_fault_specs,
    reload_faults,
)
from tpuddp.resilience.watchdog import (  # noqa: F401
    Heartbeat,
    Watchdog,
    WatchdogTimeout,
    watchdog_timeout_seconds,
)
from tpuddp.resilience.integrity import (  # noqa: F401
    manifest_path,
    verify_file,
    write_manifest,
)
from tpuddp.resilience.guard import (  # noqa: F401
    DISABLED as GUARD_DISABLED,
    GuardConfig,
    ReplicaDesync,
    audit_or_raise,
    audit_params,
    resolve_guard,
)
from tpuddp.resilience.supervisor import (  # noqa: F401
    RestartSupervisor,
    SupervisorPolicy,
    supervise,
)

__all__ = [
    "EXIT_INJECTED_CRASH",
    "auto_resume_requested",
    "EXIT_PREEMPTED",
    "EXIT_WATCHDOG",
    "EXIT_DESYNC",
    "TrainingPreempted",
    "install_preemption_handler",
    "preemption_grace_seconds",
    "preemption_requested",
    "request_preemption",
    "reset_preemption",
    "uninstall_preemption_handler",
    "RetryError",
    "RetryPolicy",
    "retry",
    "FaultSpec",
    "active_faults",
    "maybe_fire",
    "parse_fault_specs",
    "reload_faults",
    "Heartbeat",
    "Watchdog",
    "WatchdogTimeout",
    "watchdog_timeout_seconds",
    "manifest_path",
    "verify_file",
    "write_manifest",
    "GUARD_DISABLED",
    "GuardConfig",
    "ReplicaDesync",
    "audit_or_raise",
    "audit_params",
    "resolve_guard",
    "RestartSupervisor",
    "SupervisorPolicy",
    "supervise",
]
