"""Multi-host watchdog — heartbeat files + stale-peer detection.

The failure the reference cannot even see: one host of a pod dies (preempted,
OOM-killed, network-partitioned) and every other host blocks *forever* inside
the next collective — no error, no log, the job just stops consuming epochs.
jax's own collectives have no per-op timeout on the DCN path, so detection has
to live beside them:

- each process runs a :class:`Heartbeat` thread that rewrites
  ``<dir>/hb_<process_id>`` (atomic tmp+replace, wall-clock content — mtime is
  unreliable on NFS) every ``interval`` seconds;
- a :class:`Watchdog` thread checks the peers' files and, when one goes stale
  past ``$TPUDDP_WATCHDOG_TIMEOUT`` seconds, logs which peer died and how
  stale it is, then acts: ``action="exit"`` (default) leaves with
  ``EXIT_WATCHDOG`` (76) so the scheduler can requeue + auto-resume the whole
  job, ``action="raise"`` interrupts the main thread, a callable gets the
  stale list.

The heartbeat dir defaults to ``<save_dir>/.heartbeats`` (the checkpoint dir
is already the shared-filesystem rendezvous point on pods);
``$TPUDDP_HEARTBEAT_DIR`` overrides.  A ``hang`` fault (faults.is_hung) stops
the beat without stopping the process — the injected hang is indistinguishable
from a dead peer, which is the point of the chaos test.

The heartbeat file doubles as the **telemetry shard channel** (ISSUE 10,
tpuddp/observability/aggregate.py): line 1 stays the wall-clock timestamp
(the liveness contract, unchanged), and an optional line 2 carries one JSON
object — the host's last-window step-time/stall/skip shard. Writers pass
``payload=`` (or register :func:`set_heartbeat_payload` so the beat thread
carries the freshest shard on every rewrite); readers use
:func:`read_heartbeat_payload`, which skips a torn mid-write line with a
warning instead of ever crashing the aggregator. ``read_heartbeat`` parses
line 1 only, so liveness checks are indifferent to the payload.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Callable, List, Optional, Tuple, Union

from tpuddp.resilience import faults
from tpuddp.resilience.preemption import EXIT_WATCHDOG

logger = logging.getLogger("tpuddp")

_TIMEOUT_ENV = "TPUDDP_WATCHDOG_TIMEOUT"
_DIR_ENV = "TPUDDP_HEARTBEAT_DIR"


class WatchdogTimeout(RuntimeError):
    """A peer's heartbeat went stale past the configured timeout."""


def watchdog_timeout_seconds() -> Optional[float]:
    """$TPUDDP_WATCHDOG_TIMEOUT in seconds; None/invalid/<=0 disables."""
    raw = os.environ.get(_TIMEOUT_ENV, "")
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", _TIMEOUT_ENV, raw)
        return None
    return t if t > 0 else None


def heartbeat_dir(save_dir: Optional[str]) -> Optional[str]:
    env = os.environ.get(_DIR_ENV)
    if env:
        return env
    if save_dir:
        return os.path.join(save_dir, ".heartbeats")
    return None


def _hb_path(directory: str, process_id: int) -> str:
    return os.path.join(directory, f"hb_{process_id}")


_HB_RE = re.compile(r"^hb_(\d+)$")


def purge_stale_peers(directory: str, num_processes: int) -> int:
    """Remove heartbeat files whose ``process_id >= num_processes`` — the
    droppings of a previous LARGER world in the same ``heartbeat_dir``.

    An elastically-shrunk resume (ISSUE 7) reuses the save_dir, and with it
    ``<save_dir>/.heartbeats``: the old world's extra ``hb_{i}`` files are
    forever-stale by definition, and any watchdog that trusted them would
    kill the healthy smaller run with exit 76. Best-effort (a peer may purge
    the same file concurrently); returns the number removed.

    Scope contract (ISSUE 10): ONLY ids past the current world are removed.
    ``hb_{i < num_processes}`` files — including the telemetry shard payload
    on their second line — belong to live peers of THIS world and must
    survive the purge: a blanket clean-slate delete here would race a peer's
    first shard publish and silently blind the pod aggregator on every
    elastic resume."""
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    removed = 0
    for name in names:
        m = _HB_RE.match(name)
        if m and int(m.group(1)) >= num_processes:
            try:
                os.remove(os.path.join(directory, name))
                removed += 1
            except OSError:
                pass  # a peer got there first, or the FS hiccuped
    if removed:
        logger.info(
            "watchdog: purged %d stale heartbeat file(s) from a previous "
            "larger world in %s (current world: %d processes)",
            removed, directory, num_processes,
        )
    return removed


def write_heartbeat(
    directory: str,
    process_id: int,
    now: Optional[float] = None,
    payload: Optional[dict] = None,
) -> str:
    """Atomically rewrite this process's liveness file: timestamp line plus,
    when given, one JSON telemetry-shard line (the aggregation channel).
    The tmp+replace means a reader sees the old whole file or the new whole
    file — a *torn* payload can only come from a non-atomic filesystem, and
    the payload reader tolerates that too."""
    path = _hb_path(directory, process_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{time.time() if now is None else now:.6f}\n")
        if payload is not None:
            f.write(json.dumps(payload, allow_nan=False) + "\n")
    os.replace(tmp, path)
    return path


def read_heartbeat(directory: str, process_id: int) -> Optional[float]:
    """The peer's last beat timestamp (line 1 ONLY — a telemetry shard on
    line 2 must never make a live peer read as dead)."""
    try:
        with open(_hb_path(directory, process_id)) as f:
            return float(f.readline().strip())
    except (OSError, ValueError):
        return None


def read_heartbeat_payload(directory: str, process_id: int) -> Optional[dict]:
    """The peer's telemetry shard (line 2), or None: no file, no payload
    line, or a torn/partial JSON line — the last is skipped with a warning,
    never an exception (the aggregator's tolerance contract, ISSUE 10)."""
    try:
        with open(_hb_path(directory, process_id)) as f:
            f.readline()  # the timestamp line
            raw = f.readline().strip()
    except OSError:
        return None
    if not raw:
        return None
    try:
        shard = json.loads(raw)
    except ValueError:
        logger.warning(
            "heartbeat shard for process %d is torn mid-write; skipping "
            "this read (the next rewrite heals it)",
            process_id,
        )
        return None
    return shard if isinstance(shard, dict) else None


# The beat thread's shard feed: a zero-arg callable returning the freshest
# telemetry payload (or None). Module-level because the Heartbeat starts in
# spawn BEFORE the epoch driver builds its telemetry — RunTelemetry registers
# here once it exists, and every subsequent beat carries the shard.
_payload_fn = {"fn": None}


def set_heartbeat_payload(fn: Optional[Callable[[], Optional[dict]]]) -> None:
    _payload_fn["fn"] = fn


def _current_payload() -> Optional[dict]:
    fn = _payload_fn["fn"]
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — liveness must outlive telemetry
        logger.warning("heartbeat payload callback failed: %s", e)
        return None


class Heartbeat:
    """Daemon thread publishing this process's liveness file."""

    def __init__(self, directory: str, process_id: int, interval: float = 1.0):
        self.directory = directory
        self.process_id = int(process_id)
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Heartbeat":
        os.makedirs(self.directory, exist_ok=True)
        write_heartbeat(self.directory, self.process_id)  # beat before returning
        self._thread = threading.Thread(
            target=self._run, name="tpuddp-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if faults.is_hung():
                continue  # injected hang: look exactly like a dead peer
            try:
                # each beat carries the freshest telemetry shard (if a
                # publisher registered one) — liveness and aggregation ride
                # the same atomic rewrite
                write_heartbeat(
                    self.directory, self.process_id,
                    payload=_current_payload(),
                )
            except OSError as e:  # shared FS hiccup: log, keep beating
                logger.warning("heartbeat write failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def start(
    save_dir: Optional[str],
    process_id: int,
    num_processes: int,
    interval: float = 1.0,
) -> Optional[Tuple["Heartbeat", "Watchdog"]]:
    """Start this process's heartbeat + stale-peer watchdog pair — the wiring
    ``spawn.run_ddp_training`` uses on the multi-host path. Returns None (fully
    disabled) unless ``$TPUDDP_WATCHDOG_TIMEOUT`` is set, there are peers to
    watch, and a shared directory is resolvable (``$TPUDDP_HEARTBEAT_DIR`` or
    ``<save_dir>/.heartbeats``). Pass the pair to :func:`stop` on the way out."""
    timeout = watchdog_timeout_seconds()
    if timeout is None or num_processes <= 1:
        return None
    directory = heartbeat_dir(save_dir)
    if directory is None:
        logger.warning(
            "%s set but no heartbeat dir resolvable (no save_dir and no %s); "
            "watchdog disabled",
            _TIMEOUT_ENV,
            _DIR_ENV,
        )
        return None
    # a previous (possibly larger) run's leftover heartbeat files must not
    # poison this run's staleness verdicts: ids past the current world are
    # purged outright (they would never be rewritten), and check_once treats
    # beats that predate this watchdog as "no file yet" (startup grace)
    purge_stale_peers(directory, num_processes)
    hb = Heartbeat(directory, process_id, interval=interval).start()
    # heartbeat-lag telemetry: the stale-peer verdict lands in history.jsonl
    # as a typed event row, written by WHICHEVER process detected it (the
    # single-writer process-0 gate does not apply — process 0 may be the dead
    # one) and fsync'd before the exit that follows
    event_writer = None
    if save_dir is not None:
        from tpuddp.observability import MetricsWriter

        event_writer = MetricsWriter(save_dir, main_only=False)
    wd = Watchdog(
        directory, process_id, num_processes, timeout, event_writer=event_writer
    ).start()
    logger.info(
        "watchdog armed: %d-process heartbeat dir %s, timeout %.1fs",
        num_processes,
        directory,
        timeout,
    )
    return hb, wd


def stop(pair: Optional[Tuple["Heartbeat", "Watchdog"]]) -> None:
    """Tear down a :func:`start` pair (None-safe)."""
    if pair is None:
        return
    hb, wd = pair
    wd.stop()
    hb.stop()


class Watchdog:
    """Daemon thread that detects stale peers.

    ``action``: ``"exit"`` (os._exit(EXIT_WATCHDOG) — the only escape that
    works while the main thread is wedged GIL-free inside a collective),
    ``"raise"`` (interrupt the main thread; fine for interruptible waits), or
    a callable receiving ``[(peer_id, age_seconds), ...]``.
    """

    def __init__(
        self,
        directory: str,
        process_id: int,
        num_processes: int,
        timeout: float,
        action: Union[str, Callable] = "exit",
        interval: Optional[float] = None,
        event_writer=None,
    ):
        self.directory = directory
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.timeout = float(timeout)
        self.action = action
        self.interval = float(interval) if interval else max(0.25, self.timeout / 4.0)
        # observability.MetricsWriter (or None): stale-peer verdicts become
        # typed event records in history.jsonl before the exit
        self.event_writer = event_writer
        self.max_observed_lag = 0.0
        self._started_at = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self, now: Optional[float] = None) -> List[Tuple[int, float]]:
        """Stale peers as ``(peer_id, age_seconds)``. A peer with no file yet
        is only stale once the timeout has elapsed since the watchdog started
        (startup grace — peers finish rendezvous at slightly different times).
        A file whose beat PREDATES this watchdog is a leftover from a
        previous run in the same heartbeat_dir (e.g. an elastic resume) and
        gets the same startup grace — trusting it would kill a healthy
        resumed run the instant the watchdog armed."""
        now = time.time() if now is None else now
        started = self._started_at if self._started_at is not None else now
        stale = []
        for peer in range(self.num_processes):
            if peer == self.process_id:
                continue
            beat = read_heartbeat(self.directory, peer)
            if beat is not None and beat < started:
                beat = None  # a previous run's droppings: same as no file
            if beat is None:
                if now - started > self.timeout:
                    stale.append((peer, now - started))
            elif now - beat > self.timeout:
                stale.append((peer, now - beat))
            else:
                self.max_observed_lag = max(self.max_observed_lag, now - beat)
        return stale

    def start(self) -> "Watchdog":
        self._started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name="tpuddp-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                stale = self.check_once()
            except OSError as e:
                logger.warning("watchdog scan failed: %s", e)
                continue
            if stale:
                self._fire(stale)
                return

    def _fire(self, stale: List[Tuple[int, float]]) -> None:
        desc = ", ".join(f"process {p} ({age:.1f}s stale)" for p, age in stale)
        logger.critical(
            "watchdog: peer heartbeat stale past %.1fs — %s; a dead peer wedges "
            "every collective, so this process will not wait",
            self.timeout,
            desc,
        )
        if self.event_writer is not None:
            # the verdict as a typed history record, fsync'd before os._exit
            # (which skips every atexit/finally on purpose) can eat it
            try:
                from tpuddp.observability import make_run_meta, stamp

                path = self.event_writer.path
                if path is not None and (
                    not os.path.exists(path) or os.path.getsize(path) == 0
                ):
                    # this process died before any driver wrote the header
                    # (e.g. process 0 hung in rendezvous): the schema says
                    # run_meta comes first, and the post-mortem must still
                    # validate — write a minimal header before the event
                    self.event_writer.write(make_run_meta(
                        extra={"api": "watchdog", "process": self.process_id}
                    ))
                self.event_writer.write(stamp("event", {
                    "event": "watchdog_stale",
                    "process": self.process_id,
                    "timeout_s": self.timeout,
                    "stale_peers": [
                        {"process": p, "lag_s": round(age, 3)}
                        for p, age in stale
                    ],
                    "max_observed_lag_s": round(self.max_observed_lag, 3),
                }))
                self.event_writer.sync()
            except Exception:
                logger.exception("watchdog event record failed")
        # the crash flight recorder's exit-76 dump: the last windows/events
        # this process saw before it stopped waiting on the dead peer
        try:
            from tpuddp.observability import flight

            flight.dump_all("watchdog")
        except Exception:
            logger.exception("watchdog flight dump failed")
        if callable(self.action):
            self.action(stale)
        elif self.action == "raise":
            threading.interrupt_main()
        else:
            os._exit(EXIT_WATCHDOG)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
