"""Step-level numerical guard + cross-replica desync auditor (ISSUE 3).

PR 1 made tpuddp survive *process-level* failures and PR 2 compressed the
gradient wire; this module defends the *training math itself* — the two
silent killers neither layer sees:

1. **Non-finite gradient firewall** (``GuardConfig.enabled``): inside the
   compiled step, a cheap finiteness check on the *post-allreduce* gradient
   gates the optimizer update through ``lax.cond`` — the sum over replicas
   propagates any replica's NaN/Inf to every replica, so the predicate
   agrees by construction and a bad step becomes a bitwise no-op on
   params/opt-state/error-feedback residual, counted in
   ``TrainState.skipped_steps``.  The torch analog is a fused
   ``GradScaler``-style found-inf skip, minus the mixed-precision scaler.
   Cost model: one fused ``isfinite``-all reduction over the aggregated
   gradient per optimizer update (plus one scalar psum under
   weight-update sharding, whose shards must agree globally); config-off
   builds lower to the identical HLO as an unguarded build.

2. **Desync auditor** (:func:`audit_params`): a lightweight parameter-tree
   fingerprint — per-leaf chunked sums, reduced across the data axis via
   ``pmax - pmin == 0`` — the TPU-mesh analog of torch DDP's wrap-time
   ``_verify_params_across_processes`` and of veScale's first-class
   consistency contract (PAPERS.md).  Run at DDP wrap / Accelerator prepare
   time and every ``audit_every_n_epochs`` epochs; a divergent replica
   surfaces as :class:`ReplicaDesync` -> exit ``EXIT_DESYNC`` (77), the
   "requeue me into auto-resume" signal, or as a rollback to the last
   integrity-verified checkpoint when ``on_desync="rollback"``.  Cost model:
   ONE fingerprint reduction (a chunked-sum pass over the parameters plus a
   pmax/pmin pair on the small fingerprint vectors) per audit — nothing per
   step.

The third leg, **rollback-to-last-good**, lives in the epoch driver
(``training/loop.py``): when ``max_consecutive_skips`` is exceeded, or the
auditor trips with ``on_desync="rollback"``, the driver restores the newest
integrity-verified checkpoint, re-derives the data order for the redone
epoch (``set_epoch``), and records the rollback in ``history.jsonl``.
"""

from __future__ import annotations

import dataclasses
import difflib
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuddp.parallel.mesh import DATA_AXIS
from tpuddp.resilience.preemption import EXIT_DESYNC
from tpuddp.utils.compat import shard_map

_ON_DESYNC = ("exit", "rollback")


class ReplicaDesync(RuntimeError):
    """Raised when the auditor finds a parameter leaf whose per-replica
    fingerprints disagree (or went non-finite). ``spawn.run_ddp_training``
    converts it into ``sys.exit(EXIT_DESYNC)`` (77) so a scheduler can
    requeue the run into auto-resume."""

    def __init__(self, leaf: str, where: str = "audit"):
        self.leaf = leaf
        self.where = where
        super().__init__(
            f"cross-replica desync at {where}: parameter leaf {leaf!r} differs "
            "between replicas (or is non-finite on all of them); exit "
            f"{EXIT_DESYNC} requeues into auto-resume"
        )


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """The ``training.guard`` block. ``enabled=False`` (the default) is a
    strict no-op: the step builders take the exact pre-guard code path and
    lower to the identical HLO."""

    enabled: bool = False
    # rollback to the last intact checkpoint once MORE than this many
    # consecutive optimizer updates were skipped by the firewall (a single
    # cosmic-ray step rides through; a persistently-poisoned stream doesn't)
    max_consecutive_skips: int = 3
    # run the desync auditor at the start of every Nth epoch (None: only at
    # wrap/prepare time — the torch _verify_params_across_processes moment)
    audit_every_n_epochs: Optional[int] = None
    on_desync: str = "exit"  # or "rollback" (needs checkpoints in save_dir)
    # rollback-loop bound: after this many rollbacks the run raises instead
    # of replaying a poisoned epoch forever
    max_rollbacks: int = 2


DISABLED = GuardConfig()

_GUARD_KEYS = {f.name for f in dataclasses.fields(GuardConfig)}


def resolve_guard(raw: Any) -> GuardConfig:
    """Parse the ``training.guard`` knob: None/False -> disabled, True -> all
    defaults, a mapping -> overrides (unknown keys refused with a
    did-you-mean hint, same contract as ``config.training_config``), an
    existing :class:`GuardConfig` -> itself."""
    if raw is None or raw is False:
        return DISABLED
    if isinstance(raw, GuardConfig):
        return raw
    if raw is True:
        return GuardConfig(enabled=True)
    if not isinstance(raw, dict):
        raise ValueError(
            f"training.guard must be a bool or a mapping, got {type(raw).__name__}"
        )
    unknown = set(raw) - _GUARD_KEYS
    if unknown:
        hints = []
        for k in sorted(unknown):
            close = difflib.get_close_matches(k, _GUARD_KEYS, n=1)
            hints.append(f"{k!r}" + (f" (did you mean {close[0]!r}?)" if close else ""))
        raise ValueError(
            f"unknown training.guard key(s): {', '.join(hints)}. Known keys: "
            f"{sorted(_GUARD_KEYS)}"
        )
    cfg = dict(raw)
    cfg.setdefault("enabled", True)  # writing the block means wanting it on
    out = GuardConfig(**cfg)
    if out.on_desync not in _ON_DESYNC:
        raise ValueError(
            f"training.guard.on_desync must be one of {_ON_DESYNC}, got "
            f"{out.on_desync!r}"
        )
    if out.max_consecutive_skips < 0:
        raise ValueError("training.guard.max_consecutive_skips must be >= 0")
    if out.audit_every_n_epochs is not None and int(out.audit_every_n_epochs) < 1:
        raise ValueError("training.guard.audit_every_n_epochs must be >= 1")
    return out


# ------------------------------------------------------- skipped counters --


def init_skip_counters():
    """Zeros for ``TrainState.skipped_steps``: total skips since init (the
    monotone record that checkpoints) and the consecutive-run length the
    rollback policy watches (reset by every applied update)."""
    return {
        "total": jnp.zeros((), jnp.int32),
        "consecutive": jnp.zeros((), jnp.int32),
    }


def bump_skip_counters(skipped):
    """The skip branch's counter update (in-jit): total and the consecutive
    run both advance."""
    return {
        "total": skipped["total"] + 1,
        "consecutive": skipped["consecutive"] + 1,
    }


def reset_consecutive(skipped):
    """The apply branch's counter update (in-jit): an applied update ends
    any consecutive-skip run."""
    return {
        "total": skipped["total"],
        "consecutive": jnp.zeros((), jnp.int32),
    }


def read_skip_counters(state) -> Tuple[int, int]:
    """Host ``(total, consecutive)`` of a state's skip counters; (0, 0) for
    unguarded states. One tiny fetch — the epoch driver calls it once per
    epoch, never per step."""
    counters = getattr(state, "skipped_steps", None)
    if counters is None:
        return 0, 0
    total, consec = jax.device_get((counters["total"], counters["consecutive"]))
    return int(total), int(consec)


def tree_all_finite(tree):
    """ONE fused finiteness reduction over a pytree: scalar bool, True iff
    every element of every leaf is finite."""
    leaves = jax.tree_util.tree_leaves(tree)
    ok = jnp.bool_(True)
    for leaf in leaves:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


# --------------------------------------------------------- desync auditor --

_FP_CHUNK = 4096  # fingerprint granularity: chunked sums localize a
# divergence to a ~16 KB span without carrying O(params) audit output


def _leaf_fingerprint(leaf):
    flat = jnp.ravel(leaf).astype(jnp.float32)
    pad = (-flat.size) % _FP_CHUNK
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return jnp.sum(flat.reshape(-1, _FP_CHUNK), axis=1)


def _make_audit_check(mesh):
    from tpuddp.parallel.mesh import data_axes

    axis = data_axes(mesh)  # the flat "data" axis (also on a 2-D
    # ("data", "model") mesh — TP shards are compared across data replicas
    # ONLY), or the factored ("host", "local") tuple on a hierarchical mesh

    def check(tree):
        fp = jax.tree_util.tree_map(_leaf_fingerprint, tree)
        # identical replicas <=> pmax == pmin elementwise. NaN params poison
        # the subtraction into NaN != 0 — a non-finite parameter tree is
        # reported too (it is never a state worth training on).
        return jax.tree_util.tree_map(
            lambda v: lax.pmax(v, axis) - lax.pmin(v, axis), fp
        )

    return check


@functools.lru_cache(maxsize=8)
def _audit_program(mesh):
    """The compiled fingerprint-and-compare pass for ``mesh`` (cached per
    mesh; jax.jit then caches per parameter tree structure, so repeated
    audits on the same model never recompile)."""
    return jax.jit(
        shard_map(
            _make_audit_check(mesh), mesh=mesh, in_specs=(P(),),
            out_specs=P(), check_vma=False,
        )
    )


def _tp_audit_program(mesh, specs):
    """The tensor-parallel variant: ``specs`` is the parameter tree's
    PartitionSpec pytree (model-axis shards), so every device fingerprints
    its OWN shard and the pmax-pmin compare runs across DATA replicas only —
    a TP shard legitimately differs from its model-axis neighbor and must
    never be convicted for it. The per-shard diff vectors are exposed per
    model index (out spec over the model axis), so a divergence on ANY
    shard group is visible from the host. Built per call — audits run once
    per wrap plus every guard.audit_every_n_epochs, never per step."""
    from tpuddp.parallel.mesh2d import MODEL_AXIS

    out_spec = jax.tree_util.tree_map(lambda _: P(MODEL_AXIS), specs)
    return jax.jit(
        shard_map(
            _make_audit_check(mesh), mesh=mesh, in_specs=(specs,),
            out_specs=out_spec, check_vma=False,
        )
    )


def audit_params(mesh, params, specs=None) -> Optional[str]:
    """Compare every replica's copy of (nominally replicated) ``params``.

    Returns the keystr path of the FIRST divergent leaf, or None when all
    replicas hold bitwise-agreeing fingerprints. Each device hashes its own
    local copy of the buffer, so single-device corruption of a replicated
    array (bad host, bit flip, desynced update) is visible even though JAX
    treats the array as one logical value.

    ``specs`` (a PartitionSpec pytree, the TP wrap's ``tp_param_specs``)
    marks model-axis-sharded parameters on a 2-D mesh: fingerprints then
    cover each device's own shard and the comparison runs across data
    replicas only.
    """
    program = (
        _audit_program(mesh) if specs is None else _tp_audit_program(mesh, specs)
    )
    diffs = program(params)
    flat = jax.tree_util.tree_flatten_with_path(diffs)[0]
    # ONE host fetch for every (small) per-leaf diff vector
    host = jax.device_get([d for _, d in flat])
    for (path, _), diff in zip(flat, host):
        bad = np.asarray(diff)
        if np.any(bad != 0) or not np.all(np.isfinite(bad)):
            return jax.tree_util.keystr(path)
    return None


def audit_or_raise(mesh, params, where: str, specs=None) -> None:
    """Run :func:`audit_params`; raise :class:`ReplicaDesync` naming the
    first divergent leaf. The wrap-time entry point (DDP init_state /
    Accelerator prepare). ``specs`` as in :func:`audit_params`."""
    leaf = audit_params(mesh, params, specs=specs)
    if leaf is not None:
        raise ReplicaDesync(leaf, where=where)
